"""Paged (block-table) KV cache + ragged decode attention.

Parity role: reference decode serving is a contiguous per-request KV
workspace (``inference_context.h`` KV-cache workspace management).  The
TPU-native upgrade is a *paged* cache — fixed-size pages shared across
sequences through per-sequence block tables (vLLM/ragged-paged-attention
style, cf. PAPERS.md) — which removes max-length over-allocation and lets
sequences of very different lengths batch together.

Layout:
  k_pages/v_pages: [num_pages, Hkv, page_size, D] — the physical pool
  (seq on sublanes, D on lanes — the layout Mosaic tiles natively)
  block_tables:    [B, max_pages_per_seq] int32 — page ids per sequence
  lengths:         [B] int32 — tokens currently stored per sequence

Two compute paths behind one API: the fused ragged Pallas kernel
(``ops/pallas/ragged_paged_attention.py`` — the K/V index maps read the
block table so only each sequence's own pages are DMA'd, and one launch
serves a mixed prefill+decode batch) on TPU, and this module's jnp
gather + masked softmax as the oracle/fallback.
``resolve_attention_backend`` maps the ``serving.attention_backend``
config strings onto the pair.  Page allocation is host-side
(``PagedAllocator``) because it is control flow, not compute.
"""

import math
from collections import OrderedDict
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class PagedKVCache(NamedTuple):
    k_pages: jnp.ndarray   # [P, Hkv, page, D]
    v_pages: jnp.ndarray


# public vocabulary for serving.attention_backend (docs/config-json.md)
ATTENTION_BACKENDS = ("auto", "jnp", "pallas", "pallas-interpret")


def resolve_attention_backend(backend):
    """Map a ``serving.attention_backend`` string to (impl, interpret).

    ``impl`` is what ``use_pallas`` consumes (None = auto: Pallas on TPU,
    jnp elsewhere); ``interpret`` forces the Pallas kernel through the
    interpreter so CPU CI can run the exact kernel path bit-for-bit."""
    if backend is None or backend == "auto":
        return None, False
    if backend == "pallas-interpret":
        return "pallas", True
    if backend in ("jnp", "pallas"):
        return backend, False
    raise ValueError(f"unknown attention backend {backend!r}; "
                     f"expected one of {ATTENTION_BACKENDS}")


def init_paged_cache(num_pages, page_size, n_kv_heads, head_dim,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    shape = (num_pages, n_kv_heads, page_size, head_dim)
    return PagedKVCache(k_pages=jnp.zeros(shape, dtype),
                        v_pages=jnp.zeros(shape, dtype))


def append_paged(cache: PagedKVCache, block_tables, lengths, k_new, v_new
                 ) -> Tuple[PagedKVCache, jnp.ndarray]:
    """Append ONE token per sequence (decode step).

    k_new/v_new: [B, 1, Hkv, D].  Returns (cache, new lengths).  The pages
    written must already be mapped in ``block_tables`` (allocator's job).
    """
    B = k_new.shape[0]
    page_size = cache.k_pages.shape[2]
    page_idx = jnp.take_along_axis(
        block_tables, (lengths // page_size)[:, None], axis=1)[:, 0]
    offset = lengths % page_size
    k = cache.k_pages.at[page_idx, :, offset].set(
        k_new[:, 0].astype(cache.k_pages.dtype))
    v = cache.v_pages.at[page_idx, :, offset].set(
        v_new[:, 0].astype(cache.v_pages.dtype))
    return PagedKVCache(k_pages=k, v_pages=v), lengths + 1


def prefill_paged(cache: PagedKVCache, block_tables, lengths, k_new, v_new
                  ) -> Tuple[PagedKVCache, jnp.ndarray]:
    """Write a whole prompt [B, T, Hkv, D] starting at ``lengths`` (which is
    typically zero)."""
    B, T = k_new.shape[:2]
    page_size = cache.k_pages.shape[2]
    pos = lengths[:, None] + jnp.arange(T)[None, :]          # [B, T]
    page_idx = jnp.take_along_axis(block_tables, pos // page_size, axis=1)
    offset = pos % page_size
    # advanced indices (page_idx, offset) around the ':' slice put their
    # broadcast dims first: the set value is [B, T, Hkv, D] = k_new's layout
    k = cache.k_pages.at[page_idx, :, offset].set(
        k_new.astype(cache.k_pages.dtype))
    v = cache.v_pages.at[page_idx, :, offset].set(
        v_new.astype(cache.v_pages.dtype))
    return PagedKVCache(k_pages=k, v_pages=v), lengths + T


def paged_decode_attention(q, cache: PagedKVCache, block_tables, lengths,
                           softmax_scale: Optional[float] = None,
                           impl: Optional[str] = None,
                           interpret: bool = False,
                           logit_softcap: Optional[float] = None,
                           backend: Optional[str] = None):
    """q: [B, T, H, D] — the last T tokens of each sequence (T=1 decode).

    ``impl``: None (auto: Pallas kernel on TPU, jnp elsewhere), "pallas",
    or "jnp"; ``backend`` is the serving-config spelling ("auto" | "jnp" |
    "pallas" | "pallas-interpret") and overrides ``impl``/``interpret``
    when given.  The Pallas path is the fused ragged kernel
    (``ops/pallas/ragged_paged_attention.py``); the jnp path gathers each
    sequence's pages into its logical view and runs masked attention over
    the valid ragged prefix — it is the oracle the kernel is tested
    against.  ``logit_softcap`` is jnp-only and forces the fallback."""
    from deepspeed_tpu.ops.decode_attention import use_pallas
    if backend is not None:
        impl, forced = resolve_attention_backend(backend)
        interpret = interpret or forced
    if use_pallas(impl) and not logit_softcap:
        from deepspeed_tpu.ops.pallas.ragged_paged_attention import \
            ragged_paged_attention_rect
        return ragged_paged_attention_rect(q, cache.k_pages, cache.v_pages,
                                           block_tables, lengths,
                                           softmax_scale=softmax_scale,
                                           interpret=interpret)
    B, T, H, D = q.shape
    Hkv = cache.k_pages.shape[1]
    page_size = cache.k_pages.shape[2]
    max_pages = block_tables.shape[1]
    S = max_pages * page_size

    # [B, max_pages, Hkv, page, D] → [B, Hkv, S, D]
    k = jnp.swapaxes(cache.k_pages[block_tables], 1, 2) \
        .reshape(B, Hkv, S, D)
    v = jnp.swapaxes(cache.v_pages[block_tables], 1, 2) \
        .reshape(B, Hkv, S, D)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if logit_softcap:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)
    kpos = jnp.arange(S)[None, None, :]                       # [1, 1, S]
    qpos = (lengths[:, None] - T + jnp.arange(T)[None, :])[..., None]
    mask = kpos <= qpos                                       # [B, T, S]
    logits = jnp.where(mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)   # impl-independent output dtype


class PageAllocationError(RuntimeError):
    """Typed allocator failure (pool exhausted, per-sequence cap exceeded,
    or an injected ``page_alloc`` fault): callers turn it into a structured
    rejection / retry instead of an engine-killing assert."""


class PagedAllocator:
    """Host-side page bookkeeping (the control-flow half of vLLM's block
    manager): per-sequence page lists over a fixed pool, with free-list
    reuse.

    Pages are REFCOUNTED so the prefix cache
    (``inference/prefix_cache.py``) can attach one physical page to many
    sequences' block tables: ``allocate(..., shared=pages)`` bumps the
    shared pages' refcounts instead of taking fresh ones, and a page only
    returns to circulation when its last reference drops.  Pages the cache
    has registered (``mark_cached``) don't go back to the free list on
    release — they park in an LRU "reclaimable" tier, still holding their
    KV content for future hits, and are evicted back into the free list
    (oldest first, ``evict_hook`` notified so the cache can drop its index
    entries) only when an allocation outgrows the free list.  With no
    cache layered on top every refcount is 1 and the reclaimable tier
    stays empty — the original allocator semantics."""

    def __init__(self, num_pages: int, page_size: int,
                 max_pages_per_seq: int, reserve_scratch: bool = False,
                 injector=None):
        """``reserve_scratch``: keep page 0 out of the pool — serving
        engines point INACTIVE batch slots' tables at page 0 so their
        dummy-token writes land in a sacrificial page.  ``injector``: a
        ``runtime.resilience.FaultInjector`` consulted at the ``page_alloc``
        site before any page leaves the free list (so an injected fault
        never half-allocates)."""
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self.scratch_reserved = bool(reserve_scratch)
        self.free: List[int] = list(range(1 if reserve_scratch else 0,
                                          num_pages))
        self.seq_pages = {}
        self.injector = injector
        self.ref = {}                       # page -> live-sequence refcount
        self.cached = set()                 # pages the prefix cache indexed
        self.reclaimable = OrderedDict()    # ref==0 cached pages, LRU order
        self.evict_hook = None              # called with each evicted page
        self.pages_taken = 0                # fresh pages handed out (stats)
        self.reclaim_evictions = 0          # reclaimable pages surrendered

    def can_allocate(self, n_pages: int) -> bool:
        return self.available_page_count >= n_pages

    @property
    def free_page_count(self) -> int:
        return len(self.free)

    @property
    def available_page_count(self) -> int:
        """Pages an allocation can actually obtain: the free list plus the
        reclaimable tier (cached pages evictable on demand)."""
        return len(self.free) + len(self.reclaimable)

    # -- refcount plumbing ----------------------------------------------
    def _ref_page(self, page: int):
        self.ref[page] = self.ref.get(page, 0) + 1
        self.reclaimable.pop(page, None)

    def _release_page(self, page: int):
        n = self.ref.get(page, 1) - 1
        if n > 0:
            self.ref[page] = n
            return
        self.ref.pop(page, None)
        if page in self.cached:
            # most-recently-used end; evictions pop from the other side
            self.reclaimable[page] = None
            self.reclaimable.move_to_end(page)
        else:
            self.free.append(page)

    def _take_page(self) -> int:
        """One fresh page: free list first, then evict the LRU reclaimable
        page (its cache index entries die via ``evict_hook``)."""
        if self.free:
            page = self.free.pop()
        else:
            page = self.evict_reclaimable()
            if page is None:
                raise PageAllocationError("out of KV pages: free list and "
                                          "reclaimable tier both empty")
        self.ref[page] = 1
        self.pages_taken += 1
        return page

    def evict_reclaimable(self) -> Optional[int]:
        """Evict the least-recently-used reclaimable page back toward the
        caller (None when the tier is empty).  The page leaves the cached
        set and the hook lets the prefix cache unindex it."""
        if not self.reclaimable:
            return None
        page, _ = self.reclaimable.popitem(last=False)
        self.cached.discard(page)
        self.reclaim_evictions += 1
        if self.evict_hook is not None:
            self.evict_hook(page)
        return page

    def reclaim_to_free(self) -> Optional[int]:
        """Evict the LRU reclaimable page straight onto the free list (the
        prefix cache's capacity enforcement); None when none evictable."""
        page = self.evict_reclaimable()
        if page is not None:
            self.free.append(page)
        return page

    def mark_cached(self, page: int):
        """The prefix cache indexed this page: on last release it parks in
        the reclaimable tier instead of returning to the free list."""
        self.cached.add(page)

    def unmark_cached(self, page: int):
        """Drop cache status; if the page is parked reclaimable it returns
        to the free list immediately."""
        self.cached.discard(page)
        if page in self.reclaimable:
            del self.reclaimable[page]
            self.free.append(page)

    def _check_injector(self):
        if self.injector is not None:
            try:
                self.injector.check("page_alloc")
            except Exception as e:
                raise PageAllocationError(
                    f"injected page_alloc fault: {e}") from e

    def allocate(self, seq_id, n_tokens: int, shared=(),
                 protect=()) -> List[int]:
        """Pages for ``n_tokens``, reusing ``shared`` cached pages (in
        order) as the sequence's leading pages — their refcounts bump
        instead of fresh pages being taken.  ``protect`` pages are pinned
        for the duration of the call so the reclaim-tier eviction that
        feeds fresh pages can never surrender them (the serving engine
        pins a copy-on-write source page this way).  All feasibility
        checks and the injected-fault site run BEFORE any state mutates,
        so a ``PageAllocationError`` never leaks a refcount or
        half-attaches a page."""
        shared = list(shared)
        need = -(-n_tokens // self.page_size)
        if need > self.max_pages_per_seq:
            raise PageAllocationError(
                f"{n_tokens} tokens exceed max_pages_per_seq "
                f"({self.max_pages_per_seq})")
        if len(shared) > need:
            raise PageAllocationError(
                f"{len(shared)} shared pages exceed the {need}-page "
                f"reservation for {n_tokens} tokens")
        fresh_needed = need - len(shared)
        # shared/protected pages parked in the reclaimable tier are about
        # to be pinned — they can't feed this allocation's fresh pages
        pinned = set(shared) | set(protect)
        evictable = sum(1 for p in self.reclaimable if p not in pinned)
        if fresh_needed > len(self.free) + evictable:
            raise PageAllocationError(
                f"out of KV pages: need {fresh_needed}, free "
                f"{len(self.free)} (+{evictable} reclaimable)")
        self._check_injector()
        for p in protect:
            self._ref_page(p)
        try:
            for p in shared:
                self._ref_page(p)
            pages = shared + [self._take_page() for _ in range(fresh_needed)]
        finally:
            for p in protect:
                self._release_page(p)
        self.seq_pages[seq_id] = pages
        return pages

    def extend(self, seq_id, total_tokens: int) -> List[int]:
        """Ensure ``seq_id`` has pages for ``total_tokens``; allocates new
        pages as it crosses page boundaries."""
        pages = self.seq_pages[seq_id]
        need = -(-total_tokens // self.page_size)
        if need > self.max_pages_per_seq:
            raise PageAllocationError(
                f"{total_tokens} tokens exceed max_pages_per_seq "
                f"({self.max_pages_per_seq})")
        if len(pages) < need:
            if not self.can_allocate(need - len(pages)):
                raise PageAllocationError(
                    f"out of KV pages: need {need - len(pages)} more, "
                    f"free {len(self.free)}")
            self._check_injector()
            while len(pages) < need:
                pages.append(self._take_page())
        return pages

    def shrink(self, seq_id, total_tokens: int):
        """Release pages beyond what ``total_tokens`` needs (a bucketed
        prefill over-allocates to the padded length, then trims)."""
        pages = self.seq_pages[seq_id]
        need = max(1, -(-total_tokens // self.page_size))
        while len(pages) > need:
            self._release_page(pages.pop())

    def free_sequence(self, seq_id):
        for page in self.seq_pages.pop(seq_id, []):
            self._release_page(page)

    def audit(self) -> dict:
        """Refcount/accounting invariants; {} when clean.  Every page is
        exactly one of: free, reclaimable (cached, ref 0), or referenced
        (ref == number of sequences holding it); totals balance against
        the pool."""
        problems = {}
        held = {}
        for pages in self.seq_pages.values():
            for p in pages:
                held[p] = held.get(p, 0) + 1
        if held != self.ref:
            dangling = {p: n for p, n in self.ref.items()
                        if held.get(p) != n}
            unrefed = {p: n for p, n in held.items()
                       if self.ref.get(p) != n}
            problems["refcounts"] = {"dangling": dangling,
                                     "unreferenced_held": unrefed}
        overlap = (set(self.free) & set(self.reclaimable)) | \
                  (set(self.free) & set(self.ref)) | \
                  (set(self.reclaimable) & set(self.ref))
        if overlap:
            problems["tier_overlap"] = sorted(overlap)
        pool = self.num_pages - (1 if self.scratch_reserved else 0)
        total = len(self.free) + len(self.reclaimable) + len(self.ref)
        if total != pool:
            problems["page_accounting"] = {
                "free": len(self.free), "reclaimable": len(self.reclaimable),
                "referenced": len(self.ref), "pool": pool}
        if not self.cached >= set(self.reclaimable):
            problems["uncached_reclaimable"] = sorted(
                set(self.reclaimable) - self.cached)
        return problems

    def block_table(self, seq_ids) -> np.ndarray:
        """[B, max_pages_per_seq] table (0-padded) for the given batch."""
        out = np.zeros((len(seq_ids), self.max_pages_per_seq), np.int32)
        for b, sid in enumerate(seq_ids):
            pages = self.seq_pages[sid]
            out[b, :len(pages)] = pages
        return out
