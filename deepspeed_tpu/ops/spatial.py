"""Spatial (diffusers/UNet) inference ops.

Parity: reference ``csrc/spatial/csrc/opt_bias_add.cu`` (``nhwc_bias_add``,
``nhwc_bias_add_add``, ``nhwc_bias_add_bias_add`` — fused NHWC bias/residual
adds for Stable-Diffusion UNet/VAE).

TPU design: jnp expressions — XLA fuses them into the surrounding convs;
NHWC is already TPU's preferred conv layout.  Provided for API parity and
as the op_builder "spatial_inference" surface.
"""

import jax.numpy as jnp


def nhwc_bias_add(activation, bias):
    """activation [N,H,W,C] + bias [C]."""
    return activation + bias.astype(activation.dtype)


def nhwc_bias_add_add(activation, bias, other):
    return activation + bias.astype(activation.dtype) + other


def nhwc_bias_add_bias_add(activation, bias, other, other_bias):
    return (activation + bias.astype(activation.dtype) + other +
            other_bias.astype(activation.dtype))


reference_impl = nhwc_bias_add
