"""Async file I/O for NVMe offload (ZeRO-Infinity swap engine).

Parity: reference ``csrc/aio/py_lib`` (``aio_handle`` with
pread/pwrite/sync_/async_/wait + pinned-tensor manager over a libaio
O_DIRECT submission queue drained by ``deepspeed_aio_thread.cpp``).

TPU design: the swap target is the TPU-VM host NVMe.  ``AsyncIOHandle``
reproduces the handle API over a raw-syscall **io_uring** engine
(``csrc/aio.cpp``): async ops are real kernel submissions with
``queue_depth`` in flight (large transfers are chunked into ``block_size``
submissions so one tensor saturates the queue), buffers from
``new_cpu_locked_tensor`` are 4k-aligned and mlock'd, and O_DIRECT is used
whenever alignment allows.  When io_uring is unavailable (seccomp'd
container, old kernel) the same surface degrades to the blocking C++
pread/pwrite core on a Python thread pool, and finally to pure-Python
file I/O — the swapper state machines in ``runtime/zero/offload.py``
behave identically on every tier.
"""

import concurrent.futures as cf
import ctypes
import os
from typing import Dict, List, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger

_CPP_SRC = os.path.join(os.path.dirname(__file__), "csrc", "aio.cpp")
_lib = None
_lib_tried = False


def _load_native():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        from deepspeed_tpu.ops.native import load_extension
        lib = load_extension("aio", [_CPP_SRC], extra_ldflags=["-lpthread"])
        lib.ds_pread.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                 ctypes.c_long, ctypes.c_long, ctypes.c_int]
        lib.ds_pread.restype = ctypes.c_long
        lib.ds_pwrite.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                  ctypes.c_long, ctypes.c_long, ctypes.c_int]
        lib.ds_pwrite.restype = ctypes.c_long
        lib.ds_aio_create.argtypes = [ctypes.c_int]
        lib.ds_aio_create.restype = ctypes.c_void_p
        for f in (lib.ds_aio_submit_read, lib.ds_aio_submit_write):
            f.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_long, ctypes.c_long]
            f.restype = ctypes.c_long
        lib.ds_aio_drain.argtypes = [ctypes.c_void_p]
        lib.ds_aio_drain.restype = ctypes.c_long
        lib.ds_aio_inflight.argtypes = [ctypes.c_void_p]
        lib.ds_aio_inflight.restype = ctypes.c_long
        lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
        lib.ds_aio_destroy.restype = None
        lib.ds_alloc_pinned.argtypes = [ctypes.c_long]
        lib.ds_alloc_pinned.restype = ctypes.c_void_p
        lib.ds_free_pinned.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.ds_free_pinned.restype = None
        _lib = lib
    except Exception as e:
        logger.warning(f"aio native build unavailable, python fallback: {e}")
        _lib = None
    return _lib


class AsyncIOHandle:
    """Parity surface of reference ``deepspeed_py_aio_handle.h``:
    sync_pread/sync_pwrite, async_pread/async_pwrite + wait,
    new_cpu_locked_tensor/free_cpu_locked_tensor."""

    def __init__(self, block_size=1048576, queue_depth=8, single_submit=False,
                 overlap_events=True, thread_count=4):
        self._block_size = block_size
        self._queue_depth = queue_depth
        self._thread_count = thread_count
        self._pool = None           # lazy: only the fallback tier needs it
        self._pending: List[cf.Future] = []
        self._inflight_bufs: List[np.ndarray] = []
        self._reqs = 0              # async requests since last wait()
        self._pinned: Dict[int, Tuple[int, int]] = {}   # id -> (ptr, nbytes)
        self._engine = None
        lib = _load_native()
        if lib is not None:
            eng = lib.ds_aio_create(ctypes.c_int(queue_depth))
            self._engine = eng or None
            if self._engine is None:
                logger.warning("io_uring unavailable (seccomp/kernel); "
                               "aio falls back to the thread-pool tier")

    def __del__(self):
        try:
            if self._engine is not None and _lib is not None:
                _lib.ds_aio_destroy(self._engine)
                self._engine = None
        except Exception:
            pass

    # ---- introspection parity ------------------------------------
    def get_block_size(self):
        return self._block_size

    def get_queue_depth(self):
        return self._queue_depth

    def get_thread_count(self):
        return self._thread_count

    def uses_io_uring(self):
        return self._engine is not None

    # ---- blocking core (sync ops + fallback tier) ----------------
    @staticmethod
    def _do_read(buffer: np.ndarray, filename: str, offset: int = 0):
        lib = _load_native()
        nbytes = buffer.nbytes
        if lib is not None:
            got = lib.ds_pread(filename.encode(),
                               buffer.ctypes.data_as(ctypes.c_void_p),
                               ctypes.c_long(nbytes), ctypes.c_long(offset),
                               ctypes.c_int(0))
            assert got == nbytes, f"short read {got}/{nbytes} from {filename}"
            return got
        with open(filename, "rb") as f:
            f.seek(offset)
            data = f.read(nbytes)
        assert len(data) == nbytes, f"short read from {filename}"
        buffer.view(np.uint8).reshape(-1)[:] = np.frombuffer(data, np.uint8)
        return nbytes

    @staticmethod
    def _do_write(buffer: np.ndarray, filename: str, offset: int = 0):
        lib = _load_native()
        nbytes = buffer.nbytes
        buf = np.ascontiguousarray(buffer)
        if lib is not None:
            put = lib.ds_pwrite(filename.encode(),
                                buf.ctypes.data_as(ctypes.c_void_p),
                                ctypes.c_long(nbytes), ctypes.c_long(offset),
                                ctypes.c_int(0))
            assert put == nbytes, f"short write {put}/{nbytes} to {filename}"
            return put
        mode = "r+b" if os.path.exists(filename) else "wb"
        with open(filename, mode) as f:
            f.seek(offset)
            f.write(buf.tobytes())
        return nbytes

    def sync_pread(self, buffer, filename, offset=0):
        return self._do_read(np.asarray(buffer), filename, offset)

    def sync_pwrite(self, buffer, filename, offset=0):
        return self._do_write(np.asarray(buffer), filename, offset)

    # ---- async ops -----------------------------------------------
    def _submit_chunks(self, arr: np.ndarray, filename: str, offset: int,
                      write: bool):
        """Submit one transfer as block_size io_uring chunks so a single
        large tensor fills the queue depth (the reference splits requests
        across its aio threads the same way)."""
        lib = _lib
        submit = lib.ds_aio_submit_write if write else lib.ds_aio_submit_read
        flat = arr.view(np.uint8).reshape(-1)
        base = flat.ctypes.data
        nbytes = flat.nbytes
        fname = filename.encode()
        # keep-alive BEFORE any chunk is in flight: a mid-transfer submit
        # failure must not let numpy free memory the kernel is DMA-ing into
        self._inflight_bufs.append(arr)
        self._reqs += 1
        pos = 0
        while pos < nbytes:
            n = min(self._block_size, nbytes - pos)
            rc = submit(self._engine, fname,
                        ctypes.c_void_p(base + pos),
                        ctypes.c_long(n), ctypes.c_long(offset + pos))
            if rc < 0:
                raise OSError(-rc, f"io_uring submit failed for {filename}")
            pos += n

    def async_pread(self, buffer, filename, offset=0):
        arr = np.asarray(buffer)
        if self._engine is not None and arr.flags.c_contiguous:
            # write path needs the file to exist only at completion; read
            # chunks can complete out of order — both fine for swap blobs
            self._submit_chunks(arr, filename, offset, write=False)
            return 0
        if self._pool is None:
            self._pool = cf.ThreadPoolExecutor(max_workers=self._thread_count)
        self._pending.append(
            self._pool.submit(self._do_read, arr, filename, offset))
        return 0

    def async_pwrite(self, buffer, filename, offset=0):
        arr = np.asarray(buffer)
        if self._engine is not None and arr.flags.c_contiguous:
            self._submit_chunks(arr, filename, offset, write=True)
            return 0
        if self._pool is None:
            self._pool = cf.ThreadPoolExecutor(max_workers=self._thread_count)
        self._pending.append(
            self._pool.submit(self._do_write, np.ascontiguousarray(arr),
                              filename, offset))
        return 0

    # parity aliases
    read = sync_pread
    write = sync_pwrite
    pread = sync_pread
    pwrite = sync_pwrite

    def wait(self):
        """Block until every async request completes; returns the number of
        completed REQUESTS (one per async_pread/async_pwrite call — the
        reference aio_handle counts the same way on every tier)."""
        n = 0
        if self._engine is not None:
            done = _lib.ds_aio_drain(self._engine)
            if done < 0:
                self._reqs = 0
                raise OSError(-done, "io_uring drain failed")
            n += self._reqs
            self._reqs = 0
            self._inflight_bufs.clear()
        for fut in self._pending:
            fut.result()
            n += 1
        self._pending = []
        return n

    # ---- pinned buffers ------------------------------------------
    def new_cpu_locked_tensor(self, num_elem, dtype=np.float32):
        """4k-aligned, mlock'd host buffer (true pinned memory — the
        reference's deepspeed_pin_tensor_t).  Falls back to plain numpy
        when the native library is unavailable."""
        dtype = np.dtype(dtype)
        nbytes = int(num_elem) * dtype.itemsize
        lib = _load_native()
        if lib is not None:
            ptr = lib.ds_alloc_pinned(ctypes.c_long(nbytes))
            if ptr:
                cbuf = (ctypes.c_char * nbytes).from_address(ptr)
                arr = np.frombuffer(cbuf, dtype=dtype, count=int(num_elem))
                self._pinned[id(arr)] = (ptr, nbytes)
                return arr
        arr = np.zeros(int(num_elem), dtype=dtype)
        self._pinned[id(arr)] = (0, nbytes)
        return arr

    def free_cpu_locked_tensor(self, tensor):
        ptr, nbytes = self._pinned.pop(id(tensor), (0, 0))
        if ptr and _lib is not None:
            _lib.ds_free_pinned(ctypes.c_void_p(ptr), ctypes.c_long(nbytes))


def aio_read(buffer, filename, **kw):
    return AsyncIOHandle()._do_read(np.asarray(buffer), filename)


def aio_write(buffer, filename, **kw):
    return AsyncIOHandle()._do_write(np.asarray(buffer), filename)


reference_impl = AsyncIOHandle
