"""Async file I/O for NVMe offload (ZeRO-Infinity swap engine).

Parity: reference ``csrc/aio/py_lib`` (``aio_handle`` with
pread/pwrite/sync_/async_/wait + pinned-tensor manager over libaio O_DIRECT).

TPU design: the swap target is the TPU-VM host NVMe.  ``AsyncIOHandle``
reproduces the handle API with a C++ pread/pwrite core (O_DIRECT,
thread-pool; built lazily from ``csrc/aio.cpp``) and a pure-Python
thread-pool fallback — either way the Python surface is identical and the
swapper state machines in ``runtime/zero/offload.py`` are the schedulers.
"""

import concurrent.futures as cf
import ctypes
import os
from typing import Dict, List

import numpy as np

from deepspeed_tpu.utils.logging import logger

_CPP_SRC = os.path.join(os.path.dirname(__file__), "csrc", "aio.cpp")
_lib = None
_lib_tried = False


def _load_native():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        from deepspeed_tpu.ops.native import load_extension
        lib = load_extension("aio", [_CPP_SRC], extra_ldflags=["-lpthread"])
        lib.ds_pread.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                 ctypes.c_long, ctypes.c_long, ctypes.c_int]
        lib.ds_pread.restype = ctypes.c_long
        lib.ds_pwrite.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                  ctypes.c_long, ctypes.c_long, ctypes.c_int]
        lib.ds_pwrite.restype = ctypes.c_long
        _lib = lib
    except Exception as e:
        logger.warning(f"aio native build unavailable, python fallback: {e}")
        _lib = None
    return _lib


class AsyncIOHandle:
    """Parity surface of reference ``deepspeed_py_aio_handle.h``:
    sync_pread/sync_pwrite, async_pread/async_pwrite + wait,
    new_cpu_locked_tensor/free_cpu_locked_tensor."""

    def __init__(self, block_size=1048576, queue_depth=8, single_submit=False,
                 overlap_events=True, thread_count=4):
        self._block_size = block_size
        self._queue_depth = queue_depth
        self._thread_count = thread_count
        self._pool = cf.ThreadPoolExecutor(max_workers=thread_count)
        self._pending: List[cf.Future] = []
        self._pinned: Dict[int, np.ndarray] = {}

    # ---- introspection parity ------------------------------------
    def get_block_size(self):
        return self._block_size

    def get_queue_depth(self):
        return self._queue_depth

    def get_thread_count(self):
        return self._thread_count

    # ---- core ops ------------------------------------------------
    @staticmethod
    def _do_read(buffer: np.ndarray, filename: str, offset: int = 0):
        lib = _load_native()
        nbytes = buffer.nbytes
        if lib is not None:
            got = lib.ds_pread(filename.encode(),
                               buffer.ctypes.data_as(ctypes.c_void_p),
                               ctypes.c_long(nbytes), ctypes.c_long(offset),
                               ctypes.c_int(0))
            assert got == nbytes, f"short read {got}/{nbytes} from {filename}"
            return got
        with open(filename, "rb") as f:
            f.seek(offset)
            data = f.read(nbytes)
        assert len(data) == nbytes, f"short read from {filename}"
        buffer.view(np.uint8).reshape(-1)[:] = np.frombuffer(data, np.uint8)
        return nbytes

    @staticmethod
    def _do_write(buffer: np.ndarray, filename: str, offset: int = 0):
        lib = _load_native()
        nbytes = buffer.nbytes
        buf = np.ascontiguousarray(buffer)
        if lib is not None:
            put = lib.ds_pwrite(filename.encode(),
                                buf.ctypes.data_as(ctypes.c_void_p),
                                ctypes.c_long(nbytes), ctypes.c_long(offset),
                                ctypes.c_int(0))
            assert put == nbytes, f"short write {put}/{nbytes} to {filename}"
            return put
        mode = "r+b" if os.path.exists(filename) else "wb"
        with open(filename, mode) as f:
            f.seek(offset)
            f.write(buf.tobytes())
        return nbytes

    def sync_pread(self, buffer, filename, offset=0):
        return self._do_read(np.asarray(buffer), filename, offset)

    def sync_pwrite(self, buffer, filename, offset=0):
        return self._do_write(np.asarray(buffer), filename, offset)

    def async_pread(self, buffer, filename, offset=0):
        self._pending.append(
            self._pool.submit(self._do_read, np.asarray(buffer), filename, offset))
        return 0

    def async_pwrite(self, buffer, filename, offset=0):
        self._pending.append(
            self._pool.submit(self._do_write, np.asarray(buffer), filename, offset))
        return 0

    # parity aliases
    read = sync_pread
    write = sync_pwrite
    pread = sync_pread
    pwrite = sync_pwrite

    def wait(self):
        n = 0
        for fut in self._pending:
            fut.result()
            n += 1
        self._pending = []
        return n

    # ---- pinned buffers ------------------------------------------
    def new_cpu_locked_tensor(self, num_elem, dtype=np.float32):
        arr = np.zeros(num_elem, dtype=dtype)
        self._pinned[id(arr)] = arr
        return arr

    def free_cpu_locked_tensor(self, tensor):
        self._pinned.pop(id(tensor), None)


def aio_read(buffer, filename, **kw):
    return AsyncIOHandle()._do_read(np.asarray(buffer), filename)


def aio_write(buffer, filename, **kw):
    return AsyncIOHandle()._do_write(np.asarray(buffer), filename)


reference_impl = AsyncIOHandle
