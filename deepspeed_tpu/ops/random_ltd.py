"""Random layer-token-drop (random-LTD) ops.

Parity: reference ``csrc/random_ltd/`` (``token_sort_``, ``token_gather``,
``token_scatter_``, ``mask_gather_bert/gpt``) backing the random-LTD data
efficiency feature.  On TPU these are gather/scatter index ops that XLA
compiles well; the kernel-worthy part (sorting sampled indices) is
``jnp.sort`` on a small index vector.
"""

import jax
import jax.numpy as jnp


def sample_token_indices(rng, seq_len, keep, batch=None):
    """Sample ``keep`` sorted token indices per sequence (reference
    token_sort_: sampled indices must stay sorted to preserve order)."""
    if batch is None:
        idx = jax.random.permutation(rng, seq_len)[:keep]
        return jnp.sort(idx)
    keys = jax.random.split(rng, batch)
    idx = jax.vmap(lambda k: jnp.sort(jax.random.permutation(k, seq_len)[:keep]))(keys)
    return idx


def token_gather(x, indices):
    """x: [B, S, ...]; indices: [B, K] → [B, K, ...]."""
    return jnp.take_along_axis(
        x, indices.reshape(indices.shape + (1,) * (x.ndim - 2)), axis=1)


def token_scatter(full, part, indices):
    """Inverse of token_gather: write part back into full at indices."""
    idx = indices.reshape(indices.shape + (1,) * (full.ndim - 2))
    idx = jnp.broadcast_to(idx, part.shape[:2] + full.shape[2:])
    return jnp.put_along_axis(full, idx, part, axis=1, inplace=False)


def mask_gather_gpt(attention_mask, keep):
    """Causal (GPT) masks are positional; dropping tokens keeps causality, so
    the gathered mask is just the leading [keep, keep] block (reference
    slice_attn_masks.cu mask_gather_gpt)."""
    return attention_mask[..., :keep, :keep]


def mask_gather_bert(attention_mask, indices):
    """Bidirectional (BERT) mask: gather rows+cols at sampled indices."""
    m = jnp.take_along_axis(attention_mask,
                            indices[:, None, :, None], axis=2)
    m = jnp.take_along_axis(m, indices[:, None, None, :], axis=3)
    return m


reference_impl = token_gather
