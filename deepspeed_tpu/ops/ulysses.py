"""Ulysses-style sequence parallelism (all-to-all head↔sequence swap).

The reference has NO sequence parallelism (SURVEY §2.4: absent in 0.8.3);
this fills the gap the TPU-first way, as DeepSpeed later did with
"DeepSpeed-Ulysses": attention inputs arrive sequence-sharded over the ``sp``
axis; an all-to-all re-shards them head-wise so every device computes full
-sequence attention for ``H/sp`` heads; a second all-to-all restores the
sequence sharding.  Both all-to-alls ride ICI and cost O(S·D/sp) per device.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops._shard_map import axis_size, shard_map
from deepspeed_tpu.parallel.topology import BATCH_AXES, SP_AXIS
from deepspeed_tpu.runtime.zero.stage_plan import active_mesh


def sp_degree(mesh=None) -> int:
    mesh = mesh or active_mesh()
    if mesh is None:
        return 1
    return mesh.shape.get(SP_AXIS, 1)


def _seq_to_heads(x, axis_name):
    """[B, S/sp, H, D] → [B, S, H/sp, D] via all-to-all."""
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def _heads_to_seq(x, axis_name):
    """[B, S, H/sp, D] → [B, S/sp, H, D]."""
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention_local(q, k, v, attn_fn, axis_name=SP_AXIS):
    """Per-device body (call inside shard_map): q/k/v sequence-sharded
    [B, S/sp, H, D]; ``attn_fn(q,k,v)`` computes full attention on the
    head-sharded views."""
    sp = axis_size(axis_name)
    H = q.shape[2]
    Hkv = k.shape[2]
    assert H % sp == 0, f"n_heads {H} must divide sp degree {sp}"
    assert Hkv % sp == 0, f"n_kv_heads {Hkv} must divide sp degree {sp}"
    q = _seq_to_heads(q, axis_name)
    k = _seq_to_heads(k, axis_name)     # stays at Hkv/sp heads (GQA-aware)
    v = _seq_to_heads(v, axis_name)
    out = attn_fn(q, k, v)              # [B, S, H/sp, D]
    return _heads_to_seq(out, axis_name)


def ulysses_attention(q, k, v, attn_fn, mesh=None):
    """GSPMD entry: q/k/v are global [B, S, H, D] arrays (sequence-sharded
    over ``sp`` by the activation layout); runs the shard_map body over the
    mesh.  Falls back to plain attention when sp degree is 1."""
    mesh = mesh or active_mesh()
    if mesh is None or mesh.shape.get(SP_AXIS, 1) == 1:
        return attn_fn(q, k, v)
    spec = P(tuple(BATCH_AXES), SP_AXIS, None, None)
    body = shard_map(
        lambda q, k, v: ulysses_attention_local(q, k, v, attn_fn),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return body(q, k, v)
