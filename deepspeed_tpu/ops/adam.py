"""Fused Adam over flat partition buffers.

Parity: reference ``csrc/adam/fused_adam_frontend.cpp`` + ``multi_tensor_adam.cu``
(``multi_tensor_adam``) — the CUDA multi-tensor AdamW used by ZeRO.

TPU design: the optimizer math is expressed once over a flat 1-D buffer (the
ZeRO partition layout); under jit XLA fuses it into a single VPU loop, which
is what the CUDA multi-tensor apply hand-builds.  A Pallas version
(``ops/pallas/fused_adam.py``) exists for the HBM-bound regime; this jnp
implementation is the reference/oracle and the CPU fallback.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    m: jnp.ndarray
    v: jnp.ndarray
    step: jnp.ndarray


def init_state(params_flat: jnp.ndarray) -> AdamState:
    return AdamState(
        m=jnp.zeros_like(params_flat, dtype=jnp.float32),
        v=jnp.zeros_like(params_flat, dtype=jnp.float32),
        step=jnp.zeros((), jnp.int32))


def reference_impl(params, grads, state: AdamState, lr=1e-3, beta1=0.9,
                   beta2=0.999, eps=1e-8, weight_decay=0.0, adamw_mode=True,
                   bias_correction=True):
    """One fused AdamW update on flat fp32 buffers.  Returns (params, state).

    Mirrors the update in ``multi_tensor_adam.cu`` (ADAM_MODE 0/1).
    """
    g = grads.astype(jnp.float32)
    p = params.astype(jnp.float32)
    step = state.step + 1
    if not adamw_mode and weight_decay:   # L2-regularised Adam (mode 1)
        g = g + weight_decay * p
    m = beta1 * state.m + (1.0 - beta1) * g
    v = beta2 * state.v + (1.0 - beta2) * jnp.square(g)
    if bias_correction:
        sf = jnp.float32(step)
        m_hat = m / (1.0 - beta1 ** sf)
        v_hat = v / (1.0 - beta2 ** sf)
    else:
        m_hat, v_hat = m, v
    update = m_hat / (jnp.sqrt(v_hat) + eps)
    if adamw_mode and weight_decay:       # decoupled decay (mode 0)
        update = update + weight_decay * p
    new_p = p - lr * update
    return new_p.astype(params.dtype), AdamState(m=m, v=v, step=step)


def fused_adam(params, grads, state, **kw):
    """Dispatching entry: Pallas on TPU, jnp elsewhere."""
    try:
        import jax
        if jax.default_backend() not in ("cpu",):
            from deepspeed_tpu.ops.pallas.fused_adam import fused_adam_pallas
            return fused_adam_pallas(params, grads, state, **kw)
    except ImportError:
        pass
    return reference_impl(params, grads, state, **kw)


multi_tensor_adam = reference_impl  # parity alias
