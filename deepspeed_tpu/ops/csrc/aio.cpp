// Async file-I/O engine for the NVMe swap path (ZeRO-Infinity).
//
// Role parity: reference csrc/aio/ (libaio O_DIRECT engine with a
// submission queue drained by deepspeed_aio_thread.cpp).  Here the queue
// IS the kernel's: a raw-syscall io_uring ring (no liburing dependency)
// with queue_depth in-flight ops, O_DIRECT when alignment allows, and
// mlock'd pinned buffers.  The blocking ds_pread/ds_pwrite entry points
// remain as the sync path and the fallback when io_uring is unavailable
// (seccomp'd containers return -EPERM from io_uring_setup).
//
// API (ctypes):
//   void* ds_aio_create(int queue_depth)            NULL if unavailable
//   long  ds_aio_submit_read(h, fname, buf, n, off) >=0 ok, <0 errno
//   long  ds_aio_submit_write(h, fname, buf, n, off)
//   long  ds_aio_drain(h)        wait all in-flight; completed count / <0
//   void  ds_aio_destroy(h)
//   void* ds_alloc_pinned(long nbytes)              4k-aligned + mlock
//   void  ds_free_pinned(void* p, long nbytes)

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <linux/io_uring.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr long kAlign = 4096;

bool aligned(const void* p, long n, long off) {
    return ((reinterpret_cast<uintptr_t>(p) % kAlign) == 0) &&
           (n % kAlign == 0) && (off % kAlign == 0);
}

int sys_io_uring_setup(unsigned entries, io_uring_params* p) {
    return (int)syscall(__NR_io_uring_setup, entries, p);
}

int sys_io_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                       unsigned flags) {
    return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete,
                        flags, nullptr, 0);
}

// IORING_OP_READ/WRITE need kernel >= 5.6 while io_uring_setup exists from
// 5.1 — probe the opcode so 5.1-5.5 kernels fall back to the thread pool
// instead of failing every op with -EINVAL
bool probe_read_write_ops(int ring_fd) {
// IORING_REGISTER_PROBE is an enum; gate on the same-era flag macro.
// io_uring_probe ends in a flexible array member, so size it by hand
// (C++ rejects embedding it in a larger struct).
#ifdef IO_URING_OP_SUPPORTED
    size_t sz = sizeof(io_uring_probe) + 64 * sizeof(io_uring_probe_op);
    std::vector<uint8_t> mem(sz, 0);
    io_uring_probe* pr = reinterpret_cast<io_uring_probe*>(mem.data());
    int r = (int)syscall(__NR_io_uring_register, ring_fd,
                         IORING_REGISTER_PROBE, pr, 64);
    if (r < 0) return false;   // probe itself needs 5.6+ — same cutoff
    if (pr->last_op < IORING_OP_WRITE) return false;
    return (pr->ops[IORING_OP_READ].flags & IO_URING_OP_SUPPORTED) &&
           (pr->ops[IORING_OP_WRITE].flags & IO_URING_OP_SUPPORTED);
#else
    (void)ring_fd;
    return false;              // headers predate the opcodes entirely
#endif
}

// one submitted op: keeps the fd open until completion and remembers the
// request so short transfers can be finished synchronously
struct Op {
    int fd = -1;
    bool write = false;
    char* buf = nullptr;
    long nbytes = 0;
    long offset = 0;
    bool live = false;
};

struct Engine {
    int ring_fd = -1;
    unsigned sq_entries = 0, cq_entries = 0;
    // sq ring pointers
    uint8_t* sq_ring = nullptr; size_t sq_ring_sz = 0;
    uint8_t* cq_ring = nullptr; size_t cq_ring_sz = 0;
    io_uring_sqe* sqes = nullptr; size_t sqes_sz = 0;
    unsigned* sq_head = nullptr; unsigned* sq_tail = nullptr;
    unsigned* sq_mask = nullptr; unsigned* sq_array = nullptr;
    unsigned* cq_head = nullptr; unsigned* cq_tail = nullptr;
    unsigned* cq_mask = nullptr;
    io_uring_cqe* cqes = nullptr;
    bool single_mmap = false;

    std::vector<Op> ops;          // slot table, size = sq_entries
    unsigned inflight = 0;
    long completed_total = 0;
    std::mutex mu;

    ~Engine() {
        if (sqes) munmap(sqes, sqes_sz);
        if (sq_ring) munmap(sq_ring, sq_ring_sz);
        if (cq_ring && !single_mmap) munmap(cq_ring, cq_ring_sz);
        if (ring_fd >= 0) close(ring_fd);
        for (auto& op : ops)
            if (op.live && op.fd >= 0) close(op.fd);
    }
};

// reap every completion currently in the CQ; finish short transfers
// synchronously (rare: page-cache reads at EOF boundaries)
long reap(Engine* e) {
    long n = 0;
    unsigned head = __atomic_load_n(e->cq_head, __ATOMIC_ACQUIRE);
    unsigned tail = __atomic_load_n(e->cq_tail, __ATOMIC_ACQUIRE);
    while (head != tail) {
        io_uring_cqe* c = &e->cqes[head & *e->cq_mask];
        unsigned slot = (unsigned)c->user_data;
        Op& op = e->ops[slot];
        long res = c->res;
        long ok = 0;
        if (res < 0) {
            ok = res;  // errno-style failure
        } else if (res < op.nbytes) {
            // finish the tail synchronously
            long done = res;
            while (done < op.nbytes) {
                ssize_t r = op.write
                    ? pwrite(op.fd, op.buf + done, op.nbytes - done,
                             op.offset + done)
                    : pread(op.fd, op.buf + done, op.nbytes - done,
                            op.offset + done);
                if (r <= 0) { ok = -EIO; break; }
                done += r;
            }
        }
        close(op.fd);
        op.live = false;
        e->inflight--;
        if (ok < 0) n = ok;      // report the first error from drain
        else {
            if (n >= 0) n++;
            e->completed_total++;  // drain reports ALL since last drain,
        }                          // incl. reaps during submit backpressure
        head++;
    }
    __atomic_store_n(e->cq_head, head, __ATOMIC_RELEASE);
    return n;
}

long submit(Engine* e, const char* fname, void* buffer, long nbytes,
            long offset, bool write) {
    std::lock_guard<std::mutex> lock(e->mu);
    // ring full → wait for one completion first
    while (e->inflight >= e->sq_entries) {
        if (sys_io_uring_enter(e->ring_fd, 0, 1, IORING_ENTER_GETEVENTS) < 0)
            return -errno;
        long r = reap(e);
        if (r < 0) return r;
    }
    int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    if (aligned(buffer, nbytes, offset)) flags |= O_DIRECT;
    int fd = open(fname, flags, 0644);
    if (fd < 0 && (flags & O_DIRECT))
        fd = open(fname, flags & ~O_DIRECT, 0644);
    if (fd < 0) return -errno;

    // find a free slot
    unsigned slot = 0;
    while (slot < e->ops.size() && e->ops[slot].live) slot++;
    Op& op = e->ops[slot];
    op = Op{fd, write, static_cast<char*>(buffer), nbytes, offset, true};

    unsigned tail = __atomic_load_n(e->sq_tail, __ATOMIC_ACQUIRE);
    unsigned idx = tail & *e->sq_mask;
    io_uring_sqe* sqe = &e->sqes[idx];
    std::memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = write ? IORING_OP_WRITE : IORING_OP_READ;
    sqe->fd = fd;
    sqe->addr = reinterpret_cast<uint64_t>(buffer);
    sqe->len = (unsigned)nbytes;
    sqe->off = (uint64_t)offset;
    sqe->user_data = slot;
    e->sq_array[idx] = idx;
    __atomic_store_n(e->sq_tail, tail + 1, __ATOMIC_RELEASE);

    int r = sys_io_uring_enter(e->ring_fd, 1, 0, 0);
    if (r < 0) { close(fd); op.live = false; return -errno; }
    e->inflight++;
    return 0;
}

}  // namespace

extern "C" {

void* ds_aio_create(int queue_depth) {
    if (queue_depth < 1) queue_depth = 1;
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    int fd = sys_io_uring_setup((unsigned)queue_depth, &p);
    if (fd < 0) return nullptr;   // seccomp / old kernel → caller falls back
    if (!probe_read_write_ops(fd)) { close(fd); return nullptr; }

    Engine* e = new Engine();
    e->ring_fd = fd;
    e->sq_entries = p.sq_entries;
    e->cq_entries = p.cq_entries;
    e->single_mmap = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;

    e->sq_ring_sz = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    e->cq_ring_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    if (e->single_mmap && e->cq_ring_sz > e->sq_ring_sz)
        e->sq_ring_sz = e->cq_ring_sz;
    e->sq_ring = static_cast<uint8_t*>(
        mmap(nullptr, e->sq_ring_sz, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING));
    if (e->sq_ring == MAP_FAILED) { e->sq_ring = nullptr; delete e; return nullptr; }
    e->cq_ring = e->single_mmap ? e->sq_ring
        : static_cast<uint8_t*>(
              mmap(nullptr, e->cq_ring_sz, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING));
    if (e->cq_ring == MAP_FAILED) { e->cq_ring = nullptr; delete e; return nullptr; }
    e->sqes_sz = p.sq_entries * sizeof(io_uring_sqe);
    e->sqes = static_cast<io_uring_sqe*>(
        mmap(nullptr, e->sqes_sz, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES));
    if (e->sqes == MAP_FAILED) { e->sqes = nullptr; delete e; return nullptr; }

    e->sq_head = reinterpret_cast<unsigned*>(e->sq_ring + p.sq_off.head);
    e->sq_tail = reinterpret_cast<unsigned*>(e->sq_ring + p.sq_off.tail);
    e->sq_mask = reinterpret_cast<unsigned*>(e->sq_ring + p.sq_off.ring_mask);
    e->sq_array = reinterpret_cast<unsigned*>(e->sq_ring + p.sq_off.array);
    e->cq_head = reinterpret_cast<unsigned*>(e->cq_ring + p.cq_off.head);
    e->cq_tail = reinterpret_cast<unsigned*>(e->cq_ring + p.cq_off.tail);
    e->cq_mask = reinterpret_cast<unsigned*>(e->cq_ring + p.cq_off.ring_mask);
    e->cqes = reinterpret_cast<io_uring_cqe*>(e->cq_ring + p.cq_off.cqes);
    e->ops.resize(p.sq_entries);
    return e;
}

long ds_aio_submit_read(void* h, const char* fname, void* buf, long nbytes,
                        long offset) {
    return submit(static_cast<Engine*>(h), fname, buf, nbytes, offset, false);
}

long ds_aio_submit_write(void* h, const char* fname, void* buf, long nbytes,
                         long offset) {
    return submit(static_cast<Engine*>(h), fname, buf, nbytes, offset, true);
}

long ds_aio_drain(void* h) {
    Engine* e = static_cast<Engine*>(h);
    std::lock_guard<std::mutex> lock(e->mu);
    while (e->inflight > 0) {
        if (sys_io_uring_enter(e->ring_fd, 0, 1, IORING_ENTER_GETEVENTS) < 0)
            return -errno;
        long r = reap(e);
        if (r < 0) { e->completed_total = 0; return r; }
    }
    long total = e->completed_total;
    e->completed_total = 0;
    return total;
}

long ds_aio_inflight(void* h) {
    Engine* e = static_cast<Engine*>(h);
    std::lock_guard<std::mutex> lock(e->mu);
    return e->inflight;
}

void ds_aio_destroy(void* h) {
    delete static_cast<Engine*>(h);
}

void* ds_alloc_pinned(long nbytes) {
    long rounded = ((nbytes + kAlign - 1) / kAlign) * kAlign;
    void* p = nullptr;
    if (posix_memalign(&p, kAlign, rounded) != 0) return nullptr;
    std::memset(p, 0, rounded);
    mlock(p, rounded);  // best-effort: RLIMIT_MEMLOCK may cap it
    return p;
}

void ds_free_pinned(void* p, long nbytes) {
    long rounded = ((nbytes + kAlign - 1) / kAlign) * kAlign;
    if (p) { munlock(p, rounded); free(p); }
}

// ---------------------------------------------------------------------
// blocking path (sync ops + fallback when io_uring is unavailable)
// ---------------------------------------------------------------------

long ds_pread(const char* filename, void* buffer, long nbytes, long offset,
              int use_direct) {
    int flags = O_RDONLY;
    if (use_direct && aligned(buffer, nbytes, offset)) flags |= O_DIRECT;
    int fd = open(filename, flags);
    if (fd < 0 && (flags & O_DIRECT)) fd = open(filename, O_RDONLY);
    if (fd < 0) return -1;
    long done = 0;
    char* p = static_cast<char*>(buffer);
    while (done < nbytes) {
        ssize_t r = pread(fd, p + done, nbytes - done, offset + done);
        if (r <= 0) break;
        done += r;
    }
    close(fd);
    return done;
}

long ds_pwrite(const char* filename, const void* buffer, long nbytes,
               long offset, int use_direct) {
    int flags = O_WRONLY | O_CREAT;
    if (use_direct && aligned(buffer, nbytes, offset)) flags |= O_DIRECT;
    int fd = open(filename, flags, 0644);
    if (fd < 0 && (flags & O_DIRECT)) fd = open(filename, O_WRONLY | O_CREAT, 0644);
    if (fd < 0) return -1;
    long done = 0;
    const char* p = static_cast<const char*>(buffer);
    while (done < nbytes) {
        ssize_t w = pwrite(fd, p + done, nbytes - done, offset + done);
        if (w <= 0) break;
        done += w;
    }
    close(fd);
    return done;
}

}  // extern "C"
