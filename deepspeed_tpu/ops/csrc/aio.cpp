// Threaded pread/pwrite core for the NVMe swap engine.
//
// Role parity: reference csrc/aio/common + py_lib (libaio O_DIRECT engine).
// Design: POSIX pread/pwrite in chunks from a caller-managed thread pool
// (Python side schedules; each call here is one blocking transfer).  O_DIRECT
// is attempted when the buffer and size are 4k-aligned, falling back to
// buffered I/O otherwise — same behaviour the reference gets from its
// _do_io fallback.

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {
constexpr long kAlign = 4096;

bool aligned(const void* p, long n, long off) {
    return ((reinterpret_cast<uintptr_t>(p) % kAlign) == 0) &&
           (n % kAlign == 0) && (off % kAlign == 0);
}
}  // namespace

extern "C" {

long ds_pread(const char* filename, void* buffer, long nbytes, long offset,
              int use_direct) {
    int flags = O_RDONLY;
    if (use_direct && aligned(buffer, nbytes, offset)) flags |= O_DIRECT;
    int fd = open(filename, flags);
    if (fd < 0 && (flags & O_DIRECT)) fd = open(filename, O_RDONLY);
    if (fd < 0) return -1;
    long done = 0;
    char* p = static_cast<char*>(buffer);
    while (done < nbytes) {
        ssize_t r = pread(fd, p + done, nbytes - done, offset + done);
        if (r <= 0) break;
        done += r;
    }
    close(fd);
    return done;
}

long ds_pwrite(const char* filename, const void* buffer, long nbytes,
               long offset, int use_direct) {
    int flags = O_WRONLY | O_CREAT;
    if (use_direct && aligned(buffer, nbytes, offset)) flags |= O_DIRECT;
    int fd = open(filename, flags, 0644);
    if (fd < 0 && (flags & O_DIRECT)) fd = open(filename, O_WRONLY | O_CREAT, 0644);
    if (fd < 0) return -1;
    long done = 0;
    const char* p = static_cast<const char*>(buffer);
    while (done < nbytes) {
        ssize_t w = pwrite(fd, p + done, nbytes - done, offset + done);
        if (w <= 0) break;
        done += w;
    }
    close(fd);
    return done;
}

}  // extern "C"
