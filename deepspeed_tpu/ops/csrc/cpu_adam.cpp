// Host-side fused AdamW for offloaded optimizer state.
//
// Role parity: reference csrc/adam/cpu_adam.cpp (Adam_Optimizer::Step_* with
// AVX intrinsics + OpenMP).  This implementation relies on -O3 -march=native
// auto-vectorisation instead of hand-written intrinsics: the loop is a single
// fused pass (the win over numpy is avoiding five buffer sweeps), and GCC
// vectorises it to the same AVX code the reference writes by hand.
//
// Exported C ABI (ctypes-loaded from ops/cpu_adam.py):
//   adam_update(params, grads, m, v, n, lr, beta1, beta2, eps, wd,
//               bias_corr1, bias_corr2, adamw_mode)

#include <cmath>
#include <cstddef>

extern "C" {

void adam_update(float* __restrict__ params, float* __restrict__ grads,
                 float* __restrict__ exp_avg, float* __restrict__ exp_avg_sq,
                 long n, float lr, float beta1, float beta2, float eps,
                 float weight_decay, float bias_corr1, float bias_corr2,
                 int adamw_mode) {
    const float om_beta1 = 1.0f - beta1;
    const float om_beta2 = 1.0f - beta2;
    const float inv_bc1 = 1.0f / bias_corr1;
    const float inv_bc2_sqrt = 1.0f / std::sqrt(bias_corr2);
    // step_size folding: update = m_hat / (sqrt(v_hat) + eps)
    //   m_hat = m * inv_bc1 ; sqrt(v_hat) = sqrt(v) * inv_bc2_sqrt
#pragma omp parallel for schedule(static)
    for (long i = 0; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        if (!adamw_mode && weight_decay != 0.0f) g += weight_decay * p;
        float m = beta1 * exp_avg[i] + om_beta1 * g;
        float v = beta2 * exp_avg_sq[i] + om_beta2 * g * g;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        float update = (m * inv_bc1) / (std::sqrt(v) * inv_bc2_sqrt + eps);
        if (adamw_mode && weight_decay != 0.0f) update += weight_decay * p;
        params[i] = p - lr * update;
    }
}

void adagrad_update(float* __restrict__ params, float* __restrict__ grads,
                    float* __restrict__ sq_accum, long n, float lr, float eps,
                    float weight_decay) {
#pragma omp parallel for schedule(static)
    for (long i = 0; i < n; ++i) {
        float g = grads[i];
        if (weight_decay != 0.0f) g += weight_decay * params[i];
        float s = sq_accum[i] + g * g;
        sq_accum[i] = s;
        params[i] -= lr * g / (std::sqrt(s) + eps);
    }
}

}  // extern "C"
