"""Attention ops: reference implementation + Pallas flash attention.

Parity role: reference ``csrc/transformer`` fused training attention
(``ds_transformer_cuda.cpp``) and ``deepspeed/ops/sparse_attention`` — the
compute-bound inner loop of the transformer.  TPU design: a Pallas
flash-attention kernel (tiled online-softmax over VMEM blocks feeding the MXU)
with a jnp reference implementation that is also the CPU/CI fallback and the
test oracle.

``attention()`` is the public entry: picks Pallas on TPU, jnp elsewhere.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp


def reference_attention(q, k, v, causal=True, bias=None, segment_ids=None,
                        softmax_scale: Optional[float] = None,
                        logit_softcap: Optional[float] = None):
    """Plain softmax attention.

    q: [B, S, H, D]; k/v: [B, S, Hkv, D] (Hkv divides H → GQA).
    Softmax in fp32 regardless of input dtype (reference kernels do the same).
    """
    orig_dtype = q.dtype
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if logit_softcap:
        # Gemma-2 style: bounded raw scores, applied BEFORE mask/bias
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)
    Sk = k.shape[1]
    if bias is not None:
        logits = logits + bias
    if causal:
        qi = jnp.arange(Sq)[:, None] + (Sk - Sq)
        ki = jnp.arange(Sk)[None, :]
        mask = qi >= ki
        logits = jnp.where(mask[None, None], logits, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(orig_dtype)


# jnp reference doubles as the fallback; the Pallas kernel lives in
# ops/pallas/flash_attention.py and is substituted when running on TPU.
reference_impl = reference_attention


def alibi_window_bias(Sq, Sk, slopes=None, window=None):
    """Additive attention bias for ALiBi slopes and/or a sliding window —
    THE shared construction (model `_attn_bias`, flash fallback): ALiBi is
    ``slope * kpos`` (row-constant part cancels in softmax) and the window
    allows ``qpos - kpos < w`` with ``w <= 0`` meaning unlimited.  Query
    rows are aligned to the END of the key range (``Sq != Sk`` decode)."""
    import jax.numpy as jnp
    bias = None
    if slopes is not None:
        bias = (jnp.asarray(slopes, jnp.float32)[None, :, None, None]
                * jnp.arange(Sk, dtype=jnp.float32)[None, None, None, :])
    if window is not None:
        qpos = jnp.arange(Sq, dtype=jnp.int32)[:, None] + (Sk - Sq)
        kpos = jnp.arange(Sk, dtype=jnp.int32)[None, :]
        w = jnp.asarray(window).astype(jnp.int32)
        wbias = jnp.where((qpos - kpos < w) | (w <= 0), 0.0,
                          -1e30).astype(jnp.float32)[None, None]
        bias = wbias if bias is None else bias + wbias
    return bias


@functools.partial(jax.jit, static_argnames=("causal", "softmax_scale",
                                             "impl", "block_q", "block_k",
                                             "interpret", "logit_softcap"))
def attention(q, k, v, causal=True, softmax_scale=None, impl="auto",
              block_q=None, block_k=None, alibi_slopes=None, window=None,
              interpret=False, logit_softcap=None):
    """Dispatching attention entry point — the ONE place the
    pallas-vs-reference policy (and its loud fallback) lives.

    ``block_q``/``block_k`` tune the Pallas flash tiles (None = kernel
    defaults).  They MUST be static (they pick the Pallas grid) — a traced
    value here would poison the `or` below with a
    TracerBoolConversionError that the fallback except would silently turn
    into the jnp path.  ``alibi_slopes`` ([H]) and ``window`` (traced
    scalar, 0/None = unlimited) ride the flash kernel's in-kernel bias on
    the Pallas path and a materialized :func:`alibi_window_bias` on the
    reference path.  ``interpret`` (static) runs the kernel in the Pallas
    interpreter (CPU CI)."""
    use_pallas = False
    if impl == "pallas":
        use_pallas = True
    elif impl == "auto":
        use_pallas = jax.default_backend() not in ("cpu",)
    if logit_softcap:
        # tanh capping lives inside the softmax loop; the flash kernel
        # does not implement it yet — XLA fuses the jnp path fine
        use_pallas = False
    if use_pallas:
        try:
            from deepspeed_tpu.ops.pallas.flash_attention import (
                DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, flash_attention)
            return flash_attention(q, k, v, causal=causal,
                                   softmax_scale=softmax_scale,
                                   block_q=block_q or DEFAULT_BLOCK_Q,
                                   block_k=block_k or DEFAULT_BLOCK_K,
                                   alibi_slopes=alibi_slopes, window=window,
                                   interpret=interpret)
        except Exception as e:                      # pragma: no cover
            _warn_fallback(f"{type(e).__name__}: {e}")
    bias = None
    if alibi_slopes is not None or window is not None:
        bias = alibi_window_bias(q.shape[1], k.shape[1],
                                 slopes=alibi_slopes, window=window)
    return reference_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale, bias=bias,
                               logit_softcap=logit_softcap)


@functools.lru_cache(maxsize=8)
def _warn_fallback(reason: str):
    """A silent fallback once hid a tracer bug that disabled the flash
    kernel entirely (-30% train throughput); never swallow quietly."""
    from deepspeed_tpu.utils.logging import logger
    logger.warning(f"flash attention unavailable, using jnp reference "
                   f"attention: {reason}")
