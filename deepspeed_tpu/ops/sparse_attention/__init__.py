"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention/``)."""

from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseAttentionUtils, SparseSelfAttention, expand_layout_mask,
    sparse_attention)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, LocalSlidingWindowSparsityConfig, SparsityConfig,
    VariableSparsityConfig)

__all__ = [
    "SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
    "VariableSparsityConfig", "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig", "LocalSlidingWindowSparsityConfig",
    "SparseSelfAttention", "SparseAttentionUtils", "sparse_attention",
    "expand_layout_mask",
]
