"""Block-sparse self-attention on TPU.

Parity: reference ``deepspeed/ops/sparse_attention/`` — Triton block-sparse
sddmm/softmax/dsd kernels (``matmul.py:8-14``, ``softmax.py``) behind
``SparseSelfAttention``/``SparseAttentionUtils``.

TPU design: two paths behind one API.  The Pallas kernel
(``ops/pallas/sparse_attention.py``) precomputes the static layout into an
active-block index table and iterates ONLY set blocks — DMA and MXU work
scale with the set-block count, the same asymptotics the reference gets
from Triton sddmm/dsd.  The jnp path here materialises the block mask and
runs dense masked softmax (O(S²) compute): it is the oracle, the CPU
fallback, and the path for ``key_padding_mask`` (dynamic per-batch
masking, which the static-layout kernel does not take).
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    DenseSparsityConfig, SparsityConfig)


def expand_layout_mask(layout: np.ndarray, block: int, seq_len: int
                       ) -> np.ndarray:
    """[H, nb, nb] block layout → [H, S, S] boolean attention mask."""
    n = seq_len // block
    lay = np.asarray(layout[:, :n, :n])
    return np.repeat(np.repeat(lay, block, axis=1), block, axis=2)


def sparse_attention(q, k, v, layout: np.ndarray, block: int,
                     causal: bool = False, softmax_scale: Optional[float] = None,
                     key_padding_mask=None, impl: Optional[str] = None,
                     interpret: bool = False):
    """Block-sparse attention.  q/k/v: [B, S, H, D]; layout [H, nb, nb].

    ``impl``: None (auto: Pallas kernel on TPU when applicable), "pallas",
    or "jnp"."""
    from deepspeed_tpu.ops.decode_attention import use_pallas
    B, S, H, D = q.shape
    kernel_ok = key_padding_mask is None and S % block == 0
    if impl is None and not kernel_ok:
        impl = "jnp"   # auto never picks the kernel for padded/non-tiling
    if use_pallas(impl, seq_len=None):
        assert kernel_ok, "pallas sparse attention needs block-tiling " \
            "shapes and no key_padding_mask"
        from deepspeed_tpu.ops.pallas.sparse_attention import \
            sparse_attention_pallas
        return sparse_attention_pallas(q, k, v, layout, block,
                                       causal=causal,
                                       softmax_scale=softmax_scale,
                                       interpret=interpret)
    scale = softmax_scale if softmax_scale is not None else D ** -0.5
    mask = jnp.asarray(expand_layout_mask(layout, block, S))  # [H, S, S]
    if causal:
        mask = jnp.logical_and(mask, jnp.tril(jnp.ones((S, S), bool)))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    logits = jnp.where(mask[None], logits, -1e30)
    if key_padding_mask is not None:
        kp = jnp.asarray(key_padding_mask, bool)  # [B, S] True = keep
        logits = jnp.where(kp[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    # rows with no visible key (fully masked) produce uniform garbage —
    # zero them like the reference kernel's empty-row handling
    any_visible = jnp.max(mask, axis=-1)  # [H, S]
    probs = probs * any_visible[None, :, :, None]
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


class SparseSelfAttention:
    """Parity surface of reference ``sparse_self_attention.py``."""

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul", max_seq_length: int = 2048):
        self.sparsity_config = sparsity_config or DenseSparsityConfig(
            num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._layout_cache = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layout_cache:
            self._layout_cache[seq_len] = \
                self.sparsity_config.make_layout(seq_len)
        return self._layout_cache[seq_len]

    def __call__(self, q, k, v, key_padding_mask=None, causal=None):
        sc = self.sparsity_config
        if causal is None:
            causal = getattr(sc, "attention", "bidirectional") == \
                "unidirectional"
        return sparse_attention(q, k, v, self.get_layout(q.shape[1]),
                                sc.block, causal=causal,
                                key_padding_mask=key_padding_mask)

    forward = __call__


class SparseAttentionUtils:
    """Parity helpers (reference ``sparse_attention_utils.py``): pad/unpad
    sequences to block multiples."""

    @staticmethod
    def pad_to_block_size(block_size: int, input_ids=None,
                          attention_mask=None, inputs_embeds=None,
                          pad_token_id: int = 0):
        seq = (input_ids if input_ids is not None else inputs_embeds)
        S = seq.shape[1]
        pad = (-S) % block_size
        out = []
        for t, fill in ((input_ids, pad_token_id), (attention_mask, 0),
                        (inputs_embeds, 0)):
            if t is None:
                out.append(None)
                continue
            widths = [(0, 0), (0, pad)] + [(0, 0)] * (np.ndim(t) - 2)
            out.append(jnp.pad(jnp.asarray(t), widths,
                               constant_values=fill))
        return pad, *out

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        if pad_len:
            return sequence_output[:, :-pad_len]
        return sequence_output
