"""Block-sparsity layout configs.

Parity: reference ``deepspeed/ops/sparse_attention/sparsity_config.py``
(``SparsityConfig`` base ``:63`` and the family Dense/Fixed/Variable/
BigBird/BSLongformer/LocalSlidingWindow ``:63-686``): each config builds a
per-head boolean block layout [num_heads, num_blocks, num_blocks] where a
set bit means the (row-block, col-block) tile of attention is computed.

Implementation is from the documented pattern semantics (not a port):
layouts are numpy bool arrays; the TPU kernel consumes them as tile masks.
"""

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: block size + head layout bookkeeping."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq_len {seq_len} must be divisible by block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=bool)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray
                                              ) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attended (degenerate case for testing/perf baselines)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed local windows + periodic global blocks (the Sparse Transformer
    pattern).  ``num_local_blocks`` per window; the last
    ``num_global_blocks`` of each window are global: they attend/are
    attended everywhere (respecting directionality)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        assert attention in ("unidirectional", "bidirectional")
        self.attention = attention
        if horizontal_global_attention:
            assert attention == "bidirectional", \
                "horizontal global attention requires bidirectional"
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1:
            assert different_layout_per_head, \
                "different global patterns need different_layout_per_head"
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        for h in range(self.num_layout_heads):
            # local windows
            for start in range(0, n, self.num_local_blocks):
                end = min(start + self.num_local_blocks, n)
                for r in range(start, end):
                    hi = (r + 1) if self.attention == "unidirectional" else end
                    layout[h, r, start:hi] = True
            # global columns: representative block(s) of each window;
            # pattern index rotates across heads
            pat = (h % self.num_different_global_patterns)
            for start in range(0, n, self.num_local_blocks):
                g_lo = start + self.num_local_blocks - (pat + 1) * \
                    self.num_global_blocks
                g_lo = max(start, g_lo)
                g_hi = min(g_lo + self.num_global_blocks, n, start +
                           self.num_local_blocks)
                for g in range(g_lo, g_hi):
                    if self.attention == "unidirectional":
                        layout[h, g:, g] = True     # later rows see global g
                    else:
                        layout[h, :, g] = True
                        if self.horizontal_global_attention:
                            layout[h, g, :] = True
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local window sizes + random blocks + global first blocks."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.rng = np.random.default_rng(seed)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        for h in range(self.num_layout_heads):
            # variable local windows: cycle through the size list
            start, wi = 0, 0
            while start < n:
                w = self.local_window_blocks[
                    min(wi, len(self.local_window_blocks) - 1)]
                end = min(start + w, n)
                for r in range(start, end):
                    hi = (r + 1) if self.attention == "unidirectional" else end
                    layout[h, r, start:hi] = True
                start, wi = end, wi + 1
            # random blocks per row
            for r in range(n):
                limit = (r + 1) if self.attention == "unidirectional" else n
                for _ in range(self.num_random_blocks):
                    layout[h, r, int(self.rng.integers(0, limit))] = True
            # global columns
            cols = self._global_cols(n)
            for g in cols:
                if self.attention == "unidirectional":
                    layout[h, g:, g] = True
                else:
                    layout[h, :, g] = True
                    if self.horizontal_global_attention:
                        layout[h, g, :] = True
        return self.check_and_propagate_first_head_layout(layout)

    def _global_cols(self, n):
        if self.global_block_end_indices:
            cols = []
            for lo, hi in zip(self.global_block_indices,
                              self.global_block_end_indices):
                cols.extend(range(lo, min(hi, n)))
            return cols
        return [g for g in self.global_block_indices if g < n]


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + global first/last blocks."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional", seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.rng = np.random.default_rng(seed)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        g = self.num_global_blocks
        for h in range(self.num_layout_heads):
            for r in range(n):
                lo, hi = max(0, r - w), min(n, r + w + 1)
                if self.attention == "unidirectional":
                    hi = min(hi, r + 1)
                layout[h, r, lo:hi] = True
                limit = (r + 1) if self.attention == "unidirectional" else n
                for _ in range(self.num_random_blocks):
                    layout[h, r, int(self.rng.integers(0, limit))] = True
            # global: first g block rows/cols (+ last g for bidirectional)
            layout[h, :, :g] = True
            layout[h, :g, :] = (layout[h, :g, :] if
                                self.attention == "unidirectional" else True)
            if self.attention == "bidirectional":
                layout[h, :, n - g:] = True
                layout[h, n - g:, :] = True
            else:
                # causal: zero out the upper triangle contributions added
                tri = np.tril(np.ones((n, n), dtype=bool))
                layout[h] &= tri
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + explicit global blocks."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for r in range(n):
                lo, hi = max(0, r - w), min(n, r + w + 1)
                if self.attention == "unidirectional":
                    hi = min(hi, r + 1)
                layout[h, r, lo:hi] = True
            cols = (self.global_block_indices
                    if not self.global_block_end_indices else
                    [c for lo, hi in zip(self.global_block_indices,
                                         self.global_block_end_indices)
                     for c in range(lo, min(hi, n))])
            for g in cols:
                if g >= n:
                    continue
                if self.attention == "unidirectional":
                    layout[h, g:, g] = True
                else:
                    layout[h, :, g] = True
                    layout[h, g, :] = True
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (optionally causal)."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional"):
        super().__init__(num_heads, block, different_layout_per_head=False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        full = self.num_sliding_window_blocks
        for r in range(n):
            if self.attention == "unidirectional":
                lo = max(0, r - full + 1)
                layout[0, r, lo:r + 1] = True
            else:
                layout[0, r, max(0, r - w):min(n, r + w + 1)] = True
        layout[1:] = layout[0]
        return layout
