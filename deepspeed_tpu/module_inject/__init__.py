"""Policy-driven module injection (reference ``deepspeed/module_inject/``)."""

from deepspeed_tpu.module_inject.auto_tp import AutoTP, get_tp_rules
from deepspeed_tpu.module_inject.policies import (GPT2Policy, GPTNeoXPolicy,
                                                  InjectionPolicy, LlamaPolicy,
                                                  OPTPolicy, REPLACE_POLICIES,
                                                  find_policy)
from deepspeed_tpu.module_inject.replace_module import (convert_hf_model,
                                                        is_hf_model,
                                                        replace_transformer_layer,
                                                        revert_transformer_layer)

__all__ = [
    "AutoTP", "get_tp_rules", "InjectionPolicy", "GPT2Policy", "LlamaPolicy",
    "OPTPolicy", "GPTNeoXPolicy", "REPLACE_POLICIES", "find_policy",
    "convert_hf_model", "is_hf_model", "replace_transformer_layer",
    "revert_transformer_layer",
]
