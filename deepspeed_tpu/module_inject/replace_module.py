"""Policy-driven model replacement (HF → TPU-native runtime model).

Parity: reference ``module_inject/replace_module.py:308
replace_transformer_layer`` — walk the model, match a policy, build
containers that copy/slice weights into the fused kernel module.

TPU design: instead of mutating the torch module in place, the whole HF
model is converted ONCE into a ``CausalTransformerLM`` + params pytree
(stacked layers → ``lax.scan``), and sharding (auto-TP) happens by
``device_put`` with the model's ``tp_rules`` — XLA inserts the row-parallel
all-reduces the reference issues by hand after attention/MLP.
"""

from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)
from deepspeed_tpu.module_inject.policies import (REPLACE_POLICIES,
                                                  find_policy)
from deepspeed_tpu.utils.logging import logger


def _state_dict_of(model) -> Dict[str, Any]:
    if isinstance(model, dict):
        return model
    sd = model.state_dict()
    return dict(sd)


def is_hf_model(model) -> bool:
    """True for torch ``nn.Module``-like objects carrying an HF config."""
    return (hasattr(model, "state_dict") and hasattr(model, "config")
            and hasattr(model.config, "model_type"))


def replace_transformer_layer(model, hf_config=None, dtype=None,
                              checkpoint_dict=None
                              ) -> Tuple[CausalTransformerLM, Dict[str, Any]]:
    """Convert an HF model (or raw ``state_dict`` + ``hf_config``) into
    ``(CausalTransformerLM, params)``.

    The returned model's ``tp_rules()`` is the auto-TP sharding plan
    (reference ``auto_tp.py`` + ``ReplaceWithTensorSlicing``).
    """
    if hf_config is None:
        assert not isinstance(model, dict), \
            "raw state_dict conversion needs hf_config="
        hf_config = model.config
    policy = find_policy(hf_config)
    if policy is None:
        known = sorted({t for p in REPLACE_POLICIES for t in p.model_types})
        raise ValueError(
            f"no injection policy for model_type="
            f"'{getattr(hf_config, 'model_type', '?')}'; supported: {known}")
    sd = checkpoint_dict if checkpoint_dict is not None else _state_dict_of(model)
    cfg, params = policy.build(hf_config, sd)
    model_cls = (policy.model_cls() if hasattr(policy, "model_cls")
                 else CausalTransformerLM)
    logger.info(
        f"module_inject: {hf_config.model_type} → {model_cls.__name__} "
        f"(L={cfg.n_layers} d={cfg.hidden_size} V={cfg.vocab_size}) "
        f"via {policy.__name__}")
    return model_cls(cfg), params


# parity alias (the reference API name most users call indirectly)
convert_hf_model = replace_transformer_layer


def revert_transformer_layer(orig_layer_impl, model, config, preln=False):
    """Reference ``revert_transformer_layer`` reverses in-place kernel
    injection.  Our conversion is FUNCTIONAL — ``replace_transformer_layer``
    builds a fresh (TransformerConfig, params) and never mutates the HF
    model — so there is nothing to revert: the original module is returned
    unchanged, which is exactly the reference's postcondition."""
    return model
