"""Per-architecture injection policies.

Parity: reference ``module_inject/replace_policy.py`` + the container classes
under ``module_inject/containers/`` (``bloom.py:13``, ``opt.py:15``,
``gpt2.py``, ``llama``-style megatron containers): each policy knows how an
upstream HuggingFace architecture lays out its weights and how to map them
into the fused runtime module.

TPU design: the "fused runtime module" is ``CausalTransformerLM`` (one
jit-compiled program — XLA does the fusing the reference's CUDA kernels do by
hand).  A policy maps an HF ``model_type`` to (a) a ``TransformerConfig``
and (b) a params pytree built from the HF ``state_dict``.  Tensor-parallel
slicing (reference ``ReplaceWithTensorSlicing``, ``replace_module.py:25``)
is not done by copying shards: the converted params carry ``tp_rules`` and
``device_put`` shards them over the ``tp`` mesh axis.
"""

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.utils.logging import logger


def _np(t) -> np.ndarray:
    """torch tensor | ndarray → fp32 numpy (host)."""
    if isinstance(t, np.ndarray):
        return t.astype(np.float32)
    # torch path — lazy import so jax-only installs work
    return t.detach().to("cpu").float().numpy()


def _stack(sd: Dict[str, Any], fmt: str, n: int, transpose=False) -> np.ndarray:
    mats = [_np(sd[fmt.format(i)]) for i in range(n)]
    if transpose:
        mats = [m.T for m in mats]
    return np.stack(mats)


class InjectionPolicy:
    """Base policy (reference ``DSPolicy``/``TransformerPolicy``)."""

    model_types: Tuple[str, ...] = ()

    @classmethod
    def matches(cls, hf_config) -> bool:
        return getattr(hf_config, "model_type", None) in cls.model_types

    @classmethod
    def build(cls, hf_config, sd: Dict[str, Any]
              ) -> Tuple[TransformerConfig, Dict[str, Any]]:
        raise NotImplementedError


class GPT2Policy(InjectionPolicy):
    """HF ``GPT2LMHeadModel`` (reference ``containers/gpt2.py`` HFGPT2Layer
    policy).  Conv1D weights are stored [in, out] — already our layout; the
    fused c_attn splits into q/k/v thirds."""

    model_types = ("gpt2",)

    @classmethod
    def build(cls, hf, sd):
        d, L = hf.n_embd, hf.n_layer
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L,
            n_heads=hf.n_head, max_seq_len=hf.n_positions,
            norm_eps=hf.layer_norm_epsilon, activation="gelu",
            use_rmsnorm=False, use_rope=False, use_bias=True,
            norm_bias=True, tie_embeddings=True, remat=False)

        pre = "transformer.h.{}."
        qkv_w = _stack(sd, pre + "attn.c_attn.weight", L)   # [L, d, 3d]
        qkv_b = _stack(sd, pre + "attn.c_attn.bias", L)     # [L, 3d]
        layers = {
            "attn_norm": _stack(sd, pre + "ln_1.weight", L),
            "attn_norm_b": _stack(sd, pre + "ln_1.bias", L),
            "wq": qkv_w[:, :, :d], "wk": qkv_w[:, :, d:2 * d],
            "wv": qkv_w[:, :, 2 * d:],
            "wq_b": qkv_b[:, :d], "wk_b": qkv_b[:, d:2 * d],
            "wv_b": qkv_b[:, 2 * d:],
            "wo": _stack(sd, pre + "attn.c_proj.weight", L),
            "wo_b": _stack(sd, pre + "attn.c_proj.bias", L),
            "mlp_norm": _stack(sd, pre + "ln_2.weight", L),
            "mlp_norm_b": _stack(sd, pre + "ln_2.bias", L),
            "w_up": _stack(sd, pre + "mlp.c_fc.weight", L),
            "w_up_b": _stack(sd, pre + "mlp.c_fc.bias", L),
            "w_down": _stack(sd, pre + "mlp.c_proj.weight", L),
            "w_down_b": _stack(sd, pre + "mlp.c_proj.bias", L),
        }
        params = {
            "tok_embed": _np(sd["transformer.wte.weight"]),
            "pos_embed": _np(sd["transformer.wpe.weight"]),
            "final_norm": _np(sd["transformer.ln_f.weight"]),
            "final_norm_b": _np(sd["transformer.ln_f.bias"]),
            "layers": layers,
        }
        return cfg, params


def _rope_scaled_inv_freq(hf, dh: int):
    """Precompute the scaled inverse-frequency table for HF
    ``rope_scaling`` (None when unscaled).  Implements "linear" and
    "llama3" (the Llama-3.1+ NTK-by-parts rescale, matching HF
    ``_compute_llama3_parameters``); seq-len-dependent or
    attention-scaled types (dynamic/yarn/longrope) raise."""
    rs = getattr(hf, "rope_scaling", None)
    if not rs:
        return None
    kind = rs.get("rope_type", rs.get("type", "default"))
    theta = float(getattr(hf, "rope_theta", 10000.0))
    half = dh // 2
    inv = theta ** (-np.arange(half, dtype=np.float64) / half)
    if kind in ("default",):
        return None
    if kind == "linear":
        return tuple(float(v) for v in inv / float(rs["factor"]))
    if kind == "llama3":
        factor = float(rs["factor"])
        lo_f = float(rs["low_freq_factor"])
        hi_f = float(rs["high_freq_factor"])
        old_len = float(rs["original_max_position_embeddings"])
        wavelen = 2.0 * np.pi / inv
        out = np.where(wavelen > old_len / lo_f, inv / factor, inv)
        smooth = (old_len / wavelen - lo_f) / (hi_f - lo_f)
        smoothed = (1.0 - smooth) / factor * inv + smooth * inv
        medium = (wavelen >= old_len / hi_f) & (wavelen <= old_len / lo_f)
        out = np.where(medium, smoothed, out)
        return tuple(float(v) for v in out)
    raise ValueError(
        f"rope_scaling type {kind!r} is not supported (linear/llama3 "
        "convert; dynamic/yarn/longrope need runtime or attention "
        "scaling this model does not implement)")


class LlamaPolicy(InjectionPolicy):
    """HF ``LlamaForCausalLM`` / ``MistralForCausalLM`` /
    ``Qwen2ForCausalLM`` (reference has no llama container in 0.8.3 —
    auto-TP handles it; here it is first-class).  Linear weights are
    [out, in] → transpose.  GQA via num_key_value_heads; Qwen2 adds
    biases on q/k/v only (picked up when present)."""

    model_types = ("llama", "mistral", "qwen2")

    @classmethod
    def build(cls, hf, sd):
        d, L = hf.hidden_size, hf.num_hidden_layers
        n_kv = getattr(hf, "num_key_value_heads", None) or hf.num_attention_heads
        tied = bool(getattr(hf, "tie_word_embeddings", False))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L,
            n_heads=hf.num_attention_heads,
            n_kv_heads=(None if n_kv == hf.num_attention_heads else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=getattr(hf, "max_position_embeddings", 4096),
            rope_theta=float(getattr(hf, "rope_theta", 10000.0)),
            rope_inv_freq=_rope_scaled_inv_freq(
                hf, d // hf.num_attention_heads),
            norm_eps=hf.rms_norm_eps, activation="silu",
            use_rmsnorm=True, use_rope=True,
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."
        layers = {
            "attn_norm": _stack(sd, pre + "input_layernorm.weight", L),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L, transpose=True),
        }
        if pre.format(0) + "self_attn.q_proj.bias" in sd:   # Qwen2
            layers["wq_b"] = _stack(sd, pre + "self_attn.q_proj.bias", L)
            layers["wk_b"] = _stack(sd, pre + "self_attn.k_proj.bias", L)
            layers["wv_b"] = _stack(sd, pre + "self_attn.v_proj.bias", L)
        layers.update({
            "mlp_norm": _stack(sd, pre + "post_attention_layernorm.weight", L),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L, transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L, transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L, transpose=True),
        })
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class OPTPolicy(InjectionPolicy):
    """HF ``OPTForCausalLM`` (reference ``containers/opt.py:15`` HFOPTLayer
    policy).  ReLU FFN, learned positions with the OPT +2 offset (folded in
    by slicing the embedding), pre-LN only."""

    model_types = ("opt",)

    @classmethod
    def build(cls, hf, sd):
        if not getattr(hf, "do_layer_norm_before", True):
            raise ValueError("OPT with do_layer_norm_before=False (350m) is "
                             "not supported (post-LN architecture)")
        if getattr(hf, "word_embed_proj_dim", hf.hidden_size) != hf.hidden_size:
            raise ValueError("OPT word_embed_proj_dim != hidden_size is not "
                             "supported")
        d, L = hf.hidden_size, hf.num_hidden_layers
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L,
            n_heads=hf.num_attention_heads,
            ffn_hidden_size=hf.ffn_dim,
            max_seq_len=hf.max_position_embeddings,
            activation="relu", use_rmsnorm=False, use_rope=False,
            use_bias=True, norm_bias=True, tie_embeddings=True, remat=False)

        pre = "model.decoder.layers.{}."
        layers = {
            "attn_norm": _stack(sd, pre + "self_attn_layer_norm.weight", L),
            "attn_norm_b": _stack(sd, pre + "self_attn_layer_norm.bias", L),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, pre + "self_attn.out_proj.weight", L, transpose=True),
            "wq_b": _stack(sd, pre + "self_attn.q_proj.bias", L),
            "wk_b": _stack(sd, pre + "self_attn.k_proj.bias", L),
            "wv_b": _stack(sd, pre + "self_attn.v_proj.bias", L),
            "wo_b": _stack(sd, pre + "self_attn.out_proj.bias", L),
            "mlp_norm": _stack(sd, pre + "final_layer_norm.weight", L),
            "mlp_norm_b": _stack(sd, pre + "final_layer_norm.bias", L),
            "w_up": _stack(sd, pre + "fc1.weight", L, transpose=True),
            "w_up_b": _stack(sd, pre + "fc1.bias", L),
            "w_down": _stack(sd, pre + "fc2.weight", L, transpose=True),
            "w_down_b": _stack(sd, pre + "fc2.bias", L),
        }
        # OPT's learned positions index with a +2 offset
        pos = _np(sd["model.decoder.embed_positions.weight"])[2:]
        params = {
            "tok_embed": _np(sd["model.decoder.embed_tokens.weight"]),
            "pos_embed": pos,
            "final_norm": _np(sd["model.decoder.final_layer_norm.weight"]),
            "final_norm_b": _np(sd["model.decoder.final_layer_norm.bias"]),
            "layers": layers,
        }
        return cfg, params


class GPTNeoXPolicy(InjectionPolicy):
    """HF ``GPTNeoXForCausalLM`` (Pythia; reference ``containers/gptneox.py``).
    Fused QKV is laid out [H, 3, dh] per head; partial rotary via
    ``rotary_pct``.  ``use_parallel_residual`` maps onto the model's
    ``parallel_block`` (two distinct LNs, unlike GPT-J's shared one).
    """

    model_types = ("gpt_neox",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        dh = d // H
        rot = int(dh * getattr(hf, "rotary_pct", 1.0))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rotary_emb_base", 10000.0)),
            norm_eps=hf.layer_norm_eps, activation="gelu",
            use_rmsnorm=False, use_rope=True,
            rope_dim=(None if rot == dh else rot),
            parallel_block=bool(getattr(hf, "use_parallel_residual", True)),
            use_bias=True, norm_bias=True, tie_embeddings=False, remat=False)

        pre = "gpt_neox.layers.{}."
        # fused qkv: weight [3d, d] arranged [H, 3, dh, d]
        wq, wk, wv, bq, bk, bv = [], [], [], [], [], []
        for i in range(L):
            w = _np(sd[pre.format(i) + "attention.query_key_value.weight"])
            b = _np(sd[pre.format(i) + "attention.query_key_value.bias"])
            w = w.reshape(H, 3, dh, d)
            b = b.reshape(H, 3, dh)
            wq.append(w[:, 0].reshape(H * dh, d).T)
            wk.append(w[:, 1].reshape(H * dh, d).T)
            wv.append(w[:, 2].reshape(H * dh, d).T)
            bq.append(b[:, 0].reshape(-1))
            bk.append(b[:, 1].reshape(-1))
            bv.append(b[:, 2].reshape(-1))
        layers = {
            "attn_norm": _stack(sd, pre + "input_layernorm.weight", L),
            "attn_norm_b": _stack(sd, pre + "input_layernorm.bias", L),
            "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
            "wq_b": np.stack(bq), "wk_b": np.stack(bk), "wv_b": np.stack(bv),
            "wo": _stack(sd, pre + "attention.dense.weight", L, transpose=True),
            "wo_b": _stack(sd, pre + "attention.dense.bias", L),
            "mlp_norm": _stack(sd, pre + "post_attention_layernorm.weight", L),
            "mlp_norm_b": _stack(sd, pre + "post_attention_layernorm.bias", L),
            "w_up": _stack(sd, pre + "mlp.dense_h_to_4h.weight", L,
                           transpose=True),
            "w_up_b": _stack(sd, pre + "mlp.dense_h_to_4h.bias", L),
            "w_down": _stack(sd, pre + "mlp.dense_4h_to_h.weight", L,
                             transpose=True),
            "w_down_b": _stack(sd, pre + "mlp.dense_4h_to_h.bias", L),
        }
        params = {
            "tok_embed": _np(sd["gpt_neox.embed_in.weight"]),
            "final_norm": _np(sd["gpt_neox.final_layer_norm.weight"]),
            "final_norm_b": _np(sd["gpt_neox.final_layer_norm.bias"]),
            "lm_head": _np(sd["embed_out.weight"]).T,
            "layers": layers,
        }
        return cfg, params


class BertPolicy(InjectionPolicy):
    """HF ``BertForMaskedLM`` (reference ``containers/bert.py`` HFBertLayer
    policy).  Post-LN encoder → ``BertEncoder``; MLM head transform +
    tied decoder + bias."""

    model_types = ("bert",)

    @classmethod
    def model_cls(cls):
        from deepspeed_tpu.models.bert import BertEncoder
        return BertEncoder

    @classmethod
    def build(cls, hf, sd):
        from deepspeed_tpu.models.bert import BertConfig
        d, L = hf.hidden_size, hf.num_hidden_layers
        cfg = BertConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L,
            n_heads=hf.num_attention_heads,
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            type_vocab_size=hf.type_vocab_size,
            norm_eps=hf.layer_norm_eps)

        pre = "bert.encoder.layer.{}."
        layers = {
            "wq": _stack(sd, pre + "attention.self.query.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "attention.self.key.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "attention.self.value.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "attention.output.dense.weight", L,
                         transpose=True),
            "wq_b": _stack(sd, pre + "attention.self.query.bias", L),
            "wk_b": _stack(sd, pre + "attention.self.key.bias", L),
            "wv_b": _stack(sd, pre + "attention.self.value.bias", L),
            "wo_b": _stack(sd, pre + "attention.output.dense.bias", L),
            "attn_norm": _stack(sd, pre + "attention.output.LayerNorm.weight",
                                L),
            "attn_norm_b": _stack(sd, pre + "attention.output.LayerNorm.bias",
                                  L),
            "w_up": _stack(sd, pre + "intermediate.dense.weight", L,
                           transpose=True),
            "w_up_b": _stack(sd, pre + "intermediate.dense.bias", L),
            "w_down": _stack(sd, pre + "output.dense.weight", L,
                             transpose=True),
            "w_down_b": _stack(sd, pre + "output.dense.bias", L),
            "mlp_norm": _stack(sd, pre + "output.LayerNorm.weight", L),
            "mlp_norm_b": _stack(sd, pre + "output.LayerNorm.bias", L),
        }
        params = {
            "tok_embed": _np(sd["bert.embeddings.word_embeddings.weight"]),
            "pos_embed": _np(sd["bert.embeddings.position_embeddings.weight"]),
            "type_embed": _np(
                sd["bert.embeddings.token_type_embeddings.weight"]),
            "embed_norm": _np(sd["bert.embeddings.LayerNorm.weight"]),
            "embed_norm_b": _np(sd["bert.embeddings.LayerNorm.bias"]),
            "layers": layers,
            "mlm_dense": _np(
                sd["cls.predictions.transform.dense.weight"]).T,
            "mlm_dense_b": _np(sd["cls.predictions.transform.dense.bias"]),
            "mlm_norm": _np(
                sd["cls.predictions.transform.LayerNorm.weight"]),
            "mlm_norm_b": _np(sd["cls.predictions.transform.LayerNorm.bias"]),
            "mlm_bias": _np(sd["cls.predictions.bias"]),
        }
        return cfg, params


class BloomPolicy(InjectionPolicy):
    """HF ``BloomForCausalLM`` (reference ``containers/bloom.py:13``
    ``BLOOMLayerPolicy``).  ALiBi positions (no position embeddings), a
    LayerNorm directly after the word embeddings, and a fused QKV laid out
    [H, 3, dh] per head — the same head-interleaved split as GPT-NeoX."""

    model_types = ("bloom",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.n_layer, hf.n_head
        dh = d // H
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            max_seq_len=getattr(hf, "seq_length", 2048),
            norm_eps=hf.layer_norm_epsilon, activation="gelu",
            use_rmsnorm=False, use_rope=False, use_alibi=True,
            embed_norm=True, use_bias=True, norm_bias=True,
            tie_embeddings=True, remat=False)

        pre = "transformer.h.{}."
        wq, wk, wv, bq, bk, bv = [], [], [], [], [], []
        for i in range(L):
            w = _np(sd[pre.format(i) + "self_attention.query_key_value.weight"])
            b = _np(sd[pre.format(i) + "self_attention.query_key_value.bias"])
            w = w.reshape(H, 3, dh, d)
            b = b.reshape(H, 3, dh)
            wq.append(w[:, 0].reshape(H * dh, d).T)
            wk.append(w[:, 1].reshape(H * dh, d).T)
            wv.append(w[:, 2].reshape(H * dh, d).T)
            bq.append(b[:, 0].reshape(-1))
            bk.append(b[:, 1].reshape(-1))
            bv.append(b[:, 2].reshape(-1))
        layers = {
            "attn_norm": _stack(sd, pre + "input_layernorm.weight", L),
            "attn_norm_b": _stack(sd, pre + "input_layernorm.bias", L),
            "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
            "wq_b": np.stack(bq), "wk_b": np.stack(bk), "wv_b": np.stack(bv),
            "wo": _stack(sd, pre + "self_attention.dense.weight", L,
                         transpose=True),
            "wo_b": _stack(sd, pre + "self_attention.dense.bias", L),
            "mlp_norm": _stack(sd, pre + "post_attention_layernorm.weight", L),
            "mlp_norm_b": _stack(sd, pre + "post_attention_layernorm.bias", L),
            "w_up": _stack(sd, pre + "mlp.dense_h_to_4h.weight", L,
                           transpose=True),
            "w_up_b": _stack(sd, pre + "mlp.dense_h_to_4h.bias", L),
            "w_down": _stack(sd, pre + "mlp.dense_4h_to_h.weight", L,
                             transpose=True),
            "w_down_b": _stack(sd, pre + "mlp.dense_4h_to_h.bias", L),
        }
        params = {
            "tok_embed": _np(sd["transformer.word_embeddings.weight"]),
            "embed_norm": _np(
                sd["transformer.word_embeddings_layernorm.weight"]),
            "embed_norm_b": _np(
                sd["transformer.word_embeddings_layernorm.bias"]),
            "final_norm": _np(sd["transformer.ln_f.weight"]),
            "final_norm_b": _np(sd["transformer.ln_f.bias"]),
            "layers": layers,
        }
        return cfg, params


def _interleaved_to_half_rope_perm(rot: int, dh: int) -> np.ndarray:
    """Column permutation turning an interleaved-RoPE weight (GPT-J
    ``rotate_every_two``: pair (2j, 2j+1) gets freq j) into our half-split
    layout (pair (j, j+rot/2) gets freq j).  Applying it to BOTH wq and wk
    preserves all q·k dot products, so logits are unchanged."""
    half = rot // 2
    return np.asarray([2 * j for j in range(half)] +
                      [2 * j + 1 for j in range(half)] +
                      list(range(rot, dh)), np.int64)


class GPTJPolicy(InjectionPolicy):
    """HF ``GPTJForCausalLM`` (reference ``containers/gptj.py``
    ``HFGPTJLayerPolicy``).  Parallel attention+MLP residual sharing one
    LayerNorm, partial interleaved rotary (folded into a wq/wk column
    permutation), biased LM head."""

    model_types = ("gptj",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.n_embd, hf.n_layer, hf.n_head
        dh = d // H
        rot = getattr(hf, "rotary_dim", None) or dh
        perm = _interleaved_to_half_rope_perm(rot, dh)

        def qk(name, i):
            w = _np(sd[f"transformer.h.{i}.attn.{name}.weight"]).T  # [d, d]
            return w.reshape(d, H, dh)[:, :, perm].reshape(d, H * dh)

        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            ffn_hidden_size=getattr(hf, "n_inner", None) or 4 * d,
            max_seq_len=hf.n_positions,
            norm_eps=hf.layer_norm_epsilon, activation="gelu",
            use_rmsnorm=False, use_rope=True,
            rope_dim=(None if rot == dh else rot),
            parallel_block=True, use_bias=True, norm_bias=True,
            tie_embeddings=False, lm_head_bias=True, remat=False)

        pre = "transformer.h.{}."
        ln_w = _stack(sd, pre + "ln_1.weight", L)
        ln_b = _stack(sd, pre + "ln_1.bias", L)
        layers = {
            # one shared LN: duplicated into both sub-block norms
            "attn_norm": ln_w, "attn_norm_b": ln_b,
            "mlp_norm": ln_w.copy(), "mlp_norm_b": ln_b.copy(),
            "wq": np.stack([qk("q_proj", i) for i in range(L)]),
            "wk": np.stack([qk("k_proj", i) for i in range(L)]),
            "wv": _stack(sd, pre + "attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, pre + "attn.out_proj.weight", L, transpose=True),
            "w_up": _stack(sd, pre + "mlp.fc_in.weight", L, transpose=True),
            "w_up_b": _stack(sd, pre + "mlp.fc_in.bias", L),
            "w_down": _stack(sd, pre + "mlp.fc_out.weight", L, transpose=True),
            "w_down_b": _stack(sd, pre + "mlp.fc_out.bias", L),
        }
        params = {
            "tok_embed": _np(sd["transformer.wte.weight"]),
            "final_norm": _np(sd["transformer.ln_f.weight"]),
            "final_norm_b": _np(sd["transformer.ln_f.bias"]),
            "lm_head": _np(sd["lm_head.weight"]).T,
            "lm_head_b": _np(sd["lm_head.bias"]),
            "layers": layers,
        }
        return cfg, params


class GPTNeoPolicy(InjectionPolicy):
    """HF ``GPTNeoForCausalLM`` (reference ``containers/gptneo.py``
    ``HFGPTNEOLayerPolicy``).  Unscaled attention logits (no 1/sqrt(dh)),
    alternating global/local layers with a sliding window, learned
    positions, unbiased q/k/v."""

    model_types = ("gpt_neo",)

    @classmethod
    def build(cls, hf, sd):
        d, L = hf.hidden_size, hf.num_layers
        attn_types = [t for block in ([hf.attention_types]
                                      if isinstance(hf.attention_types[0][0],
                                                    str)
                                      else hf.attention_types)
                      for t in block[0] * block[1]]
        pattern = tuple(hf.window_size if t == "local" else 0
                        for t in attn_types)
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L,
            n_heads=hf.num_heads,
            ffn_hidden_size=getattr(hf, "intermediate_size", None) or 4 * d,
            max_seq_len=hf.max_position_embeddings,
            norm_eps=hf.layer_norm_epsilon, activation="gelu",
            use_rmsnorm=False, use_rope=False, use_bias=True, norm_bias=True,
            attn_scale=1.0,
            local_attn_pattern=(pattern if any(pattern) else None),
            tie_embeddings=True, remat=False)

        pre = "transformer.h.{}."
        att = "transformer.h.{}.attn.attention."
        layers = {
            "attn_norm": _stack(sd, pre + "ln_1.weight", L),
            "attn_norm_b": _stack(sd, pre + "ln_1.bias", L),
            "wq": _stack(sd, att + "q_proj.weight", L, transpose=True),
            "wk": _stack(sd, att + "k_proj.weight", L, transpose=True),
            "wv": _stack(sd, att + "v_proj.weight", L, transpose=True),
            "wo": _stack(sd, att + "out_proj.weight", L, transpose=True),
            "wo_b": _stack(sd, att + "out_proj.bias", L),
            "mlp_norm": _stack(sd, pre + "ln_2.weight", L),
            "mlp_norm_b": _stack(sd, pre + "ln_2.bias", L),
            "w_up": _stack(sd, pre + "mlp.c_fc.weight", L, transpose=True),
            "w_up_b": _stack(sd, pre + "mlp.c_fc.bias", L),
            "w_down": _stack(sd, pre + "mlp.c_proj.weight", L, transpose=True),
            "w_down_b": _stack(sd, pre + "mlp.c_proj.bias", L),
        }
        params = {
            "tok_embed": _np(sd["transformer.wte.weight"]),
            "pos_embed": _np(sd["transformer.wpe.weight"]),
            "final_norm": _np(sd["transformer.ln_f.weight"]),
            "final_norm_b": _np(sd["transformer.ln_f.bias"]),
            "layers": layers,
        }
        return cfg, params


class DistilBertPolicy(InjectionPolicy):
    """HF ``DistilBertForMaskedLM`` (reference ``containers/distil_bert.py``
    ``HFDistilBertLayerPolicy``).  BERT post-LN encoder without token-type
    embeddings → ``BertEncoder`` with a 1-entry (all-zero) type table."""

    model_types = ("distilbert",)

    @classmethod
    def model_cls(cls):
        from deepspeed_tpu.models.bert import BertEncoder
        return BertEncoder

    @classmethod
    def build(cls, hf, sd):
        from deepspeed_tpu.models.bert import BertConfig
        d, L = hf.dim, hf.n_layers
        cfg = BertConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L,
            n_heads=hf.n_heads, ffn_hidden_size=hf.hidden_dim,
            max_seq_len=hf.max_position_embeddings,
            type_vocab_size=1, norm_eps=1e-12)

        pre = "distilbert.transformer.layer.{}."
        layers = {
            "wq": _stack(sd, pre + "attention.q_lin.weight", L, transpose=True),
            "wk": _stack(sd, pre + "attention.k_lin.weight", L, transpose=True),
            "wv": _stack(sd, pre + "attention.v_lin.weight", L, transpose=True),
            "wo": _stack(sd, pre + "attention.out_lin.weight", L,
                         transpose=True),
            "wq_b": _stack(sd, pre + "attention.q_lin.bias", L),
            "wk_b": _stack(sd, pre + "attention.k_lin.bias", L),
            "wv_b": _stack(sd, pre + "attention.v_lin.bias", L),
            "wo_b": _stack(sd, pre + "attention.out_lin.bias", L),
            "attn_norm": _stack(sd, pre + "sa_layer_norm.weight", L),
            "attn_norm_b": _stack(sd, pre + "sa_layer_norm.bias", L),
            "w_up": _stack(sd, pre + "ffn.lin1.weight", L, transpose=True),
            "w_up_b": _stack(sd, pre + "ffn.lin1.bias", L),
            "w_down": _stack(sd, pre + "ffn.lin2.weight", L, transpose=True),
            "w_down_b": _stack(sd, pre + "ffn.lin2.bias", L),
            "mlp_norm": _stack(sd, pre + "output_layer_norm.weight", L),
            "mlp_norm_b": _stack(sd, pre + "output_layer_norm.bias", L),
        }
        params = {
            "tok_embed": _np(sd["distilbert.embeddings.word_embeddings.weight"]),
            "pos_embed": _np(
                sd["distilbert.embeddings.position_embeddings.weight"]),
            "type_embed": np.zeros((1, d), np.float32),
            "embed_norm": _np(sd["distilbert.embeddings.LayerNorm.weight"]),
            "embed_norm_b": _np(sd["distilbert.embeddings.LayerNorm.bias"]),
            "layers": layers,
            "mlm_dense": _np(sd["vocab_transform.weight"]).T,
            "mlm_dense_b": _np(sd["vocab_transform.bias"]),
            "mlm_norm": _np(sd["vocab_layer_norm.weight"]),
            "mlm_norm_b": _np(sd["vocab_layer_norm.bias"]),
            "mlm_bias": _np(sd["vocab_projector.bias"]),
        }
        return cfg, params


class CLIPPolicy(InjectionPolicy):
    """HF ``CLIPTextModel`` (reference ``containers/clip.py``
    ``HFCLIPLayerPolicy`` — the Stable Diffusion text tower).  Pre-LN
    causal encoder with quick-GELU; maps onto ``CLIPTextEncoder``."""

    model_types = ("clip_text_model", "clip")

    @classmethod
    def model_cls(cls):
        from deepspeed_tpu.models.clip import CLIPTextEncoder
        return CLIPTextEncoder

    @classmethod
    def build(cls, hf, sd):
        from deepspeed_tpu.models.clip import CLIPTextConfig
        if getattr(hf, "text_config", None) is not None:  # full CLIPConfig
            hf = hf.text_config
        d, L = hf.hidden_size, hf.num_hidden_layers
        cfg = CLIPTextConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L,
            n_heads=hf.num_attention_heads,
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            norm_eps=hf.layer_norm_eps,
            activation=("quick_gelu" if hf.hidden_act == "quick_gelu"
                        else "gelu"),
            eos_token_id=getattr(hf, "eos_token_id", 2))

        pre = "text_model.encoder.layers.{}."
        layers = {
            "attn_norm": _stack(sd, pre + "layer_norm1.weight", L),
            "attn_norm_b": _stack(sd, pre + "layer_norm1.bias", L),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.out_proj.weight", L,
                         transpose=True),
            "wq_b": _stack(sd, pre + "self_attn.q_proj.bias", L),
            "wk_b": _stack(sd, pre + "self_attn.k_proj.bias", L),
            "wv_b": _stack(sd, pre + "self_attn.v_proj.bias", L),
            "wo_b": _stack(sd, pre + "self_attn.out_proj.bias", L),
            "mlp_norm": _stack(sd, pre + "layer_norm2.weight", L),
            "mlp_norm_b": _stack(sd, pre + "layer_norm2.bias", L),
            "w_up": _stack(sd, pre + "mlp.fc1.weight", L, transpose=True),
            "w_up_b": _stack(sd, pre + "mlp.fc1.bias", L),
            "w_down": _stack(sd, pre + "mlp.fc2.weight", L, transpose=True),
            "w_down_b": _stack(sd, pre + "mlp.fc2.bias", L),
        }
        params = {
            "tok_embed": _np(
                sd["text_model.embeddings.token_embedding.weight"]),
            "pos_embed": _np(
                sd["text_model.embeddings.position_embedding.weight"]),
            "final_norm": _np(sd["text_model.final_layer_norm.weight"]),
            "final_norm_b": _np(sd["text_model.final_layer_norm.bias"]),
            "layers": layers,
        }
        return cfg, params


class FalconPolicy(InjectionPolicy):
    """HF ``FalconForCausalLM`` (falcon-7b lineage:
    ``new_decoder_architecture=False``, ``multi_query=True``,
    ``parallel_attn=True``): parallel attn+MLP residual sharing ONE
    input layernorm (duplicated into attn_norm/mlp_norm like the GPT-J
    policy), fused QKV ``[(H+2)·dh, d]`` with a single shared K/V head
    (multi-query = GQA with kv_heads=1), RoPE, GELU, biasless linears,
    tied embeddings."""

    model_types = ("falcon",)

    @classmethod
    def matches(cls, hf_config) -> bool:
        if getattr(hf_config, "model_type", None) not in cls.model_types:
            return False
        if getattr(hf_config, "new_decoder_architecture", False):
            raise ValueError(
                "Falcon new_decoder_architecture (40b/180b grouped-KV "
                "layout) is not supported yet; falcon-7b lineage only")
        if getattr(hf_config, "alibi", False) or \
                not getattr(hf_config, "parallel_attn", True):
            raise ValueError(
                "only the rotary + parallel_attn Falcon variant is "
                "supported (falcon-7b lineage)")
        if not getattr(hf_config, "multi_query", True):
            raise ValueError(
                "Falcon multi_query=False uses a per-head [H, 3, dh] QKV "
                "interleave this policy does not un-scramble yet")
        if getattr(hf_config, "bias", False):
            raise ValueError(
                "Falcon bias=True checkpoints are not supported (the "
                "falcon-7b lineage is biasless)")
        return True

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        dh = d // H
        tied = bool(getattr(hf, "tie_word_embeddings", True))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=1,                      # multi_query
            ffn_hidden_size=getattr(hf, "ffn_hidden_size", None) or 4 * d,
            max_seq_len=getattr(hf, "max_position_embeddings", 2048),
            rope_theta=float(getattr(hf, "rope_theta", 10000.0)),
            norm_eps=hf.layer_norm_epsilon, activation="gelu",
            use_rmsnorm=False, use_rope=True, norm_bias=True,
            parallel_block=True, tie_embeddings=tied, remat=False)

        pre = "transformer.h.{}."
        ln_w = _stack(sd, pre + "input_layernorm.weight", L)
        ln_b = _stack(sd, pre + "input_layernorm.bias", L)
        wq, wk, wv = [], [], []
        for i in range(L):
            qkv = _np(sd[pre.format(i) +
                         "self_attention.query_key_value.weight"])
            wq.append(qkv[:H * dh].T)          # [d, H*dh]
            wk.append(qkv[H * dh:(H + 1) * dh].T)
            wv.append(qkv[(H + 1) * dh:].T)
        layers = {
            # one LN feeds both parallel branches (GPT-J duplication trick)
            "attn_norm": ln_w, "attn_norm_b": ln_b,
            "mlp_norm": ln_w.copy(), "mlp_norm_b": ln_b.copy(),
            "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
            "wo": _stack(sd, pre + "self_attention.dense.weight", L,
                         transpose=True),
            "w_up": _stack(sd, pre + "mlp.dense_h_to_4h.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "mlp.dense_4h_to_h.weight", L,
                             transpose=True),
        }
        params = {
            "tok_embed": _np(sd["transformer.word_embeddings.weight"]),
            "final_norm": _np(sd["transformer.ln_f.weight"]),
            "final_norm_b": _np(sd["transformer.ln_f.bias"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


def _megatron_qkv(sd, key_w, key_b, H, dh, d, v2):
    """Un-scramble one layer's fused Megatron QKV (both checkpoint
    layouts): v2 per-head ``[H, 3, dh, d]`` interleave, v0/v1 ``[3, H*dh]``
    row groups.  Returns ([wq, wk, wv] as [d, H*dh], [bq, bk, bv])."""
    w = _np(sd[key_w])
    b = _np(sd[key_b])
    if v2:
        w = w.reshape(H, 3, dh, d)
        b = b.reshape(H, 3, dh)
        return ([w[:, j].reshape(H * dh, d).T for j in range(3)],
                [b[:, j].reshape(-1) for j in range(3)])
    w = w.reshape(3, H * dh, d)
    b = b.reshape(3, H * dh)
    return [w[j].T for j in range(3)], [b[j] for j in range(3)]


class MegatronGPTPolicy(InjectionPolicy):
    """Megatron-LM GPT checkpoints (reference ``containers/megatron_gpt.py``
    ``MegatronLayerPolicy``, whose ``version`` field selects the same two
    QKV fusions; the MoE variant in ``megatron_gpt_moe.py``).

    QKV layouts by ``checkpoint_version`` (hf config attr, default 2):
    * >= 2: per-head ``[H, 3, dh]`` interleave (modern Megatron raw
      layout — what HF's convert_megatron_gpt2_checkpoint.py un-scrambles)
    * < 2 (v0/v1): ``[3, H*dh]`` row groups (all Q rows, then K, then V)

    Learned positions, GELU, pre-LN, tied embeddings."""

    model_types = ("megatron-lm", "megatron_gpt", "megatron")

    @classmethod
    def build(cls, hf, sd):
        d = getattr(hf, "hidden_size")
        L = getattr(hf, "num_layers", None) or hf.num_hidden_layers
        H = getattr(hf, "num_attention_heads")
        megatron_v2 = float(getattr(hf, "checkpoint_version", 2.0) or 0) >= 2
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            ffn_hidden_size=getattr(hf, "ffn_hidden_size", None) or 4 * d,
            max_seq_len=getattr(hf, "max_position_embeddings", 1024),
            norm_eps=getattr(hf, "layernorm_epsilon", 1e-5),
            activation="gelu", use_rmsnorm=False, use_rope=False,
            use_bias=True, norm_bias=True, tie_embeddings=True, remat=False)

        pre = "language_model.transformer.layers.{}."
        dh = d // H
        wq, wk, wv, bq, bk, bv = [], [], [], [], [], []
        for i in range(L):
            (q, k, v), (qb, kb, vb) = _megatron_qkv(
                sd, pre.format(i) + "attention.query_key_value.weight",
                pre.format(i) + "attention.query_key_value.bias",
                H, dh, d, megatron_v2)
            wq.append(q); wk.append(k); wv.append(v)
            bq.append(qb); bk.append(kb); bv.append(vb)
        layers = {
            "attn_norm": _stack(sd, pre + "input_layernorm.weight", L),
            "attn_norm_b": _stack(sd, pre + "input_layernorm.bias", L),
            "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
            "wq_b": np.stack(bq), "wk_b": np.stack(bk), "wv_b": np.stack(bv),
            "wo": _stack(sd, pre + "attention.dense.weight", L,
                         transpose=True),
            "wo_b": _stack(sd, pre + "attention.dense.bias", L),
            "mlp_norm": _stack(sd, pre + "post_attention_layernorm.weight",
                               L),
            "mlp_norm_b": _stack(sd, pre + "post_attention_layernorm.bias",
                                 L),
            "w_up": _stack(sd, pre + "mlp.dense_h_to_4h.weight", L,
                           transpose=True),
            "w_up_b": _stack(sd, pre + "mlp.dense_h_to_4h.bias", L),
            "w_down": _stack(sd, pre + "mlp.dense_4h_to_h.weight", L,
                             transpose=True),
            "w_down_b": _stack(sd, pre + "mlp.dense_4h_to_h.bias", L),
        }
        emb = "language_model.embedding."
        params = {
            "tok_embed": _np(sd[emb + "word_embeddings.weight"]),
            "pos_embed": _np(sd[emb + "position_embeddings.weight"]),
            "final_norm": _np(
                sd["language_model.transformer.final_layernorm.weight"]),
            "final_norm_b": _np(
                sd["language_model.transformer.final_layernorm.bias"]),
            "layers": layers,
        }
        return cfg, params


class MegatronGPTMoEPolicy(InjectionPolicy):
    """Megatron-DeepSpeed MoE checkpoints (reference
    ``containers/megatron_gpt_moe.py`` ``MegatronMoELayerPolicy``): GPT
    attention blocks + ``mlp.deepspeed_moe`` expert FFNs on a subset of
    layers.

    Checkpoint keys per MoE layer ``i`` (reference MoE param naming):
      ``...layers.{i}.mlp.deepspeed_moe.gate.wg.weight``          [E, d]
      ``...layers.{i}.mlp.deepspeed_moe.experts.deepspeed_experts.{e}.
         dense_h_to_4h.{weight,bias}``                            [f, d]/[f]
      ``...dense_4h_to_h.{weight,bias}``                          [d, f]/[d]
    Dense layers keep plain ``mlp.dense_h_to_4h``/``dense_4h_to_h``.

    Emits the MoE params layout (``layers`` = LIST of per-layer dicts,
    expert leaves stacked to [E, ...] — the ep-sharded serve/train layout).
    """

    model_types = ("megatron-moe", "megatron_gpt_moe", "megatron-deepspeed-moe")

    @staticmethod
    def _num_experts(hf_config) -> int:
        # Megatron-DeepSpeed stores num_experts as a per-layer-group LIST
        # (e.g. [8]); configs/shims may also carry a plain int
        n = getattr(hf_config, "num_experts", 0) or 0
        if isinstance(n, (list, tuple)):
            n = n[0] if n else 0
        return int(n)

    @classmethod
    def matches(cls, hf_config) -> bool:
        mt = (getattr(hf_config, "model_type", "") or "").lower()
        return mt in cls.model_types or (
            "megatron" in mt and cls._num_experts(hf_config) > 1)

    @classmethod
    def build(cls, hf, sd):
        d = getattr(hf, "hidden_size")
        L = getattr(hf, "num_layers", None) or hf.num_hidden_layers
        H = getattr(hf, "num_attention_heads")
        E = cls._num_experts(hf)
        f = getattr(hf, "ffn_hidden_size", None) or 4 * d
        megatron_v2 = float(getattr(hf, "checkpoint_version", 2.0) or 0) >= 2
        dh = d // H
        pre = "language_model.transformer.layers.{}."

        moe_flags = [
            pre.format(i) + "mlp.deepspeed_moe.gate.wg.weight" in sd
            for i in range(L)]
        assert any(moe_flags), "no deepspeed_moe layers found in state dict"
        # infer the layer frequency our config encodes (reference models
        # place experts every Nth layer, MoE on the LAST of each group)
        first = moe_flags.index(True)
        freq = first + 1
        assert all(moe_flags[i] == (i % freq == freq - 1)
                   for i in range(L)), \
            f"MoE layer pattern {moe_flags} is not an every-Nth-layer grid"

        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            ffn_hidden_size=f,
            max_seq_len=getattr(hf, "max_position_embeddings", 1024),
            norm_eps=getattr(hf, "layernorm_epsilon", 1e-5),
            activation="gelu", use_rmsnorm=False, use_rope=False,
            use_bias=True, norm_bias=True, tie_embeddings=True, remat=False,
            moe_num_experts=E, moe_layer_freq=freq,
            moe_top_k=int(getattr(hf, "moe_top_k", 1) or 1))

        def qkv(i):
            return _megatron_qkv(
                sd, pre.format(i) + "attention.query_key_value.weight",
                pre.format(i) + "attention.query_key_value.bias",
                H, dh, d, megatron_v2)

        layers = []
        for i in range(L):
            p = pre.format(i)
            (wq, wk, wv), (bq, bk, bv) = qkv(i)
            layer = {
                "attn_norm": _np(sd[p + "input_layernorm.weight"]),
                "attn_norm_b": _np(sd[p + "input_layernorm.bias"]),
                "wq": wq, "wk": wk, "wv": wv,
                "wq_b": bq, "wk_b": bk, "wv_b": bv,
                "wo": _np(sd[p + "attention.dense.weight"]).T,
                "wo_b": _np(sd[p + "attention.dense.bias"]),
                "mlp_norm": _np(sd[p + "post_attention_layernorm.weight"]),
                "mlp_norm_b": _np(sd[p + "post_attention_layernorm.bias"]),
            }
            if moe_flags[i]:
                ex = p + "mlp.deepspeed_moe.experts.deepspeed_experts.{}."
                layer["moe"] = {
                    # gate stays fp32 (reference casts gate input to fp32)
                    "wg": _np(sd[p + "mlp.deepspeed_moe.gate.wg.weight"])
                    .T.astype(np.float32),
                    "w_up": np.stack([
                        _np(sd[ex.format(e) + "dense_h_to_4h.weight"]).T
                        for e in range(E)]),
                    "w_up_b": np.stack([
                        _np(sd[ex.format(e) + "dense_h_to_4h.bias"])
                        for e in range(E)]),
                    "w_down": np.stack([
                        _np(sd[ex.format(e) + "dense_4h_to_h.weight"]).T
                        for e in range(E)]),
                    "w_down_b": np.stack([
                        _np(sd[ex.format(e) + "dense_4h_to_h.bias"])
                        for e in range(E)]),
                }
            else:
                layer["w_up"] = _np(sd[p + "mlp.dense_h_to_4h.weight"]).T
                layer["w_up_b"] = _np(sd[p + "mlp.dense_h_to_4h.bias"])
                layer["w_down"] = _np(sd[p + "mlp.dense_4h_to_h.weight"]).T
                layer["w_down_b"] = _np(sd[p + "mlp.dense_4h_to_h.bias"])
            layers.append(layer)

        emb = "language_model.embedding."
        params = {
            "tok_embed": _np(sd[emb + "word_embeddings.weight"]),
            "pos_embed": _np(sd[emb + "position_embeddings.weight"]),
            "final_norm": _np(
                sd["language_model.transformer.final_layernorm.weight"]),
            "final_norm_b": _np(
                sd["language_model.transformer.final_layernorm.bias"]),
            "layers": layers,
        }
        return cfg, params


class PhiPolicy(InjectionPolicy):
    """HF ``PhiForCausalLM`` (phi-1/1.5/2 lineage; the reference's
    injection matrix covers the same era of decoder archs under
    ``module_inject/containers/``).  GPT-J-shaped: parallel attn+MLP
    residual sharing ONE LayerNorm (duplicated into both sub-block
    norms), partial rotary (``partial_rotary_factor``, half-rope layout
    like GPT-NeoX — no interleave permutation needed), biases on every
    linear, tanh-GELU MLP, biased LM head, final LayerNorm."""

    model_types = ("phi",)

    @classmethod
    def matches(cls, hf_config) -> bool:
        if getattr(hf_config, "model_type", None) not in cls.model_types:
            return False
        if getattr(hf_config, "qk_layernorm", False):
            raise ValueError("phi qk_layernorm is not supported yet")
        return True

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        dh = d // H
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        rot = int(round(getattr(hf, "partial_rotary_factor", 1.0) * dh))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 10000.0)),
            norm_eps=hf.layer_norm_eps, activation="gelu",
            use_rmsnorm=False, use_rope=True,
            rope_dim=(None if rot == dh else rot),
            # partial rotary: the scaled table covers the ROTATED slice
            # (raises on dynamic/yarn — no silent unscaled conversion)
            rope_inv_freq=_rope_scaled_inv_freq(hf, rot),
            parallel_block=True, use_bias=True, norm_bias=True,
            tie_embeddings=False, lm_head_bias=True, remat=False)

        pre = "model.layers.{}."
        ln_w = _stack(sd, pre + "input_layernorm.weight", L)
        ln_b = _stack(sd, pre + "input_layernorm.bias", L)
        layers = {
            # one shared LN feeds both parallel branches (GPT-J trick)
            "attn_norm": ln_w, "attn_norm_b": ln_b,
            "mlp_norm": ln_w.copy(), "mlp_norm_b": ln_b.copy(),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wq_b": _stack(sd, pre + "self_attn.q_proj.bias", L),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wk_b": _stack(sd, pre + "self_attn.k_proj.bias", L),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wv_b": _stack(sd, pre + "self_attn.v_proj.bias", L),
            "wo": _stack(sd, pre + "self_attn.dense.weight", L,
                         transpose=True),
            "wo_b": _stack(sd, pre + "self_attn.dense.bias", L),
            "w_up": _stack(sd, pre + "mlp.fc1.weight", L, transpose=True),
            "w_up_b": _stack(sd, pre + "mlp.fc1.bias", L),
            "w_down": _stack(sd, pre + "mlp.fc2.weight", L, transpose=True),
            "w_down_b": _stack(sd, pre + "mlp.fc2.bias", L),
        }
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.final_layernorm.weight"]),
            "final_norm_b": _np(sd["model.final_layernorm.bias"]),
            "lm_head": _np(sd["lm_head.weight"]).T,
            "lm_head_b": _np(sd["lm_head.bias"]),
            "layers": layers,
        }
        return cfg, params


class StableLmPolicy(InjectionPolicy):
    """HF ``StableLmForCausalLM`` (stablelm-3b/zephyr lineage): llama
    wiring (SwiGLU MLP, GQA, o_proj) but LayerNorm-with-bias instead of
    RMSNorm, partial rotary (``partial_rotary_factor``), optional QKV
    biases (``use_qkv_bias``, presence-based like Qwen2)."""

    model_types = ("stablelm",)

    @classmethod
    def matches(cls, hf_config) -> bool:
        if getattr(hf_config, "model_type", None) not in cls.model_types:
            return False
        if getattr(hf_config, "use_parallel_residual", False):
            raise ValueError(
                "stablelm use_parallel_residual=True (stablelm-2 lineage) "
                "shares norms differently and is not supported yet")
        if getattr(hf_config, "qk_layernorm", False):
            raise ValueError("stablelm qk_layernorm is not supported yet")
        return True

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        dh = d // H
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        rot = int(round(getattr(hf, "partial_rotary_factor", 1.0) * dh))
        tied = bool(getattr(hf, "tie_word_embeddings", False))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 10000.0)),
            norm_eps=hf.layer_norm_eps, activation="silu",
            use_rmsnorm=False, norm_bias=True, use_rope=True,
            rope_dim=(None if rot == dh else rot),
            # partial rotary: the scaled table covers the ROTATED slice
            # (raises on dynamic/yarn — no silent unscaled conversion)
            rope_inv_freq=_rope_scaled_inv_freq(hf, rot),
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."
        layers = {
            "attn_norm": _stack(sd, pre + "input_layernorm.weight", L),
            "attn_norm_b": _stack(sd, pre + "input_layernorm.bias", L),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "mlp_norm": _stack(sd, pre + "post_attention_layernorm.weight",
                               L),
            "mlp_norm_b": _stack(sd, pre + "post_attention_layernorm.bias",
                                 L),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L,
                             transpose=True),
        }
        if pre.format(0) + "self_attn.q_proj.bias" in sd:  # use_qkv_bias
            layers["wq_b"] = _stack(sd, pre + "self_attn.q_proj.bias", L)
            layers["wk_b"] = _stack(sd, pre + "self_attn.k_proj.bias", L)
            layers["wv_b"] = _stack(sd, pre + "self_attn.v_proj.bias", L)
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "final_norm_b": _np(sd["model.norm.bias"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class MptPolicy(InjectionPolicy):
    """HF ``MptForCausalLM`` (mpt-7b lineage: ``no_bias=True``, ALiBi):
    fused ``Wqkv [3d, d]`` split by rows, biasless LayerNorms, ALiBi
    attention with no position embeddings (Bloom-style slopes), GELU
    MLP, tied embeddings."""

    model_types = ("mpt",)

    @classmethod
    def matches(cls, hf_config) -> bool:
        if getattr(hf_config, "model_type", None) not in cls.model_types:
            return False
        attn_cfg = getattr(hf_config, "attn_config", None)
        alibi = getattr(attn_cfg, "alibi", True) if attn_cfg is not None \
            else True
        if not alibi:
            raise ValueError(
                "mpt with attn_config.alibi=False (learned positions) is "
                "not supported yet")
        if not getattr(hf_config, "no_bias", True):
            raise ValueError(
                "mpt no_bias=False checkpoints are not supported (the "
                "mpt-7b lineage is biasless)")
        if attn_cfg is not None:
            if getattr(attn_cfg, "clip_qkv", None):
                raise ValueError(
                    "mpt attn_config.clip_qkv (mpt-30b lineage) is not "
                    "supported — the converted model would silently skip "
                    "the QKV clamp")
            if getattr(attn_cfg, "qk_ln", False):
                raise ValueError(
                    "mpt attn_config.qk_ln (replit-code lineage) is not "
                    "supported yet")
            if getattr(attn_cfg, "softmax_scale", None):
                raise ValueError(
                    "mpt attn_config.softmax_scale overrides are not "
                    "supported yet")
        if getattr(hf_config, "logit_scale", None):
            raise ValueError("mpt logit_scale is not supported yet")
        H = getattr(hf_config, "n_heads", 1)
        bias_max = getattr(attn_cfg, "alibi_bias_max", 8) \
            if attn_cfg is not None else 8
        if bias_max != 8 or (H & (H - 1)):
            # MPT pads slopes to the NEXT power of two and reorders
            # [1::2]+[::2]; our alibi_slopes (models/transformer.py:302)
            # is the Bloom schedule (floor power of two + interleaved
            # extras).  They agree exactly iff H is a power of two and
            # alibi_bias_max is the default 8.
            raise ValueError(
                "mpt with non-power-of-two n_heads or non-default "
                "alibi_bias_max uses a slope schedule this policy does "
                "not reproduce")
        return True

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.d_model, hf.n_layers, hf.n_heads
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            ffn_hidden_size=getattr(hf, "expansion_ratio", 4) * d,
            max_seq_len=hf.max_seq_len,
            norm_eps=getattr(hf, "layer_norm_epsilon", 1e-5),
            activation="gelu_exact", use_rmsnorm=False, use_rope=False,
            use_alibi=True, tie_embeddings=True, remat=False)

        pre = "transformer.blocks.{}."
        wq, wk, wv = [], [], []
        for i in range(L):
            qkv = _np(sd[pre.format(i) + "attn.Wqkv.weight"])   # [3d, d]
            wq.append(qkv[:d].T)
            wk.append(qkv[d:2 * d].T)
            wv.append(qkv[2 * d:].T)
        layers = {
            "attn_norm": _stack(sd, pre + "norm_1.weight", L),
            "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
            "wo": _stack(sd, pre + "attn.out_proj.weight", L,
                         transpose=True),
            "mlp_norm": _stack(sd, pre + "norm_2.weight", L),
            "w_up": _stack(sd, pre + "ffn.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "ffn.down_proj.weight", L,
                             transpose=True),
        }
        params = {
            "tok_embed": _np(sd["transformer.wte.weight"]),
            "final_norm": _np(sd["transformer.norm_f.weight"]),
            "layers": layers,
        }
        return cfg, params


class Phi3Policy(InjectionPolicy):
    """HF ``Phi3ForCausalLM`` (phi-3-mini-4k lineage): llama wiring with
    fused ``qkv_proj [(H+2·Hkv)·dh, d]`` (q|k|v row blocks) and fused
    ``gate_up_proj [2f, d]`` (gate|up halves), RMSNorm, SwiGLU, RoPE,
    biasless, untied head.  The longrope-scaled 128k variants are
    guarded (su/longrope rescaling is not implemented)."""

    model_types = ("phi3",)

    @classmethod
    def matches(cls, hf_config) -> bool:
        if getattr(hf_config, "model_type", None) not in cls.model_types:
            return False
        if getattr(hf_config, "rope_scaling", None):
            raise ValueError(
                "phi3 rope_scaling (longrope/su 128k variants) is not "
                "supported yet; the 4k-context checkpoints convert")
        return True

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        dh = d // H
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        tied = bool(getattr(hf, "tie_word_embeddings", False))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 10000.0)),
            norm_eps=hf.rms_norm_eps, activation="silu",
            use_rmsnorm=True, use_rope=True,
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."
        f = hf.intermediate_size
        wq, wk, wv, wg, wu = [], [], [], [], []
        for i in range(L):
            qkv = _np(sd[pre.format(i) + "self_attn.qkv_proj.weight"])
            wq.append(qkv[:H * dh].T)
            wk.append(qkv[H * dh:(H + n_kv) * dh].T)
            wv.append(qkv[(H + n_kv) * dh:].T)
            gu = _np(sd[pre.format(i) + "mlp.gate_up_proj.weight"])
            wg.append(gu[:f].T)
            wu.append(gu[f:].T)
        layers = {
            "attn_norm": _stack(sd, pre + "input_layernorm.weight", L),
            "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "mlp_norm": _stack(sd, pre + "post_attention_layernorm.weight",
                               L),
            "w_gate": np.stack(wg), "w_up": np.stack(wu),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L,
                             transpose=True),
        }
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class CoherePolicy(InjectionPolicy):
    """HF ``CohereForCausalLM`` (Command-R): parallel attn+MLP residual
    sharing ONE biasless LayerNorm (GPT-J duplication), INTERLEAVED
    rotary folded into the wq/wk column permutation, SwiGLU, tied
    embeddings with a ``logit_scale`` multiplier on the head
    (``final_logit_scale``).  ``use_qk_norm`` checkpoints are guarded."""

    model_types = ("cohere",)

    @classmethod
    def matches(cls, hf_config) -> bool:
        if getattr(hf_config, "model_type", None) not in cls.model_types:
            return False
        if getattr(hf_config, "use_qk_norm", False):
            raise ValueError("cohere use_qk_norm is not supported yet")
        return True

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        dh = d // H
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        perm = _interleaved_to_half_rope_perm(dh, dh)

        def rot_cols(name, i, heads):
            w = _np(sd[f"model.layers.{i}.self_attn.{name}.weight"]).T
            return w.reshape(d, heads, dh)[:, :, perm].reshape(d, heads * dh)

        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 10000.0)),
            rope_inv_freq=_rope_scaled_inv_freq(hf, dh),
            norm_eps=hf.layer_norm_eps, activation="silu",
            use_rmsnorm=False, norm_bias=False, use_rope=True,
            parallel_block=True,
            final_logit_scale=float(hf.logit_scale),
            tie_embeddings=bool(getattr(hf, "tie_word_embeddings", True)),
            remat=False)

        pre = "model.layers.{}."
        ln = _stack(sd, pre + "input_layernorm.weight", L)
        layers = {
            # one LN feeds both parallel branches (GPT-J duplication)
            "attn_norm": ln, "mlp_norm": ln.copy(),
            "wq": np.stack([rot_cols("q_proj", i, H) for i in range(L)]),
            "wk": np.stack([rot_cols("k_proj", i, n_kv) for i in range(L)]),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L,
                             transpose=True),
        }
        # NB: q/k biases would need the same interleave permutation as
        # the weights; cohere ships attention_bias=False, so guard
        if pre.format(0) + "self_attn.q_proj.bias" in sd:
            raise ValueError(
                "cohere attention_bias=True checkpoints are not "
                "supported (bias would need the rotary column fold)")
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "layers": layers,
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class Olmo2Policy(InjectionPolicy):
    """HF ``Olmo2ForCausalLM``: POST-norm-only blocks
    (``x + post_attn_norm(attn(x))`` — no pre-norms at all; the layer
    simply omits ``attn_norm``/``mlp_norm`` and ships the sandwich
    post-norm keys) plus FLAT q/k RMSNorm over the whole projection
    (``qk_norm="rms_flat"``, weights [H·dh]/[Hkv·dh], variance pooled
    across heads), RMSNorm final norm, SwiGLU, RoPE, untied head."""

    model_types = ("olmo2",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        tied = bool(getattr(hf, "tie_word_embeddings", False))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 5e5)),
            rope_inv_freq=_rope_scaled_inv_freq(hf, d // H),
            norm_eps=hf.rms_norm_eps, activation="silu",
            use_rmsnorm=True, use_rope=True, qk_norm="rms_flat",
            post_norm_only=True,
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."
        layers = {
            # NO pre-norms: post-norm keys only (post_norm_only makes
            # the model treat the absent pre-norm weights as identity)
            "attn_post_norm": _stack(
                sd, pre + "post_attention_layernorm.weight", L),
            "mlp_post_norm": _stack(
                sd, pre + "post_feedforward_layernorm.weight", L),
            "q_norm": _stack(sd, pre + "self_attn.q_norm.weight", L),
            "k_norm": _stack(sd, pre + "self_attn.k_norm.weight", L),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L,
                             transpose=True),
        }
        for name, key in (("wq_b", "q_proj"), ("wk_b", "k_proj"),
                          ("wv_b", "v_proj"), ("wo_b", "o_proj")):
            if pre.format(0) + f"self_attn.{key}.bias" in sd:
                layers[name] = _stack(sd, pre + f"self_attn.{key}.bias", L)
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class DbrxPolicy(InjectionPolicy):
    """HF ``DbrxForCausalLM``: fused ``Wqkv`` with a mandatory pre-rope
    clamp (``clip_qkv``), biasless LayerNorms, and top-4 MoE whose
    experts are PACKED tensors ``w1/v1/w2 [E·f, d]`` (w1=gate, v1=up —
    both used transposed; w2=down used untransposed, i.e. already this
    repo's ``[E, f, d]`` layout).  Router renormalization
    ``moe_normalize_expert_weights=1`` is exactly ``topkgating``'s
    sum-renorm; other p-norms are guarded."""

    model_types = ("dbrx",)

    @classmethod
    def matches(cls, hf_config) -> bool:
        if getattr(hf_config, "model_type", None) not in cls.model_types:
            return False
        ffn = getattr(hf_config, "ffn_config", None)
        p = getattr(ffn, "moe_normalize_expert_weights", 1.0) \
            if ffn is not None else 1.0
        if p is not None and float(p) != 1.0:
            raise ValueError(
                "dbrx moe_normalize_expert_weights != 1 (p-norm "
                "renormalization) is not supported; 1 (sum) and None "
                "(no renorm) convert")
        act = getattr(ffn, "ffn_act_fn", None) if ffn is not None else None
        if act and act.get("name", "silu") != "silu":
            raise ValueError("dbrx non-silu expert activation is not "
                             "supported yet")
        return True

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.d_model, hf.n_layers, hf.n_heads
        dh = d // H
        ac, fc = hf.attn_config, hf.ffn_config
        n_kv = ac.kv_n_heads
        E, f = fc.moe_num_experts, fc.ffn_hidden_size
        renorm = fc.moe_normalize_expert_weights is not None
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=f, max_seq_len=hf.max_seq_len,
            rope_theta=float(getattr(ac, "rope_theta", 5e5)),
            clip_qkv=(float(ac.clip_qkv) if ac.clip_qkv else None),
            norm_eps=1e-5, activation="silu",
            use_rmsnorm=False, norm_bias=False, use_rope=True,
            moe_num_experts=E, moe_top_k=fc.moe_top_k, moe_layer_freq=1,
            moe_norm_topk_prob=renorm,
            moe_eval_capacity_factor=float(E),
            tie_embeddings=bool(getattr(hf, "tie_word_embeddings", False)),
            remat=False)

        pre = "transformer.blocks.{}."
        layers = []
        for i in range(L):
            qkv = _np(sd[pre.format(i) + "norm_attn_norm.attn.Wqkv.weight"])
            w1 = _np(sd[pre.format(i) + "ffn.experts.mlp.w1"])
            v1 = _np(sd[pre.format(i) + "ffn.experts.mlp.v1"])
            w2 = _np(sd[pre.format(i) + "ffn.experts.mlp.w2"])
            layers.append({
                "attn_norm": _np(sd[pre.format(i) +
                                    "norm_attn_norm.norm_1.weight"]),
                "wq": qkv[:H * dh].T,
                "wk": qkv[H * dh:(H + n_kv) * dh].T,
                "wv": qkv[(H + n_kv) * dh:].T,
                "wo": _np(sd[pre.format(i) +
                             "norm_attn_norm.attn.out_proj.weight"]).T,
                "mlp_norm": _np(sd[pre.format(i) +
                                   "norm_attn_norm.norm_2.weight"]),
                "moe": {
                    "wg": _np(sd[pre.format(i) +
                                 "ffn.router.layer.weight"]).T,
                    # packed [E*f, d]: gate/up transpose per expert,
                    # down is already [E, f, d]
                    "w_gate": w1.reshape(E, f, d).transpose(0, 2, 1),
                    "w_up": v1.reshape(E, f, d).transpose(0, 2, 1),
                    "w_down": w2.reshape(E, f, d),
                },
            })
        params = {
            "tok_embed": _np(sd["transformer.wte.weight"]),
            "final_norm": _np(sd["transformer.norm_f.weight"]),
            "layers": layers,
        }
        if "lm_head.weight" in sd:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class OlmoPolicy(InjectionPolicy):
    """HF ``OlmoForCausalLM``: llama wiring under NON-PARAMETRIC
    LayerNorm (no weight, no bias — converted as all-ones weights),
    SwiGLU, RoPE, untied head, optional pre-rope QKV clamp
    (``clip_qkv``)."""

    model_types = ("olmo",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        tied = bool(getattr(hf, "tie_word_embeddings", False))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 10000.0)),
            rope_inv_freq=_rope_scaled_inv_freq(hf, d // H),
            clip_qkv=(float(hf.clip_qkv) if getattr(hf, "clip_qkv", None)
                      else None),
            norm_eps=1e-5, activation="silu",
            use_rmsnorm=False, norm_bias=False, use_rope=True,
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."
        ones = np.ones((L, d), np.float32)
        layers = {
            # non-parametric LayerNorms → identity weights
            "attn_norm": ones, "mlp_norm": ones.copy(),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L,
                             transpose=True),
        }
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": np.ones((d,), np.float32),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class GranitePolicy(InjectionPolicy):
    """HF ``GraniteForCausalLM``: llama wiring plus four scalar
    multipliers — ``embedding_multiplier`` (→ ``embed_scale``),
    ``attention_multiplier`` (→ ``attn_scale``), ``residual_multiplier``
    on every sub-block residual add (→ ``residual_scale``), and
    ``logits_scaling`` which DIVIDES head logits
    (→ ``final_logit_scale = 1/logits_scaling``)."""

    model_types = ("granite",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        tied = bool(getattr(hf, "tie_word_embeddings", True))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 1e4)),
            rope_inv_freq=_rope_scaled_inv_freq(hf, d // H),
            norm_eps=hf.rms_norm_eps, activation="silu",
            use_rmsnorm=True, use_rope=True,
            embed_scale=float(hf.embedding_multiplier),
            attn_scale=float(hf.attention_multiplier),
            residual_scale=float(hf.residual_multiplier),
            final_logit_scale=1.0 / float(hf.logits_scaling),
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."
        layers = {
            "attn_norm": _stack(sd, pre + "input_layernorm.weight", L),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "mlp_norm": _stack(sd, pre + "post_attention_layernorm.weight",
                               L),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L,
                             transpose=True),
        }
        if pre.format(0) + "self_attn.q_proj.bias" in sd:
            for name, key in (("wq_b", "q_proj"), ("wk_b", "k_proj"),
                              ("wv_b", "v_proj"), ("wo_b", "o_proj")):
                layers[name] = _stack(sd, pre + f"self_attn.{key}.bias", L)
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class Starcoder2Policy(InjectionPolicy):
    """HF ``Starcoder2ForCausalLM``: llama wiring under
    LayerNorm-with-bias, biased linears throughout (``use_bias``),
    tanh-GELU ``c_fc/c_proj`` MLP, RoPE, GQA, optional uniform sliding
    window, tied embeddings."""

    model_types = ("starcoder2",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        tied = bool(getattr(hf, "tie_word_embeddings", True))
        window = getattr(hf, "sliding_window", None)
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 1e4)),
            rope_inv_freq=_rope_scaled_inv_freq(hf, d // H),
            norm_eps=hf.norm_epsilon, activation="gelu",
            use_rmsnorm=False, norm_bias=True, use_rope=True,
            use_bias=bool(getattr(hf, "use_bias", True)),
            local_attn_pattern=((int(window),) * L if window else None),
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."
        layers = {
            "attn_norm": _stack(sd, pre + "input_layernorm.weight", L),
            "attn_norm_b": _stack(sd, pre + "input_layernorm.bias", L),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "mlp_norm": _stack(sd, pre + "post_attention_layernorm.weight",
                               L),
            "mlp_norm_b": _stack(sd, pre + "post_attention_layernorm.bias",
                                 L),
            "w_up": _stack(sd, pre + "mlp.c_fc.weight", L, transpose=True),
            "w_down": _stack(sd, pre + "mlp.c_proj.weight", L,
                             transpose=True),
        }
        if getattr(hf, "use_bias", True):
            for name, key in (("wq_b", "self_attn.q_proj"),
                              ("wk_b", "self_attn.k_proj"),
                              ("wv_b", "self_attn.v_proj"),
                              ("wo_b", "self_attn.o_proj"),
                              ("w_up_b", "mlp.c_fc"),
                              ("w_down_b", "mlp.c_proj")):
                layers[name] = _stack(sd, pre + key + ".bias", L)
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "final_norm_b": _np(sd["model.norm.bias"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class Qwen3Policy(InjectionPolicy):
    """HF ``Qwen3ForCausalLM``: llama wiring plus per-head RMSNorm on q
    and k over ``head_dim`` pre-rope (``qk_norm="rms"``; weight [dh]
    broadcasts over heads), explicit ``head_dim``, biasless linears.
    Sliding-window variants are guarded."""

    model_types = ("qwen3",)

    @classmethod
    def matches(cls, hf_config) -> bool:
        if getattr(hf_config, "model_type", None) not in cls.model_types:
            return False
        if getattr(hf_config, "use_sliding_window", False):
            raise ValueError(
                "qwen3 use_sliding_window is not supported yet")
        return True

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        dh = getattr(hf, "head_dim", None) or d // H
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        tied = bool(getattr(hf, "tie_word_embeddings", False))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            head_dim_override=(None if dh == d // H else dh),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 1e6)),
            rope_inv_freq=_rope_scaled_inv_freq(hf, dh),
            norm_eps=hf.rms_norm_eps, activation="silu",
            use_rmsnorm=True, use_rope=True, qk_norm="rms",
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."
        layers = {
            "attn_norm": _stack(sd, pre + "input_layernorm.weight", L),
            "q_norm": _stack(sd, pre + "self_attn.q_norm.weight", L),
            "k_norm": _stack(sd, pre + "self_attn.k_norm.weight", L),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "mlp_norm": _stack(sd, pre + "post_attention_layernorm.weight",
                               L),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L,
                             transpose=True),
        }
        for name, key in (("wq_b", "q_proj"), ("wk_b", "k_proj"),
                          ("wv_b", "v_proj"), ("wo_b", "o_proj")):
            if pre.format(0) + f"self_attn.{key}.bias" in sd:
                layers[name] = _stack(sd, pre + f"self_attn.{key}.bias", L)
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class Qwen2MoEPolicy(InjectionPolicy):
    """HF ``Qwen2MoeForCausalLM``: qwen2 attention (q/k/v biases) +
    per-layer top-k MoE (``norm_topk_prob`` honored — qwen2-moe ships
    False, i.e. raw softmax mass) + an always-on SHARED SwiGLU expert
    scaled by a sigmoid gate (``shared_expert_gate``), served through
    this repo's general ``topkgating``.  Heterogeneous layer layouts
    (``decoder_sparse_step != 1`` / ``mlp_only_layers``) are guarded."""

    model_types = ("qwen2_moe",)

    @classmethod
    def matches(cls, hf_config) -> bool:
        if getattr(hf_config, "model_type", None) not in cls.model_types:
            return False
        if getattr(hf_config, "decoder_sparse_step", 1) != 1 or \
                list(getattr(hf_config, "mlp_only_layers", []) or []):
            raise ValueError(
                "qwen2_moe with decoder_sparse_step != 1 or mlp_only_layers "
                "(mixed dense/MoE stacks beyond every-Nth) is not "
                "supported yet")
        return True

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        E = hf.num_experts
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        tied = bool(getattr(hf, "tie_word_embeddings", False))
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.moe_intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 1e6)),
            norm_eps=hf.rms_norm_eps, activation="silu",
            use_rmsnorm=True, use_rope=True,
            moe_num_experts=E, moe_top_k=hf.num_experts_per_tok,
            moe_layer_freq=1,
            moe_norm_topk_prob=bool(getattr(hf, "norm_topk_prob", False)),
            moe_eval_capacity_factor=float(E),
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."

        def experts(i, which):                     # [E, in, out]
            return np.stack([
                _np(sd[pre.format(i) +
                       f"mlp.experts.{e}.{which}.weight"]).T
                for e in range(E)])

        layers = []
        for i in range(L):
            lay = {
                "attn_norm": _np(sd[pre.format(i) +
                                    "input_layernorm.weight"]),
                "wq": _np(sd[pre.format(i) +
                             "self_attn.q_proj.weight"]).T,
                "wq_b": _np(sd[pre.format(i) + "self_attn.q_proj.bias"]),
                "wk": _np(sd[pre.format(i) +
                             "self_attn.k_proj.weight"]).T,
                "wk_b": _np(sd[pre.format(i) + "self_attn.k_proj.bias"]),
                "wv": _np(sd[pre.format(i) +
                             "self_attn.v_proj.weight"]).T,
                "wv_b": _np(sd[pre.format(i) + "self_attn.v_proj.bias"]),
                "wo": _np(sd[pre.format(i) +
                             "self_attn.o_proj.weight"]).T,
                "mlp_norm": _np(sd[pre.format(i) +
                                   "post_attention_layernorm.weight"]),
                "moe": {
                    "wg": _np(sd[pre.format(i) + "mlp.gate.weight"]).T,
                    "w_gate": experts(i, "gate_proj"),
                    "w_up": experts(i, "up_proj"),
                    "w_down": experts(i, "down_proj"),
                    "shared": {
                        "wg": _np(sd[pre.format(i) +
                                     "mlp.shared_expert_gate.weight"]).T,
                        "w_gate": _np(sd[pre.format(i) +
                                         "mlp.shared_expert.gate_proj"
                                         ".weight"]).T,
                        "w_up": _np(sd[pre.format(i) +
                                       "mlp.shared_expert.up_proj"
                                       ".weight"]).T,
                        "w_down": _np(sd[pre.format(i) +
                                         "mlp.shared_expert.down_proj"
                                         ".weight"]).T,
                    },
                },
            }
            layers.append(lay)
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class Gemma2Policy(InjectionPolicy):
    """HF ``Gemma2ForCausalLM``: Gemma wiring plus four twists — tanh
    softcapping of attention scores AND final logits
    (``attn_logit_softcap``/``final_logit_softcap``; scores capped BEFORE
    the causal/window mask, matching ``modeling_gemma2.eager_attention_
    forward``), sandwich norms (``post_attention_layernorm`` /
    ``post_feedforward_layernorm`` normalize each sub-block's OUTPUT
    pre-residual — ``attn_post_norm``/``mlp_post_norm`` layer keys),
    alternating sliding/full attention per ``layer_types`` (HF mask:
    ``q - kv < sliding_window`` — exactly this repo's window
    convention), and ``query_pre_attn_scalar**-0.5`` logit scaling.
    All (1+w) RMSNorms folded at conversion like Gemma."""

    model_types = ("gemma2",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        dh = getattr(hf, "head_dim", None) or d // H
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        window = int(hf.sliding_window)
        types = list(getattr(hf, "layer_types", None) or
                     ["sliding_attention" if (i + 1) % 2 else
                      "full_attention" for i in range(L)])
        pattern = tuple(window if t == "sliding_attention" else 0
                        for t in types)
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            head_dim_override=(None if dh == d // H else dh),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 10000.0)),
            norm_eps=hf.rms_norm_eps, activation="gelu", gated_mlp=True,
            embed_scale=float(d) ** 0.5,
            attn_scale=float(hf.query_pre_attn_scalar) ** -0.5,
            attn_logit_softcap=(float(hf.attn_logit_softcapping)
                                if hf.attn_logit_softcapping else None),
            final_logit_softcap=(float(hf.final_logit_softcapping)
                                 if hf.final_logit_softcapping else None),
            local_attn_pattern=(pattern if any(pattern) else None),
            use_rmsnorm=True, use_rope=True,
            tie_embeddings=True, remat=False)

        pre = "model.layers.{}."

        def norm1p(fmt):
            return _stack(sd, fmt, L) + 1.0      # fold Gemma's (1 + w)

        layers = {
            "attn_norm": norm1p(pre + "input_layernorm.weight"),
            # NAMING TRAP: Gemma2's "post_attention_layernorm" is the
            # POST-norm of the attention OUTPUT (not llama's pre-MLP norm)
            "attn_post_norm": norm1p(pre + "post_attention_layernorm"
                                     ".weight"),
            "mlp_norm": norm1p(pre + "pre_feedforward_layernorm.weight"),
            "mlp_post_norm": norm1p(pre + "post_feedforward_layernorm"
                                    ".weight"),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L,
                             transpose=True),
        }
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]) + 1.0,
            "layers": layers,
        }
        return cfg, params


class MixtralPolicy(InjectionPolicy):
    """HF ``MixtralForCausalLM``: llama attention + per-layer top-2 MoE
    with SwiGLU experts.  HF's router (softmax over ALL experts → top-2 →
    renormalize) is exactly this repo's ``top2gating`` renormalization,
    so converted logits are exact at eval given non-dropping capacity —
    ``moe_eval_capacity_factor`` is set so no token can overflow.  The
    converted tree serves expert-parallel through
    ``ServingEngine(ep_size=...)`` like Megatron-MoE checkpoints."""

    model_types = ("mixtral",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        E = hf.num_local_experts
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        tied = bool(getattr(hf, "tie_word_embeddings", False))
        if getattr(hf, "num_experts_per_tok", 2) != 2:
            raise ValueError(
                "mixtral with num_experts_per_tok != 2 is not supported "
                "(top2gating renormalization is the exact-match path)")
        window = getattr(hf, "sliding_window", None)
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 1e6)),
            norm_eps=hf.rms_norm_eps, activation="silu",
            use_rmsnorm=True, use_rope=True,
            local_attn_pattern=((int(window),) * L if window else None),
            moe_num_experts=E, moe_top_k=2, moe_layer_freq=1,
            # eval capacity >= every token to every expert: exactness
            # requires the non-dropping regime (HF routes without capacity)
            moe_eval_capacity_factor=float(E),
            tie_embeddings=tied, remat=False)

        pre = "model.layers.{}."

        def experts(i, which):                     # [E, in, out]
            return np.stack([
                _np(sd[pre.format(i) +
                       f"block_sparse_moe.experts.{e}.{which}.weight"]).T
                for e in range(E)])

        layers = []
        for i in range(L):
            layers.append({
                "attn_norm": _np(sd[pre.format(i) +
                                    "input_layernorm.weight"]),
                "wq": _np(sd[pre.format(i) +
                             "self_attn.q_proj.weight"]).T,
                "wk": _np(sd[pre.format(i) +
                             "self_attn.k_proj.weight"]).T,
                "wv": _np(sd[pre.format(i) +
                             "self_attn.v_proj.weight"]).T,
                "wo": _np(sd[pre.format(i) +
                             "self_attn.o_proj.weight"]).T,
                "mlp_norm": _np(sd[pre.format(i) +
                                   "post_attention_layernorm.weight"]),
                "moe": {
                    "wg": _np(sd[pre.format(i) +
                                 "block_sparse_moe.gate.weight"]).T,
                    "w_gate": experts(i, "w1"),    # SwiGLU gate
                    "w_down": experts(i, "w2"),
                    "w_up": experts(i, "w3"),
                },
            })
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]),
            "layers": layers,
        }
        if not tied:
            params["lm_head"] = _np(sd["lm_head.weight"]).T
        return cfg, params


class CodeGenPolicy(InjectionPolicy):
    """HF ``CodeGenForCausalLM`` (GPT-J lineage): parallel attn+MLP on one
    LayerNorm, partial INTERLEAVED rotary (GPT-J column permutation), and
    the mp_num=4 fused QKV scramble — rows are four tensor-parallel-era
    blocks each holding [q | v | k] (note the v/k swap) of d/4 rows
    (``modeling_codegen.py`` ``mp_num = 4; query, value, key =
    torch.split(qkv_split, local_dim, dim=-1)``).  Biasless attention
    linears, biased MLP + LM head, untied embeddings."""

    model_types = ("codegen",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.n_embd, hf.n_layer, hf.n_head
        dh = d // H
        rot = getattr(hf, "rotary_dim", None) or dh
        perm = _interleaved_to_half_rope_perm(rot, dh)
        mp, local = 4, d // 4

        def qvk(i):
            w = _np(sd[f"transformer.h.{i}.attn.qkv_proj.weight"])
            w4 = w.reshape(mp, 3, local, d)        # rows: [mp][q|v|k][local]
            q, v, k = (w4[:, j].reshape(d, d).T for j in range(3))
            q = q.reshape(d, H, dh)[:, :, perm].reshape(d, d)
            k = k.reshape(d, H, dh)[:, :, perm].reshape(d, d)
            return q, k, v

        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            ffn_hidden_size=getattr(hf, "n_inner", None) or 4 * d,
            max_seq_len=hf.n_positions,
            norm_eps=hf.layer_norm_epsilon, activation="gelu",
            use_rmsnorm=False, use_rope=True,
            rope_dim=(None if rot == dh else rot),
            parallel_block=True, use_bias=True, norm_bias=True,
            tie_embeddings=False, lm_head_bias=True, remat=False)

        pre = "transformer.h.{}."
        ln_w = _stack(sd, pre + "ln_1.weight", L)
        ln_b = _stack(sd, pre + "ln_1.bias", L)
        qs, ks, vs = zip(*(qvk(i) for i in range(L)))
        layers = {
            "attn_norm": ln_w, "attn_norm_b": ln_b,
            "mlp_norm": ln_w.copy(), "mlp_norm_b": ln_b.copy(),
            "wq": np.stack(qs), "wk": np.stack(ks), "wv": np.stack(vs),
            "wo": _stack(sd, pre + "attn.out_proj.weight", L,
                         transpose=True),
            "w_up": _stack(sd, pre + "mlp.fc_in.weight", L, transpose=True),
            "w_up_b": _stack(sd, pre + "mlp.fc_in.bias", L),
            "w_down": _stack(sd, pre + "mlp.fc_out.weight", L,
                             transpose=True),
            "w_down_b": _stack(sd, pre + "mlp.fc_out.bias", L),
        }
        params = {
            "tok_embed": _np(sd["transformer.wte.weight"]),
            "final_norm": _np(sd["transformer.ln_f.weight"]),
            "final_norm_b": _np(sd["transformer.ln_f.bias"]),
            "lm_head": _np(sd["lm_head.weight"]).T,
            "lm_head_b": _np(sd["lm_head.bias"]),
            "layers": layers,
        }
        return cfg, params


class GPTBigCodePolicy(InjectionPolicy):
    """HF ``GPTBigCodeForCausalLM`` (SantaCoder/StarCoder): GPT-2 wiring
    through ``nn.Linear`` ([out, in] → transpose, unlike GPT-2's Conv1D)
    with a fused ``c_attn [d + 2·kv_dim, d]`` whose K/V block is a single
    shared head when ``multi_query`` (GQA kv_heads=1), learned positions,
    tanh-GELU, biases everywhere, tied embeddings."""

    model_types = ("gpt_bigcode",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.n_embd, hf.n_layer, hf.n_head
        dh = d // H
        mq = bool(getattr(hf, "multi_query", True))
        kv = 1 if mq else H
        kv_dim = kv * dh
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(1 if mq else None),
            ffn_hidden_size=getattr(hf, "n_inner", None) or 4 * d,
            max_seq_len=hf.n_positions,
            norm_eps=hf.layer_norm_epsilon, activation="gelu",
            use_rmsnorm=False, use_rope=False, use_bias=True,
            norm_bias=True,
            attn_scale=(None if getattr(hf, "scale_attn_weights", True)
                        else 1.0),
            tie_embeddings=True, remat=False)

        pre = "transformer.h.{}."
        wq, wk, wv, bq, bk, bv = [], [], [], [], [], []
        for i in range(L):
            w = _np(sd[pre.format(i) + "attn.c_attn.weight"])  # [d+2kv, d]
            b = _np(sd[pre.format(i) + "attn.c_attn.bias"])
            if mq:
                # [q(all heads) | k(one head) | v(one head)] row blocks
                qw, kw, vw = w[:d], w[d:d + kv_dim], w[d + kv_dim:]
                qb, kb, vb = b[:d], b[d:d + kv_dim], b[d + kv_dim:]
            else:
                # MHA fuses PER HEAD: rows are [H, 3*dh] with q/k/v dh-row
                # thirds inside each head block (modeling_gpt_bigcode
                # .view(..., num_heads, 3*head_dim).split(3*[head_dim]))
                w4 = w.reshape(H, 3, dh, d)
                b3 = b.reshape(H, 3, dh)
                qw, kw, vw = (w4[:, j].reshape(H * dh, d) for j in range(3))
                qb, kb, vb = (b3[:, j].reshape(-1) for j in range(3))
            wq.append(qw.T)
            wk.append(kw.T)
            wv.append(vw.T)
            bq.append(qb)
            bk.append(kb)
            bv.append(vb)
        layers = {
            "attn_norm": _stack(sd, pre + "ln_1.weight", L),
            "attn_norm_b": _stack(sd, pre + "ln_1.bias", L),
            "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
            "wq_b": np.stack(bq), "wk_b": np.stack(bk),
            "wv_b": np.stack(bv),
            "wo": _stack(sd, pre + "attn.c_proj.weight", L, transpose=True),
            "wo_b": _stack(sd, pre + "attn.c_proj.bias", L),
            "mlp_norm": _stack(sd, pre + "ln_2.weight", L),
            "mlp_norm_b": _stack(sd, pre + "ln_2.bias", L),
            "w_up": _stack(sd, pre + "mlp.c_fc.weight", L, transpose=True),
            "w_up_b": _stack(sd, pre + "mlp.c_fc.bias", L),
            "w_down": _stack(sd, pre + "mlp.c_proj.weight", L,
                             transpose=True),
            "w_down_b": _stack(sd, pre + "mlp.c_proj.bias", L),
        }
        params = {
            "tok_embed": _np(sd["transformer.wte.weight"]),
            "pos_embed": _np(sd["transformer.wpe.weight"]),
            "final_norm": _np(sd["transformer.ln_f.weight"]),
            "final_norm_b": _np(sd["transformer.ln_f.bias"]),
            "layers": layers,
        }
        return cfg, params


class GemmaPolicy(InjectionPolicy):
    """HF ``GemmaForCausalLM``: llama wiring with three twists — RMSNorm
    applies ``(1 + w)`` (folded into the stored weight at conversion, so
    the runtime norm stays the plain Llama form), input embeddings are
    scaled by ``sqrt(hidden_size)`` (input side only: the tied LM head
    reads the UNscaled table — ``embed_scale`` config knob), and
    ``head_dim`` is explicit with ``H*dh != d`` (``head_dim_override``).
    GeGLU MLP (tanh-GELU gate, ``gated_mlp=True``)."""

    model_types = ("gemma",)

    @classmethod
    def build(cls, hf, sd):
        d, L, H = hf.hidden_size, hf.num_hidden_layers, hf.num_attention_heads
        dh = getattr(hf, "head_dim", None) or d // H
        n_kv = getattr(hf, "num_key_value_heads", None) or H
        cfg = TransformerConfig(
            vocab_size=hf.vocab_size, hidden_size=d, n_layers=L, n_heads=H,
            n_kv_heads=(None if n_kv == H else n_kv),
            head_dim_override=(None if dh == d // H else dh),
            ffn_hidden_size=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            rope_theta=float(getattr(hf, "rope_theta", 10000.0)),
            norm_eps=hf.rms_norm_eps, activation="gelu", gated_mlp=True,
            embed_scale=float(d) ** 0.5,
            use_rmsnorm=True, use_rope=True,
            tie_embeddings=True, remat=False)

        pre = "model.layers.{}."

        def norm1p(fmt):
            return _stack(sd, fmt, L) + 1.0      # fold Gemma's (1 + w)

        layers = {
            "attn_norm": norm1p(pre + "input_layernorm.weight"),
            "wq": _stack(sd, pre + "self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(sd, pre + "self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(sd, pre + "self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(sd, pre + "self_attn.o_proj.weight", L,
                         transpose=True),
            "mlp_norm": norm1p(pre + "post_attention_layernorm.weight"),
            "w_gate": _stack(sd, pre + "mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(sd, pre + "mlp.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(sd, pre + "mlp.down_proj.weight", L,
                             transpose=True),
        }
        params = {
            "tok_embed": _np(sd["model.embed_tokens.weight"]),
            "final_norm": _np(sd["model.norm.weight"]) + 1.0,
            "layers": layers,
        }
        return cfg, params


REPLACE_POLICIES: List[type] = [GPT2Policy, LlamaPolicy, OPTPolicy,
                                GPTNeoXPolicy, BertPolicy, BloomPolicy,
                                GPTJPolicy, GPTNeoPolicy, DistilBertPolicy,
                                CLIPPolicy, FalconPolicy, PhiPolicy,
                                StableLmPolicy, MptPolicy, GemmaPolicy,
                                Gemma2Policy, Phi3Policy, MixtralPolicy,
                                Qwen2MoEPolicy, Qwen3Policy,
                                Starcoder2Policy, GranitePolicy,
                                OlmoPolicy,
                                Olmo2Policy, DbrxPolicy, CoherePolicy,
                                GPTBigCodePolicy, CodeGenPolicy,
                                MegatronGPTMoEPolicy, MegatronGPTPolicy]


def find_policy(hf_config) -> Optional[type]:
    for pol in REPLACE_POLICIES:
        if pol.matches(hf_config):
            return pol
    return None
