"""Auto tensor-parallelism — sharding heuristics for models without a policy.

Parity: reference ``module_inject/auto_tp.py`` (``AutoTP``: find the linear
layers to shard without an explicit policy; row-parallel layers get an
all-reduce) and ``module_inject/layers.py`` (``LinearAllreduce`` /
``LinearLayer``).

TPU design: AutoTP emits ``tp_rules`` — ``(path_regex, PartitionSpec)``
pairs — from parameter names/shapes.  Column-parallel (output-dim) specs for
fan-out projections, row-parallel (input-dim) specs for fan-in projections;
XLA materialises the all-reduce at the row-parallel boundary.  Works on any
params pytree, so unknown architectures still get a TP plan.
"""

import re
from typing import Any, List, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import TP_AXIS

# fan-out (column-parallel: shard the LAST dim) / fan-in (row-parallel:
# shard the FIRST weight dim) name fragments, per the reference heuristics
_COLUMN_PAT = re.compile(
    r"(wq|wk|wv|w_up|w_gate|q_proj|k_proj|v_proj|up_proj|gate_proj|"
    r"c_attn|c_fc|query_key_value|fc1|lm_head|dense_h_to_4h)(?!.*_b)")
_ROW_PAT = re.compile(
    r"(wo|w_down|o_proj|out_proj|down_proj|c_proj|fc2|dense_4h_to_h|"
    r"attention\.dense)(?!.*_b)")


def get_tp_rules(params, tp_size: int = 1) -> List[Tuple[str, P]]:
    """Build tp_rules for an arbitrary params pytree.

    Known projection names get Megatron column/row splits; everything else
    stays replicated.  Only 2-D+ leaves whose candidate dim divides
    ``tp_size`` are sharded (the reference skips unshardable layers too).
    """
    rules: List[Tuple[str, P]] = []
    seen = set()

    def visit(path, leaf):
        key = jax.tree_util.keystr(path)
        shape = np.shape(leaf)
        if len(shape) < 2:
            return
        ndim = len(shape)
        if _ROW_PAT.search(key):
            # row-parallel: shard the second-to-last (input) dim
            dim = ndim - 2
            pat_kind = "row"
        elif _COLUMN_PAT.search(key):
            dim = ndim - 1
            pat_kind = "col"
        else:
            return
        if tp_size > 1 and shape[dim] % tp_size != 0:
            return
        # derive a stable regex from the leaf name (last path component)
        name = re.findall(r"[A-Za-z0-9_.]+", key)[-1]
        if (name, ndim, pat_kind) in seen:
            return
        seen.add((name, ndim, pat_kind))
        entries = [None] * ndim
        entries[dim] = TP_AXIS
        rules.append((re.escape(name) + r"'?\]?$", P(*entries)))

    jax.tree_util.tree_map_with_path(visit, params)
    return rules


class AutoTP:
    """Parity shim of the reference class surface."""

    @staticmethod
    def tp_parser(params):
        return get_tp_rules(params)
