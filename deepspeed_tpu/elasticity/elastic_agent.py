"""Elastic agent — restart-on-membership-change supervision.

Parity: reference ``elasticity/elastic_agent.py:25`` (``DSElasticAgent``
extends torch-elastic's ``LocalElasticAgent``: on a rendezvous membership
change it tears down workers and restarts them with the new world size).

TPU design: jax has no in-process rendezvous to re-enter, so the agent is a
supervisor loop around the training entrypoint: on a worker failure or an
explicit scale event it recomputes the elastic batch configuration for the
new chip count (``compute_elastic_config``) and re-invokes the entrypoint,
which resumes from the latest checkpoint (orbax reshards the ZeRO state to
the new mesh).
"""

import time
from typing import Callable, Dict, Optional

from deepspeed_tpu.elasticity.elasticity import (
    ElasticityIncompatibleWorldSize, compute_elastic_config)
from deepspeed_tpu.utils.logging import logger


class ScaleEvent(Exception):
    """Raise from the train fn to request a restart at a new world size."""

    def __init__(self, new_world_size: int):
        self.new_world_size = new_world_size
        super().__init__(f"scale to {new_world_size}")


class DSElasticAgent:

    def __init__(self, ds_config: Dict, start_world_size: int,
                 max_restarts: int = 100, restart_delay_s: float = 0.0):
        self.ds_config = ds_config
        self.world_size = start_world_size
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        self.restarts = 0

    def run(self, train_fn: Callable[[Dict, int], Optional[int]]):
        """``train_fn(ds_config, world_size)`` runs training; return value
        is the exit status (None/0 = done).  Raising ``ScaleEvent`` (or any
        exception, up to ``max_restarts``) re-enters with refreshed elastic
        batch settings."""
        while True:
            batch, valid, micro = compute_elastic_config(
                self.ds_config, world_size=self.world_size)
            cfg = dict(self.ds_config)
            cfg["train_batch_size"] = batch
            cfg["train_micro_batch_size_per_gpu"] = micro
            try:
                return train_fn(cfg, self.world_size)
            except ScaleEvent as ev:
                logger.warning(f"elastic scale event: {self.world_size} → "
                               f"{ev.new_world_size}")
                self.world_size = ev.new_world_size
            except ElasticityIncompatibleWorldSize:
                raise
            except Exception as e:  # worker failure → restart
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise
                logger.warning(f"worker failure ({e}); restart "
                               f"{self.restarts}/{self.max_restarts}")
            if self.restart_delay_s:
                time.sleep(self.restart_delay_s)
