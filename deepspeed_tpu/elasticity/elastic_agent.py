"""Elastic agent — restart-on-membership-change supervision.

Parity: reference ``elasticity/elastic_agent.py:25`` (``DSElasticAgent``
extends torch-elastic's ``LocalElasticAgent``: on a rendezvous membership
change it tears down workers and restarts them with the new world size;
liveness comes from the rendezvous keep-alive heartbeat).

TPU design: jax has no in-process rendezvous to re-enter, so the agent
supervises at two levels:

* :meth:`DSElasticAgent.run` — in-process loop around a training callable:
  a worker failure or an explicit :class:`ScaleEvent` re-enters with the
  elastic batch configuration recomputed for the new chip count
  (``compute_elastic_config``); training resumes from the latest
  checkpoint (orbax reshards the ZeRO state to the new mesh).
* :meth:`DSElasticAgent.run_procs` — PROCESS supervision for the
  multi-host launcher path: one subprocess per worker, liveness from BOTH
  process exit codes and a heartbeat file each worker touches
  (:class:`HeartbeatMonitor` — the torch-elastic keep-alive analogue).  A
  dead or silent worker tears the generation down and restarts at the
  surviving world size.
"""

import os
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence

from deepspeed_tpu.elasticity.elasticity import (
    ElasticityIncompatibleWorldSize, compute_elastic_config)
from deepspeed_tpu.utils.logging import logger

HEARTBEAT_ENV = "DS_ELASTIC_HEARTBEAT_FILE"


class HeartbeatMonitor:
    """File-based worker liveness (reference: the rendezvous keep-alive).

    Workers call :meth:`beat` (or just ``touch`` the path handed to them in
    ``$DS_ELASTIC_HEARTBEAT_FILE``); the agent polls :meth:`dead_ranks`.
    A rank with no heartbeat file yet is given grace until ``timeout_s``
    after :meth:`start`."""

    def __init__(self, hb_dir: str, world_size: int, timeout_s: float = 60.0):
        self.hb_dir = hb_dir
        self.world_size = world_size
        self.timeout_s = float(timeout_s)
        self.t0 = time.time()
        os.makedirs(hb_dir, exist_ok=True)
        # a fresh monitor is a fresh generation: leftover heartbeat files
        # (prior generation / prior agent run) would read as instantly
        # stale and kill healthy workers before they start beating
        for r in range(world_size):
            try:
                os.remove(self.path(r))
            except OSError:
                pass

    def path(self, rank: int) -> str:
        return os.path.join(self.hb_dir, f"heartbeat_rank{rank}")

    def start(self):
        self.t0 = time.time()

    @staticmethod
    def beat(path: Optional[str] = None):
        """Touch the heartbeat file (workers call this periodically)."""
        path = path or os.environ.get(HEARTBEAT_ENV)
        if path:
            with open(path, "w") as f:
                f.write(str(time.time()))

    def dead_ranks(self, now: Optional[float] = None) -> List[int]:
        now = now if now is not None else time.time()
        dead = []
        for r in range(self.world_size):
            try:
                last = os.path.getmtime(self.path(r))
            except OSError:
                last = self.t0        # not yet written: grace from start
            if now - last > self.timeout_s:
                dead.append(r)
        return dead


class ScaleEvent(Exception):
    """Raise from the train fn to request a restart at a new world size."""

    def __init__(self, new_world_size: int):
        self.new_world_size = new_world_size
        super().__init__(f"scale to {new_world_size}")


class ReplicaAutoscaler:
    """Serving-fleet scale decisions from aggregated ``serve/*`` gauges.

    The training-side agent above supervises *worker processes*; this is
    the serving analogue the fleet router (``inference/fleet.py``) calls
    once per supervision sweep with fleet-aggregate load: total queue
    depth, shed events since the last sweep, and the worst per-replica
    free-KV-page fraction.  Decisions are hysteretic and rate-limited —
    one replica per decision, with a cooldown of sweeps between decisions
    — so a transient burst doesn't flap the fleet size.

    Scale up (toward ``max_replicas``) when queue depth per replica
    reaches ``scale_up_queue_per_replica``, OR any requests were shed
    since the last sweep, OR the tightest replica's free-page fraction is
    at/below ``free_page_low_frac``.  Scale down (toward
    ``min_replicas``) only when the queue per replica is at/below
    ``scale_down_queue_per_replica`` AND nothing was shed AND pages are
    comfortable."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 8,
                 scale_up_queue_per_replica: int = 8,
                 scale_down_queue_per_replica: int = 1,
                 free_page_low_frac: float = 0.1,
                 cooldown_sweeps: int = 8):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_queue_per_replica = int(scale_up_queue_per_replica)
        self.scale_down_queue_per_replica = int(scale_down_queue_per_replica)
        self.free_page_low_frac = float(free_page_low_frac)
        self.cooldown_sweeps = int(cooldown_sweeps)
        self._cooldown = 0
        self.scale_ups = 0
        self.scale_downs = 0

    # the threshold fields a tuned overlay may supply
    THRESHOLD_KEYS = ("min_replicas", "max_replicas",
                      "scale_up_queue_per_replica",
                      "scale_down_queue_per_replica",
                      "free_page_low_frac", "cooldown_sweeps")

    @classmethod
    def from_overlay(cls, overlay_path: str,
                     defaults: Optional[Dict] = None) -> "ReplicaAutoscaler":
        """Thresholds from a persisted autotuner overlay
        (``autotuning/overlay.py``) instead of hand-set policy: any of
        :data:`THRESHOLD_KEYS` found under the overlay fragment's
        ``serving.fleet`` block wins over ``defaults``; a missing or
        malformed overlay degrades to ``defaults`` alone."""
        from deepspeed_tpu.autotuning.overlay import load_overlay
        kwargs = dict(defaults or {})
        payload = load_overlay(overlay_path) if overlay_path else None
        if payload is not None:
            fleet = ((payload.get("overlay") or {})
                     .get("serving") or {}).get("fleet") or {}
            for key in cls.THRESHOLD_KEYS:
                if key in fleet:
                    kwargs[key] = fleet[key]
        return cls(**kwargs)

    def decide(self, n_replicas: int, queue_depth: int = 0,
               shed_delta: int = 0, free_page_frac: float = 1.0) -> int:
        """Desired replica count for the next sweep (moves by at most 1)."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return n_replicas
        per_replica = queue_depth / max(1, n_replicas)
        pressed = (per_replica >= self.scale_up_queue_per_replica
                   or shed_delta > 0
                   or free_page_frac <= self.free_page_low_frac)
        if pressed and n_replicas < self.max_replicas:
            self._cooldown = self.cooldown_sweeps
            self.scale_ups += 1
            return n_replicas + 1
        idle = (per_replica <= self.scale_down_queue_per_replica
                and shed_delta == 0
                and free_page_frac > self.free_page_low_frac)
        if idle and n_replicas > self.min_replicas:
            self._cooldown = self.cooldown_sweeps
            self.scale_downs += 1
            return n_replicas - 1
        return n_replicas


class RoleAwareAutoscaler:
    """Per-pool hysteretic scale decisions for a role-specialized fleet
    (``serving.fleet.roles`` — inference/fleet.py).

    A disaggregated fleet has independent bottlenecks: the prefill pool
    saturates on queued prompts, the decode pool on migration backlog
    and KV-page pressure.  One shared :class:`ReplicaAutoscaler` would
    couple them (a prefill burst scaling decode, or vice versa), so this
    wrapper owns one INDEPENDENT autoscaler per pool — each with its own
    cooldown and counters — and returns one decision per pool."""

    def __init__(self, pools: Dict[str, ReplicaAutoscaler]):
        if not pools:
            raise ValueError("RoleAwareAutoscaler needs >= 1 pool")
        self.pools = dict(pools)

    def decide(self, n_by_pool: Dict[str, int],
               queue_by_pool: Optional[Dict[str, int]] = None,
               shed_by_pool: Optional[Dict[str, int]] = None,
               free_frac_by_pool: Optional[Dict[str, float]] = None) \
            -> Dict[str, int]:
        """Desired replica count per pool (each moves by at most 1)."""
        queue_by_pool = queue_by_pool or {}
        shed_by_pool = shed_by_pool or {}
        free_frac_by_pool = free_frac_by_pool or {}
        return {
            pool: scaler.decide(
                max(1, int(n_by_pool.get(pool, 1))),
                queue_depth=int(queue_by_pool.get(pool, 0)),
                shed_delta=int(shed_by_pool.get(pool, 0)),
                free_page_frac=float(free_frac_by_pool.get(pool, 1.0)))
            for pool, scaler in self.pools.items()}

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        return {pool: {"scale_ups": s.scale_ups,
                       "scale_downs": s.scale_downs}
                for pool, s in self.pools.items()}


class DSElasticAgent:

    def __init__(self, ds_config: Dict, start_world_size: int,
                 max_restarts: int = 100, restart_delay_s: float = 0.0):
        self.ds_config = ds_config
        self.world_size = start_world_size
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        self.restarts = 0

    def _generation_config(self) -> Dict:
        """The ds_config for one generation: the elastic batch triangle
        RESOLVED for the current world size, with
        ``ignore_non_elastic_batch_info`` set so a worker re-parsing this
        config (``DeepSpeedConfig._maybe_apply_elasticity`` /
        ``compute_elastic_config``) does not reject its own injected
        batch keys as a fixed-vs-elastic conflict."""
        batch, valid, micro = compute_elastic_config(
            self.ds_config, world_size=self.world_size)
        cfg = dict(self.ds_config)
        cfg["train_batch_size"] = batch
        cfg["train_micro_batch_size_per_gpu"] = micro
        cfg["elasticity"] = dict(cfg.get("elasticity", {}),
                                 ignore_non_elastic_batch_info=True)
        return cfg

    def run(self, train_fn: Callable[[Dict, int], Optional[int]]):
        """``train_fn(ds_config, world_size)`` runs training; return value
        is the exit status (None/0 = done).  Raising ``ScaleEvent`` (or any
        exception, up to ``max_restarts``) re-enters with refreshed elastic
        batch settings."""
        while True:
            cfg = self._generation_config()
            try:
                return train_fn(cfg, self.world_size)
            except ScaleEvent as ev:
                logger.warning(f"elastic scale event: {self.world_size} → "
                               f"{ev.new_world_size}")
                self.world_size = ev.new_world_size
            except ElasticityIncompatibleWorldSize:
                raise
            except Exception as e:  # worker failure → restart
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    raise
                logger.warning(f"worker failure ({e}); restart "
                               f"{self.restarts}/{self.max_restarts}")
            if self.restart_delay_s:
                time.sleep(self.restart_delay_s)

    # ------------------------------------------------------------------
    # multi-host process supervision (launcher path)
    # ------------------------------------------------------------------
    def run_procs(self, cmd_for: Callable[[int, int, Dict], Sequence[str]],
                  heartbeat_dir: str,
                  heartbeat_timeout_s: Optional[float] = 60.0,
                  poll_s: float = 1.0,
                  env_for: Optional[Callable[[int, int], Dict]] = None
                  ) -> int:
        """Supervise one subprocess per worker with liveness detection.

        ``cmd_for(rank, world_size, ds_config)`` returns the argv for one
        worker; each worker gets its heartbeat path in
        ``$DS_ELASTIC_HEARTBEAT_FILE`` and should touch it periodically
        (``HeartbeatMonitor.beat()``).  A worker that exits nonzero, or
        whose heartbeat goes stale past ``heartbeat_timeout_s``, is a
        membership change: the surviving generation is torn down and
        restarted at the new world size (reference
        ``_invoke_run``'s monitor loop → ``_restart_workers``).
        ``heartbeat_timeout_s`` of ``None`` OR ``0`` disables staleness
        detection (exit codes only — for workers that never call
        ``beat()``).
        ``env_for(rank, world_size)`` supplies extra per-rank env
        (coordinator address, JAX process trio, ...).  Returns 0 when
        every worker of a generation exits cleanly."""
        hb_enabled = bool(heartbeat_timeout_s)
        while True:
            cfg = self._generation_config()
            hb = HeartbeatMonitor(heartbeat_dir, self.world_size,
                                  timeout_s=heartbeat_timeout_s or 60.0)
            procs: List[subprocess.Popen] = []
            dead: List[int] = []
            # the try starts BEFORE the spawn loop: a signal (SystemExit)
            # landing mid-spawn must still terminate the workers already
            # started, or the launcher orphans them
            try:
                for r in range(self.world_size):
                    env = dict(os.environ, RANK=str(r),
                               WORLD_SIZE=str(self.world_size))
                    if env_for is not None:
                        env.update({k: str(v) for k, v in
                                    env_for(r, self.world_size).items()})
                    env[HEARTBEAT_ENV] = hb.path(r)
                    procs.append(subprocess.Popen(
                        list(cmd_for(r, self.world_size, cfg)), env=env))
                hb.start()
                while True:
                    rcs = [p.poll() for p in procs]
                    dead = [r for r, rc in enumerate(rcs)
                            if rc is not None and rc != 0]
                    if not dead and hb_enabled:
                        dead = [r for r in hb.dead_ranks()
                                if rcs[r] is None]   # silent, not exited
                    if dead:
                        break
                    if all(rc == 0 for rc in rcs):
                        return 0
                    time.sleep(poll_s)
            finally:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
            self.restarts += 1
            if self.restarts > self.max_restarts:
                raise RuntimeError(
                    f"elastic agent: exceeded max_restarts="
                    f"{self.max_restarts} (last dead ranks: {dead})")
            new_world = self.world_size - len(dead)
            if new_world < 1:
                raise RuntimeError(
                    "elastic agent: every worker died "
                    f"(ranks {dead}) — nothing to restart with")
            logger.warning(
                f"elastic membership change: ranks {dead} died; "
                f"restarting at world size {self.world_size} → {new_world}")
            self.world_size = new_world
            if self.restart_delay_s:
                time.sleep(self.restart_delay_s)
