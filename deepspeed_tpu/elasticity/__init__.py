"""Elastic training (reference ``deepspeed/elasticity/``)."""

from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent,
                                                    HeartbeatMonitor,
                                                    ReplicaAutoscaler,
                                                    RoleAwareAutoscaler,
                                                    ScaleEvent)
from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfig, ElasticityConfigError, ElasticityError,
    ElasticityIncompatibleWorldSize, compute_elastic_config,
    ensure_immutable_elastic_config, get_valid_gpus)

__all__ = ["DSElasticAgent", "HeartbeatMonitor", "ReplicaAutoscaler",
           "RoleAwareAutoscaler",
           "ScaleEvent",
           "ElasticityConfig",
           "ElasticityError", "ElasticityConfigError",
           "ElasticityIncompatibleWorldSize", "compute_elastic_config",
           "ensure_immutable_elastic_config", "get_valid_gpus"]
