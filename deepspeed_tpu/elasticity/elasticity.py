"""Elastic training: batch-size ⇄ device-count co-design.

Parity: reference ``elasticity/elasticity.py`` (``compute_elastic_config:287``
with the v0.1 solver ``:125`` and the model-parallel-aware v0.2 ``:173``):
pick a global batch size ≤ ``max_acceptable_batch_size`` that is compatible
with the largest set of device counts, so scaling events never change the
effective batch size (checkpoint-compatible rescaling).

TPU design: "GPUs" are chips; with model parallelism the data-parallel
degree is ``chips / (tp*pp)``, which v0.2 accounts for.  The engine's ZeRO
sharding is mesh-shaped, so a scaling event is: recompute the mesh from the
new chip count, restore the checkpoint (orbax reshards), continue.
"""

from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

ELASTICITY = "elasticity"
LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Parses the ``elasticity`` config section (reference
    ``elasticity/config.py`` keys)."""

    def __init__(self, param_dict: Dict):
        self.enabled = param_dict.get("enabled", False)
        self.max_acceptable_batch_size = param_dict.get(
            "max_train_batch_size", 2000)
        self.micro_batches = param_dict.get("micro_batch_sizes",
                                            [2, 4, 6])
        self.min_gpus = param_dict.get("min_gpus", 1)
        self.max_gpus = param_dict.get("max_gpus", 10000)
        self.min_time = param_dict.get("min_time", 0)
        self.version = float(param_dict.get("version", 0.2))
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch",
                                                       True)
        self.ignore_non_elastic_batch_info = param_dict.get(
            "ignore_non_elastic_batch_info", False)
        self.model_parallel_size = param_dict.get("model_parallel_size", 1)
        self.num_gpus_per_node = param_dict.get("num_gpus_per_node", 1)
        if not isinstance(self.micro_batches, list) or \
                any(m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive ints, got "
                f"{self.micro_batches}")


# ----------------------------------------------------------------------
# solvers
# ----------------------------------------------------------------------
def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """Device counts g for which some micro-batch m gives
    ``batch_size % (m*g) == 0``."""
    valid = []
    for g in range(min_valid_gpus, max_valid_gpus + 1):
        if any(batch_size % (g * m) == 0 for m in micro_batches):
            valid.append(g)
    return valid


def _candidate_batch_sizes(micro_batches: List[int],
                           max_batch: int) -> List[int]:
    """All m * 2^k ≤ max_batch plus the highly-composite neighbourhood of
    max_batch itself."""
    cands = set()
    for m in micro_batches:
        b = m
        while b <= max_batch:
            cands.add(b)
            b *= 2
    # LCM ladder: multiples of all micro batches pack the most device counts
    lcm = 1
    for m in micro_batches:
        from math import gcd
        lcm = lcm * m // gcd(lcm, m)
    b = lcm
    while b <= max_batch:
        cands.add(b)
        b += lcm
    return sorted(cands)


def _get_compatible_gpus_v01(micro_batches: List[int],
                             max_acceptable_batch_size: int,
                             min_gpus: int, max_gpus: int,
                             prefer_larger: bool = True
                             ) -> Tuple[int, List[int]]:
    """v0.1: maximise |valid device counts|, tie-break on batch size."""
    best = (0, 0, [])
    for batch in _candidate_batch_sizes(micro_batches,
                                        max_acceptable_batch_size):
        valid = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        score = (len(valid), batch if prefer_larger else -batch)
        if score > (best[0], best[1] if prefer_larger else -best[1]):
            best = (len(valid), batch, valid)
    if not best[2]:
        raise ElasticityError(
            f"no compatible batch size ≤ {max_acceptable_batch_size} for "
            f"micro_batches={micro_batches}, gpus "
            f"[{min_gpus},{max_gpus}]")
    return best[1], best[2]


def _get_compatible_gpus_v02(micro_batches: List[int],
                             max_acceptable_batch_size: int,
                             current_num_gpus: int,
                             min_gpus: int, max_gpus: int,
                             prefer_larger: bool,
                             num_gpus_per_node: int,
                             model_parallel_size: int
                             ) -> Tuple[int, List[int], int]:
    """v0.2: model-parallel aware — data-parallel workers are groups of
    ``model_parallel_size`` chips; device counts must be multiples."""
    if model_parallel_size > 1:
        if current_num_gpus % model_parallel_size != 0:
            raise ElasticityIncompatibleWorldSize(
                f"world size {current_num_gpus} not divisible by "
                f"model_parallel_size {model_parallel_size}")
        dp_min = max(1, min_gpus // model_parallel_size)
        dp_max = max_gpus // model_parallel_size
    else:
        dp_min, dp_max = min_gpus, max_gpus
    batch, valid_dp = _get_compatible_gpus_v01(
        micro_batches, max_acceptable_batch_size, dp_min, dp_max,
        prefer_larger)
    valid_gpus = [d * model_parallel_size for d in valid_dp]
    if current_num_gpus == 0:
        # inspection path (no running world, e.g. bin/ds_elastic): report
        # the solved batch/valid set without a current-world membership
        # check; the micro batch is the solver's own candidate (reference
        # returns candidate_microbatch_size when world_size is absent)
        micro = max(m for m in micro_batches if batch % m == 0)
        return batch, valid_gpus, micro
    current_dp = current_num_gpus // model_parallel_size
    if current_dp not in valid_dp:
        raise ElasticityIncompatibleWorldSize(
            f"current world size {current_num_gpus} (dp={current_dp}) is not "
            f"in the valid set {valid_gpus}")
    # micro batch for the current dp: largest m with batch % (m*dp) == 0
    micro = max(m for m in micro_batches if batch % (m * current_dp) == 0)
    return batch, valid_gpus, micro


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0,
                           return_microbatch: bool = False):
    """Parity: reference ``compute_elastic_config:287``.

    Returns ``(final_batch_size, valid_gpus)`` and, with ``world_size`` or
    ``return_microbatch``, the per-worker micro batch for that world size.
    """
    if ELASTICITY not in ds_config:
        raise ElasticityConfigError(f"config missing '{ELASTICITY}' section")
    cfg = ElasticityConfig(ds_config[ELASTICITY])
    if not cfg.enabled:
        raise ElasticityConfigError("elasticity.enabled is false")
    if not cfg.ignore_non_elastic_batch_info:
        for key in ("train_batch_size", "train_micro_batch_size_per_gpu",
                    "gradient_accumulation_steps"):
            if key in ds_config:
                raise ElasticityConfigError(
                    f"fixed '{key}' conflicts with elasticity; remove it or "
                    "set ignore_non_elastic_batch_info")

    if cfg.version >= 0.2 and (cfg.model_parallel_size > 1 or world_size):
        batch, valid, micro = _get_compatible_gpus_v02(
            cfg.micro_batches, cfg.max_acceptable_batch_size, world_size,
            cfg.min_gpus, cfg.max_gpus, cfg.prefer_larger_batch_size,
            cfg.num_gpus_per_node, cfg.model_parallel_size)
        logger.info(f"elasticity v0.2: batch={batch} valid_gpus={valid} "
                    f"micro={micro}")
        return (batch, valid, micro) if (world_size or return_microbatch) \
            else (batch, valid)

    batch, valid = _get_compatible_gpus_v01(
        cfg.micro_batches, cfg.max_acceptable_batch_size,
        cfg.min_gpus, cfg.max_gpus, cfg.prefer_larger_batch_size)
    logger.info(f"elasticity v0.1: batch={batch} valid_gpus={valid}")
    if world_size or return_microbatch:
        # v0.1 with a live world: pick the preferred micro batch that
        # divides the final batch at this world size (the 3-tuple contract
        # every runtime caller — DeepSpeedConfig, the elastic agent —
        # relies on; previously v0.1 returned a 2-tuple and crashed them)
        order = sorted(cfg.micro_batches,
                       reverse=cfg.prefer_larger_batch_size)
        micro = next((m for m in order
                      if not world_size or batch % (m * world_size) == 0),
                     None)
        if micro is None:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} has no compatible micro batch "
                f"in {cfg.micro_batches} for final batch {batch}")
        return batch, valid, micro
    return batch, valid


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict,
                                    checkpoint_elastic_config_dict: Dict):
    """Scaling events must not change the elastic config (reference check)."""
    for k in ("max_train_batch_size", "micro_batch_sizes", "version"):
        a = runtime_elastic_config_dict.get(k)
        b = checkpoint_elastic_config_dict.get(k)
        if a != b:
            raise ElasticityConfigError(
                f"elastic config changed across restart: {k}: {b} → {a}")
