"""Compression scheduler.

Parity: reference ``compression/scheduler.py`` (``compression_scheduler``:
per-step check that flips each method on at its ``schedule_offset``; the
engine calls it every step, ``engine.py:1401``).

TPU design: the on/off gating is *traced* into the train step
(``CompressionSpec.transform`` gates on the step counter), so this class is
the host-side bookkeeping/reporting view of the same schedule.
"""

from deepspeed_tpu.compression.compress import CompressionSpec
from deepspeed_tpu.utils.logging import logger


class CompressionScheduler:

    def __init__(self, spec: CompressionSpec):
        self.spec = spec
        self._announced = set()

    def check(self, global_step: int):
        """Host-side step hook (reference ``step()``): logs phase changes."""
        for g in self.spec.groups:
            if g.name in self._announced:
                continue
            if global_step >= g.schedule_offset:
                self._announced.add(g.name)
                logger.info(f"compression active from step {global_step}: "
                            f"{g.method}/{g.name} {g.params}")

    step = check
