"""Compression entry points + the params transform.

Parity: reference ``compression/compress.py`` (``init_compression``: walk
model, wrap matched modules in *_Compress layers; ``redundancy_clean``:
physically remove pruned structures after training) and
``compression/scheduler.py`` hookup in the engine (``engine.py:1401``).

TPU design: ``init_compression`` compiles the config into a
``CompressionSpec`` — a list of (leaf-matcher, transform) pairs.  The spec's
``transform(params, step)`` runs INSIDE the jitted train step: each matched
leaf goes through STE fake-quant/pruning, gated on
``step >= schedule_offset`` with ``jnp.where`` so the same compiled program
covers warmup and compression phases.
"""

import re
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.compression import transforms as T
from deepspeed_tpu.compression.config import (ACTIVATION_QUANTIZATION,
                                              CHANNEL_PRUNING,
                                              CompressionConfig,
                                              HEAD_PRUNING, ROW_PRUNING,
                                              SPARSE_PRUNING,
                                              WEIGHT_QUANTIZATION)
from deepspeed_tpu.utils.logging import logger


def _glob_to_regex(pat: str) -> str:
    if pat == "*":
        return r".*"
    return ".*".join(re.escape(p) for p in pat.split("*"))


class CompressionSpec:
    """Compiled compression plan over a params pytree.

    Mesh-aware (reference ``ColumnParallelLinear_Compress`` /
    ``RowParallelLinear_Compress``, ``compression/basic_layer.py:836,879``):
    when ``tp_rules``/``mesh`` are given, structured pruning of a
    tp-sharded axis ranks per contiguous shard block so every tp rank
    keeps the same survivor count, and the compressed leaf is constrained
    back onto its sharding spec."""

    def __init__(self, config: CompressionConfig,
                 num_heads: Optional[int] = None,
                 tp_rules=None, mesh=None):
        self.config = config
        self.num_heads = num_heads
        self.groups = config.groups
        # (compiled_regex, PartitionSpec) pairs — the same rule table the
        # ZeRO plan applies (stage_plan.ZeroShardingPlan.tp_rules)
        self.tp_rules = [
            (pat if hasattr(pat, "search") else re.compile(pat), spec)
            for pat, spec in (tp_rules or [])]
        self.mesh = mesh

    # ------------------------------------------------------------------
    def _spec_for(self, path: str):
        for pat, spec in self.tp_rules:
            if pat.search(path):
                return spec
        return None

    def _axis_shard_degree(self, spec, shape, axis: int) -> int:
        """How many ways ``axis`` is sharded under ``spec`` on the mesh.
        Returns 1 (global ranking) when the axis length doesn't divide the
        shard degree — GSPMD pads such shardings, so per-block ranking
        would mis-assign the padded tail."""
        if spec is None or self.mesh is None:
            return 1
        ndim = len(shape)
        axis %= ndim
        entries = tuple(spec)
        if axis >= len(entries):
            return 1
        e = entries[axis]
        names = e if isinstance(e, tuple) else ((e,) if e else ())
        d = 1
        for n in names:
            d *= dict(self.mesh.shape).get(n, 1)
        if d > 1 and shape[axis] % d:
            logger.warning(
                f"structured pruning: axis {axis} of shape {tuple(shape)} "
                f"does not divide its shard degree {d}; falling back to "
                "global ranking (survivors may be shard-unbalanced)")
            return 1
        return d

    # ------------------------------------------------------------------
    def _leaf_transform(self, group, leaf, step, path=""):
        m, p = group.method, group.params
        enabled = step >= group.schedule_offset
        spec = self._spec_for(path)
        if m == WEIGHT_QUANTIZATION:
            bits = int(p.get("target_bits", p.get("bits", 8)))
            out = T.quantize_weight(
                leaf, bits=bits,
                groups=int(group.shared.get("quantize_groups", 1)),
                symmetric=group.shared.get("quantization_type",
                                           "symmetric") == "symmetric")
        elif m == SPARSE_PRUNING:
            out = T.sparse_prune(leaf, float(p.get("dense_ratio", 0.5)),
                                 method=group.shared.get("method", "l1"))
        elif m == ROW_PRUNING:
            out = T.row_prune(leaf, float(p.get("dense_ratio", 0.5)),
                              tp_degree=self._axis_shard_degree(
                                  spec, leaf.shape, -1))
        elif m == HEAD_PRUNING:
            heads = int(p.get("num_heads",
                              group.shared.get("num_heads",
                                               self.num_heads or 0)))
            if heads <= 1 or leaf.ndim < 2 or leaf.shape[-2] % heads:
                return leaf
            tp = self._axis_shard_degree(spec, leaf.shape, leaf.ndim - 2)
            if tp > 1 and heads % tp:
                tp = 1          # heads don't divide over shards: global rank
            out = T.head_prune(leaf, heads, float(p.get("dense_ratio", 0.5)),
                               tp_degree=tp)
        elif m == CHANNEL_PRUNING:
            out = T.channel_prune(leaf, float(p.get("dense_ratio", 0.5)),
                                  tp_degree=self._axis_shard_degree(
                                      spec, leaf.shape, 0))
        else:
            return leaf
        out = jnp.where(enabled, out, leaf)
        if spec is not None:
            from deepspeed_tpu.runtime.zero.stage_plan import maybe_constrain
            out = maybe_constrain(out, spec)
        return out

    def _matches(self, group, path: str, leaf) -> bool:
        if np.ndim(leaf) < 2:
            return False            # norms/biases are never compressed
        return any(re.search(_glob_to_regex(mod), path)
                   for mod in group.modules)

    def transform(self, params, step):
        """params → compressed params (jit-traceable; ``step`` may be traced)."""
        step = jnp.asarray(step, jnp.int32)

        def visit(path, leaf):
            key = jax.tree_util.keystr(path)
            for group in self.groups:
                if group.method == ACTIVATION_QUANTIZATION:
                    continue       # handled at activation sites, not params
                if self._matches(group, key, leaf):
                    leaf = self._leaf_transform(group, leaf, step, path=key)
            return leaf

        return jax.tree_util.tree_map_with_path(visit, params)

    # activation quantization parameters for model-side use --------------
    def activation_bits(self) -> Optional[int]:
        for g in self.groups:
            if g.method == ACTIVATION_QUANTIZATION:
                return int(g.params.get("bits", 8))
        return None


def init_compression(model_or_params, deepspeed_config,
                     teacher_model=None, mpu=None,
                     tp_rules=None, mesh=None) -> CompressionSpec:
    """Parity: reference ``init_compression(model, deepspeed_config)``.
    Accepts the engine's parsed config, a raw ``compression_training`` dict,
    or a JSON path.  ``tp_rules``/``mesh``: the ZeRO plan's sharding rule
    table — makes structured pruning shard-balanced (see CompressionSpec)."""
    cfg = _coerce_config(deepspeed_config)
    num_heads = None
    model_cfg = getattr(model_or_params, "config", None)
    if model_cfg is not None:
        num_heads = getattr(model_cfg, "n_heads", None)
    spec = CompressionSpec(cfg, num_heads=num_heads,
                           tp_rules=tp_rules, mesh=mesh)
    if cfg.enabled:
        logger.info(f"compression enabled: {len(cfg.groups)} group(s), "
                    f"layer_reduction={cfg.layer_reduction.enabled}")
    return spec


def _coerce_config(deepspeed_config) -> CompressionConfig:
    if isinstance(deepspeed_config, CompressionConfig):
        return deepspeed_config
    if isinstance(deepspeed_config, str):
        import json
        with open(deepspeed_config) as f:
            deepspeed_config = json.load(f)
    if isinstance(deepspeed_config, dict):
        return CompressionConfig(
            deepspeed_config.get("compression_training", deepspeed_config))
    # engine-parsed DeepSpeedConfig
    return CompressionConfig(getattr(deepspeed_config, "compression_config",
                                     {}))


# ----------------------------------------------------------------------
# redundancy_clean: physically remove pruned structure
# ----------------------------------------------------------------------
def redundancy_clean(params, deepspeed_config, mpu=None):
    """Parity: reference ``redundancy_clean`` — after compressed training,
    make the compression real: bake STE fake-quant values in, drop layers
    per ``layer_reduction`` (student keeps ``teacher_layer`` indices), and
    hard-zero pruned weights.

    Works on stacked-layer pytrees (leaves with a leading n_layers dim).
    """
    cfg = _coerce_config(deepspeed_config)
    spec = CompressionSpec(cfg)
    # bake at a step past every offset so every transform is active
    max_off = max([g.schedule_offset for g in cfg.groups], default=0)
    params = jax.tree_util.tree_map(np.asarray,
                                    spec.transform(params, max_off + 1))

    lr = cfg.layer_reduction
    if lr.enabled and lr.teacher_layer:
        keep = np.asarray(sorted(int(i) for i in lr.teacher_layer))

        def slice_layers(tree):
            return jax.tree_util.tree_map(lambda x: x[keep], tree)
        if isinstance(params, dict) and "layers" in params:
            if isinstance(params["layers"], (list, tuple)):
                params["layers"] = [params["layers"][i] for i in keep]
            else:
                params["layers"] = slice_layers(params["layers"])
            logger.info(f"layer_reduction: kept layers {keep.tolist()}")
    return params
