"""Compression primitives: STE fake-quantization and magnitude pruning.

Parity: reference ``compression/basic_layer.py`` (``LinearLayer_Compress``
with sparse/row/head/channel pruning + weight quantization under a
straight-through estimator, ``QuantAct`` activation quantization,
``Embedding_Compress``) and ``compression/utils.py`` (TopKBinarizer,
Symmetric/AsymmetricQuantizer).

TPU design: the reference subclasses ``nn.Linear`` and mutates weights in
``forward``; here compression is a pure params→params transform applied
inside the jitted train step.  The STE is the classic
``x + stop_gradient(q(x) - x)`` identity — forward sees the quantized value,
backward sees identity — so no custom VJP machinery is needed and XLA fuses
the fake-quant into the consuming matmul.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _ste(x, qx):
    """Straight-through estimator."""
    return x + lax.stop_gradient(qx - x)


# ----------------------------------------------------------------------
# quantizers (reference SymmetricQuantizer / AsymmetricQuantizer)
# ----------------------------------------------------------------------
def quantize_weight(w, bits: int = 8, groups: int = 1,
                    symmetric: bool = True, stochastic: bool = False,
                    rng=None):
    """Group-wise fake quantization with STE.

    ``groups`` splits the flattened tensor into quantization groups with
    independent scales (reference ``quantize_groups``); ``stochastic``
    rounds stochastically (reference ``ds_sr_quantize``).
    """
    orig_shape = w.shape
    flat = w.reshape(groups, -1)
    levels = 2 ** (bits - 1)
    if symmetric:
        scale = jnp.max(jnp.abs(flat), axis=1, keepdims=True) / (levels - 1)
        scale = jnp.maximum(scale, 1e-8)
        q = flat / scale
        q = _round(q, stochastic, rng)
        q = jnp.clip(q, -levels, levels - 1) * scale
    else:
        mn = jnp.min(flat, axis=1, keepdims=True)
        mx = jnp.max(flat, axis=1, keepdims=True)
        scale = jnp.maximum((mx - mn) / (2 ** bits - 1), 1e-8)
        q = (flat - mn) / scale
        q = _round(q, stochastic, rng)
        q = jnp.clip(q, 0, 2 ** bits - 1) * scale + mn
    return _ste(flat, q).reshape(orig_shape)


def _round(x, stochastic, rng):
    if stochastic:
        assert rng is not None, "stochastic rounding needs rng"
        return jnp.floor(x + jax.random.uniform(rng, x.shape))
    return jnp.round(x)


def quantize_activation(x, bits: int = 8, symmetric: bool = False,
                        static_range: Optional[float] = None):
    """Activation fake-quant (reference ``QuantAct``); dynamic per-tensor
    range by default, static range when calibrated."""
    if static_range is not None:
        mx = jnp.asarray(static_range, x.dtype)
        mn = -mx
    else:
        mx = jnp.max(x)
        mn = jnp.min(x)
    if symmetric:
        levels = 2 ** (bits - 1)
        scale = jnp.maximum(jnp.maximum(jnp.abs(mx), jnp.abs(mn)) /
                            (levels - 1), 1e-8)
        q = jnp.clip(jnp.round(x / scale), -levels, levels - 1) * scale
    else:
        scale = jnp.maximum((mx - mn) / (2 ** bits - 1), 1e-8)
        q = jnp.clip(jnp.round((x - mn) / scale), 0, 2 ** bits - 1) * scale + mn
    return _ste(x, q)


# ----------------------------------------------------------------------
# pruning (reference TopKBinarizer + *_pruning in LinearLayer_Compress)
# ----------------------------------------------------------------------
def _topk_mask(scores, dense_ratio, num_blocks: int = 1):
    """1.0 for the top ``dense_ratio`` fraction by score, else 0.0.

    ``num_blocks > 1``: rank WITHIN each of ``num_blocks`` contiguous
    blocks instead of globally — the mesh-aware mode.  When the structural
    axis is tp-sharded, each tp shard owns one contiguous block, and
    per-block ranking guarantees every shard keeps the same survivor count
    (reference ``ColumnParallelLinear_Compress``/``RowParallelLinear_Compress``,
    ``compression/basic_layer.py:836,879``: each parallel rank prunes
    ``dense_ratio`` of its OWN slice).  A global top-k could strand all
    survivors on one shard, unbalancing tp compute and making physical
    removal shard-inhomogeneous."""
    flat = scores.reshape(-1)
    n = flat.shape[0]
    if num_blocks > 1 and n % num_blocks == 0:
        per = n // num_blocks
        blocks = flat.reshape(num_blocks, per)
        k = jnp.maximum(1, jnp.round(dense_ratio * per)).astype(jnp.int32)
        order = jnp.argsort(blocks, axis=1)[:, ::-1]
        ranks = jnp.zeros_like(order).at[
            jnp.arange(num_blocks)[:, None], order].set(
            jnp.broadcast_to(jnp.arange(per), (num_blocks, per)))
        return (ranks < k).astype(scores.dtype).reshape(scores.shape)
    k = jnp.maximum(1, jnp.round(dense_ratio * n)).astype(jnp.int32)
    order = jnp.argsort(flat)[::-1]
    ranks = jnp.zeros_like(order).at[order].set(jnp.arange(n))
    return (ranks < k).astype(scores.dtype).reshape(scores.shape)


def sparse_prune(w, dense_ratio: float = 0.5, method: str = "l1"):
    """Unstructured magnitude pruning with STE (reference sparse_pruning;
    ``method='l1'`` |w|, ``'topk'`` same ranking)."""
    scores = jnp.abs(w)
    mask = _topk_mask(scores, dense_ratio)
    return _ste(w, w * mask)


def row_prune(w, dense_ratio: float = 0.5, axis: int = -1,
              tp_degree: int = 1):
    """Structured output-row pruning: ranks rows (slices of ``axis``) by L1
    norm (reference row_pruning on nn.Linear output rows).  ``tp_degree>1``:
    the row axis is tensor-parallel-sharded — prune per contiguous shard
    block so every tp rank keeps the same row count."""
    reduce_axes = tuple(a for a in range(w.ndim) if a != axis % w.ndim)
    scores = jnp.sum(jnp.abs(w), axis=reduce_axes, keepdims=False)
    mask1d = _topk_mask(scores, dense_ratio, num_blocks=tp_degree)
    shape = [1] * w.ndim
    shape[axis % w.ndim] = w.shape[axis % w.ndim]
    return _ste(w, w * mask1d.reshape(shape))


def head_prune(w, num_heads: int, dense_ratio: float = 0.5,
               tp_degree: int = 1):
    """Attention head pruning: ranks head blocks of the output projection's
    input dim by L1 norm (reference head_pruning on attention.output.dense).
    ``w``: [..., H*dh, d].  ``tp_degree>1``: the H*dh axis is tp-sharded —
    heads are ranked per contiguous shard block (H/tp heads each) so every
    tp rank keeps the same head count (reference
    ``RowParallelLinear_Compress.head_pruning_*``)."""
    in_dim = w.shape[-2]
    dh = in_dim // num_heads
    blocks = w.reshape(w.shape[:-2] + (num_heads, dh, w.shape[-1]))
    reduce_axes = tuple(a for a in range(blocks.ndim)
                        if a != blocks.ndim - 3)
    scores = jnp.sum(jnp.abs(blocks), axis=reduce_axes)
    mask = _topk_mask(scores, dense_ratio,
                      num_blocks=tp_degree)          # [H]
    shape = [1] * blocks.ndim
    shape[blocks.ndim - 3] = num_heads
    masked = blocks * mask.reshape(shape)
    return _ste(w, masked.reshape(w.shape))


def channel_prune(w, dense_ratio: float = 0.5, tp_degree: int = 1):
    """Conv-style channel pruning: ranks output channels (dim 0)."""
    return row_prune(w, dense_ratio, axis=0, tp_degree=tp_degree)


def embedding_quantize(e, bits: int = 8):
    """Embedding_Compress: per-row symmetric quantization."""
    levels = 2 ** (bits - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(e), axis=-1, keepdims=True) /
                        (levels - 1), 1e-8)
    q = jnp.clip(jnp.round(e / scale), -levels, levels - 1) * scale
    return _ste(e, q)
