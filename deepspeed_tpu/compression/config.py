"""Compression config parsing.

Parity: reference ``compression/config.py`` + ``compression/constants.py`` —
the ``compression_training`` JSON section with per-method
``shared_parameters`` / ``different_groups`` (weight_quantization,
activation_quantization, sparse_pruning, row_pruning, head_pruning,
channel_pruning, layer_reduction).  Keys keep reference spellings.
"""

from typing import Any, Dict, List, Optional

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

WEIGHT_QUANTIZATION = "weight_quantization"
ACTIVATION_QUANTIZATION = "activation_quantization"
SPARSE_PRUNING = "sparse_pruning"
ROW_PRUNING = "row_pruning"
HEAD_PRUNING = "head_pruning"
CHANNEL_PRUNING = "channel_pruning"
LAYER_REDUCTION = "layer_reduction"

PRUNING_METHODS = (SPARSE_PRUNING, ROW_PRUNING, HEAD_PRUNING, CHANNEL_PRUNING)


class CompressionGroup:
    """One entry of ``different_groups``: parameter patterns + method params."""

    def __init__(self, name: str, method: str, modules: List[str],
                 params: Dict[str, Any], shared: Dict[str, Any]):
        self.name = name
        self.method = method
        self.modules = modules or ["*"]
        self.params = params or {}
        self.shared = shared or {}

    @property
    def schedule_offset(self) -> int:
        return int(self.shared.get("schedule_offset", 0))

    def __repr__(self):
        return (f"CompressionGroup({self.method}:{self.name} "
                f"modules={self.modules} params={self.params})")


class LayerReductionConfig(DeepSpeedConfigModel):
    enabled = False
    keep_number_layer = None
    module_name_prefix = ""
    teacher_layer = []
    other_module_name = []


class CompressionConfig:
    """Parses the full ``compression_training`` dict into a group list."""

    def __init__(self, param_dict: Optional[Dict[str, Any]] = None):
        pd = dict(param_dict or {})
        self.groups: List[CompressionGroup] = []
        self.layer_reduction = LayerReductionConfig(
            pd.get(LAYER_REDUCTION, {}))
        for method in (WEIGHT_QUANTIZATION, ACTIVATION_QUANTIZATION) + \
                PRUNING_METHODS:
            section = pd.get(method, {})
            shared = section.get("shared_parameters", {})
            if not shared.get("enabled", False):
                continue
            diff = section.get("different_groups", {})
            if not diff:
                self.groups.append(CompressionGroup(
                    method, method, ["*"], {}, shared))
            for gname, g in diff.items():
                self.groups.append(CompressionGroup(
                    gname, method, g.get("modules", ["*"]),
                    g.get("params", {}), shared))

    @property
    def enabled(self) -> bool:
        return bool(self.groups) or self.layer_reduction.enabled
