"""Compression library (reference ``deepspeed/compression/``)."""

from deepspeed_tpu.compression.compress import (CompressionSpec,
                                                init_compression,
                                                redundancy_clean)
from deepspeed_tpu.compression.config import CompressionConfig
from deepspeed_tpu.compression.scheduler import CompressionScheduler

__all__ = ["CompressionSpec", "CompressionConfig", "CompressionScheduler",
           "init_compression", "redundancy_clean"]
