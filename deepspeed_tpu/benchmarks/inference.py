"""Inference latency benchmark (gpt-bench).

Parity: reference ``benchmarks/inference/gpt-bench.py`` (``print_latency:38``
— p50/p90/p99 token latency, fp16/int8, kernel-inject on/off).

Usage::

    python -m deepspeed_tpu.benchmarks.inference --model tiny --dtype bf16 \
        --batch 1 --prompt-len 128 --max-new-tokens 64 --trials 10
"""

import argparse
import json
import time
from typing import List

import numpy as np


def print_latency(latency_set: List[float], title: str, warmup: int = 3):
    """Reference gpt-bench.print_latency: trim warmup, report percentiles."""
    lat = sorted(latency_set[warmup:])
    if not lat:
        return
    n = len(lat)
    avg = sum(lat) / n
    p50 = lat[int(n * 0.5)]
    p90 = lat[min(n - 1, int(n * 0.9))]
    p99 = lat[min(n - 1, int(n * 0.99))]
    print(f"== {title} =============")
    print(f"\tAvg Latency: {avg * 1000:.2f} ms")
    print(f"\tP50 Latency: {p50 * 1000:.2f} ms")
    print(f"\tP90 Latency: {p90 * 1000:.2f} ms")
    print(f"\tP99 Latency: {p99 * 1000:.2f} ms")
    return {"avg": avg, "p50": p50, "p90": p90, "p99": p99}


def run_benchmark(model_size="tiny", dtype="bf16", batch=1, prompt_len=128,
                  max_new_tokens=64, trials=10, quant=False, tp=1,
                  zero_stream=False):
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)

    presets = {
        "tiny": TransformerConfig.tiny,
        "gpt2-125m": TransformerConfig.gpt2_125m,
        "gpt2-1.5b": TransformerConfig.gpt2_1_5b,
        "llama2-7b": TransformerConfig.llama2_7b,
    }
    import jax.numpy as jnp

    cfg = presets[model_size](remat=False)
    model = CausalTransformerLM(cfg)
    if zero_stream:
        if tp > 1:
            # the streaming engine uploads unsharded layers; accepting
            # --tp would journal a configuration that never ran
            raise ValueError(
                "--zero-stream does not compose with --tp: the streaming "
                "path uploads unsharded per-layer working sets")
        # ZeRO-Inference: weights live on the host and stream per layer —
        # init must run on the HOST backend so a beyond-HBM model never
        # materialises on the chip (the engine host-casts the layer stack
        # itself; no extra host copy here)
        with jax.default_device(jax.devices("cpu")[0]):
            params = model.init(jax.random.key(0), dtype=jnp.bfloat16)
    else:
        params = model.init(jax.random.key(0))
    kwargs = {"dtype": dtype}
    if zero_stream:
        kwargs["zero"] = {"offload_param": {"device": "cpu"}}
    if quant:
        kwargs["quant"] = {"enabled": True, "num_bits": 8}
    if tp > 1:
        kwargs["tensor_parallel"] = {"tp_size": tp}
    engine = deepspeed_tpu.init_inference(model=model, params=params,
                                          max_out_tokens=prompt_len +
                                          max_new_tokens, **kwargs)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, prompt_len))

    # calibrate the host↔device round-trip floor (remote tunnels add a
    # fixed RPC cost per pulled result that is not model time)
    tiny = jax.jit(lambda x: x + 1)
    np.asarray(tiny(jnp.ones(4)))
    t0 = time.time()
    for _ in range(5):
        np.asarray(tiny(jnp.ones(4)))
    rpc_floor = (time.time() - t0) / 5
    if rpc_floor > 0.005:
        print(f"(host↔device round-trip floor: {rpc_floor * 1000:.1f} ms — "
              "subtracted from per-token latency)")
    else:
        rpc_floor = 0.0

    e2e, per_token = [], []
    for t in range(trials + 3):
        t0 = time.time()
        out = engine.generate(ids, max_new_tokens=max_new_tokens, seed=t)
        # host transfer, not block_until_ready: remote-tunnel backends ack
        # the dispatch before the compute queue drains
        np.asarray(out)
        dt = time.time() - t0
        e2e.append(dt)
        per_token.append(max(0.0, dt - rpc_floor) / max_new_tokens)

    stats = print_latency(per_token, f"generation token latency "
                          f"({model_size}, {dtype}"
                          f"{', int8' if quant else ''}, bs={batch})")
    e2e_stats = print_latency(e2e, f"end-to-end latency ({max_new_tokens} "
                              "tokens)")
    tput = batch * max_new_tokens / (sum(e2e[3:]) / max(1, len(e2e[3:])))
    print(f"\tThroughput: {tput:.1f} tokens/s")
    # one machine-readable line so harnesses (scripts/onchip_r03.py) can
    # journal the result without scraping the human table
    record = {"model": model_size, "dtype": dtype, "int8": bool(quant),
              "zero_stream": bool(zero_stream),
              "batch": batch, "prompt_len": prompt_len,
              "max_new_tokens": max_new_tokens,
              "rpc_floor_ms": round(rpc_floor * 1000, 2),
              "token_latency_ms": {k: round(v * 1000, 3)
                                   for k, v in (stats or {}).items()},
              "e2e_latency_ms": {k: round(v * 1000, 2)
                                 for k, v in (e2e_stats or {}).items()},
              "tokens_per_sec": round(tput, 1)}
    print(json.dumps(record))
    return stats


def main():
    ap = argparse.ArgumentParser(description="deepspeed_tpu gpt-bench")
    ap.add_argument("--model", default="tiny",
                    choices=["tiny", "gpt2-125m", "gpt2-1.5b", "llama2-7b"])
    ap.add_argument("--dtype", default="bf16")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--max-new-tokens", type=int, default=64)
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--int8", action="store_true")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--zero-stream", action="store_true",
                    help="ZeRO-Inference: host-resident weights streamed "
                         "per layer (beyond-HBM models)")
    args = ap.parse_args()
    run_benchmark(args.model, args.dtype, args.batch, args.prompt_len,
                  args.max_new_tokens, args.trials, quant=args.int8,
                  zero_stream=args.zero_stream,
                  tp=args.tp)


if __name__ == "__main__":
    main()
