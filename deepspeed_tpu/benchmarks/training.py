"""Training throughput benchmark (``ds_bench train``).

Role: the training-side counterpart of the reference's benchmark harnesses
(the reference ships comm + inference benches; its training numbers come
from blog-post runs — BASELINE.md).  Measures tokens/s, model TFLOPs and
MFU for a GPT shape under the engine's ZeRO/bf16/remat configuration.

Timing rules for the tunneled-TPU environment (see .claude/skills/verify):
fresh token batches every step (the tunnel memoizes repeated identical
dispatches), `jax.block_until_ready` on the final loss, warmup step
excluded.  Token ids are tiny (KBs) so H2D does not distort the numbers.

Usage::

    ds_bench train --model gpt_350m --batch 8 --gas 4 --seq 1024 \
        --zero-stage 3 --steps 10 [--remat-policy dots_saveable]
        [--attn-block-q 512 --attn-block-k 512] [--json]
"""

import argparse
import json
import time

MODELS = {
    "gpt2_125m": dict(hidden_size=768, n_layers=12, n_heads=12),
    "gpt_350m": dict(hidden_size=1024, n_layers=24, n_heads=16),
    "gpt_760m": dict(hidden_size=1536, n_layers=24, n_heads=16),
    # 1.01B: the largest shape whose full train state fits one 16 GB chip
    # with bf16 Adam moments (master 4B + mu 2B + nu 2B per param) — the
    # single-chip >=1B MFU config (ZeRO-3 Offload would need host traffic
    # that a tunneled chip cannot sustain)
    "gpt_1b": dict(hidden_size=2048, n_layers=18, n_heads=16),
    "gpt_1_1b": dict(hidden_size=2048, n_layers=20, n_heads=16),
    "gpt2_1_5b": dict(hidden_size=1600, n_layers=48, n_heads=25),
    "gpt_2_7b": dict(hidden_size=2560, n_layers=32, n_heads=32),
    # beyond-HBM ladder (param-stream: --offload-param cpu hosts the stack;
    # only the resident group + a working-set window live in HBM).  Host
    # Adam state is 16 B/param (fp32 master + 2 fp32 moments + bf16 mirror
    # + bf16 grad accum), so host RAM — not HBM — caps the ladder
    "gpt_5b": dict(hidden_size=4096, n_layers=24, n_heads=32),
    "gpt_6_7b": dict(hidden_size=4096, n_layers=32, n_heads=32),
    "gpt_8b": dict(hidden_size=4096, n_layers=40, n_heads=32),
    # north-star shapes (--arch llama: GQA + SwiGLU + RoPE + RMSNorm —
    # BASELINE.md's Llama-2-70B-class MFU target, scaled to chip)
    "llama_1b": dict(hidden_size=2048, n_layers=16, n_heads=16,
                     n_kv_heads=4, ffn_hidden_size=5632),
    "llama_3b": dict(hidden_size=3072, n_layers=26, n_heads=24,
                     n_kv_heads=8, ffn_hidden_size=8192),
    "llama_7b": dict(hidden_size=4096, n_layers=32, n_heads=32,
                     n_kv_heads=8, ffn_hidden_size=11008),
}

_PEAK_BF16 = (("v6", 918.0), ("v5p", 459.0), ("v5 lite", 197.0),
              ("v5e", 197.0), ("v5", 459.0), ("v4", 275.0), ("v3", 61.5))


def _peak_tflops(kind: str):
    k = (kind or "").lower()
    for sub, val in _PEAK_BF16:
        if sub in k:
            return val
    return None


def run_benchmark(model="gpt_350m", batch=8, gas=1, seq=1024, steps=10,
                  zero_stage=3, offload=None, remat=True,
                  remat_policy="dots_saveable", attn_block_q=None,
                  attn_block_k=None, dtype="bf16", vocab_size=None,
                  moment_dtype="float32", grad_accum_dtype=None,
                  arch=None, offload_param=None, resident_layers=0,
                  buffer_count=None, serial_boundary=False):
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    from deepspeed_tpu.parallel import groups

    groups.reset_mesh()
    ndev = jax.device_count()
    if batch % ndev:
        import sys
        batch = ndev * max(1, round(batch / ndev))   # global batch must
        print(f"# batch rounded to {batch} (divisible by {ndev} devices)",
              file=sys.stderr)
    shape = MODELS[model] if isinstance(model, str) else dict(model)
    if arch is None:     # auto from the model name; explicit --arch wins
        arch = ("llama" if isinstance(model, str)
                and model.startswith("llama") else "gpt")
    over = {}
    if attn_block_q:
        over["attn_block_q"] = attn_block_q
    if attn_block_k:
        over["attn_block_k"] = attn_block_k
    if arch == "llama":
        # GQA + SwiGLU + RoPE + RMSNorm (the BASELINE.md north-star shape)
        arch_kw = dict(activation="silu", use_rmsnorm=True, use_rope=True,
                       tie_embeddings=False,
                       vocab_size=vocab_size or 32000)
    else:
        arch_kw = dict(activation="gelu", use_rmsnorm=False, use_rope=False,
                       tie_embeddings=True,
                       vocab_size=vocab_size or 50304)
    cfg = TransformerConfig(
        max_seq_len=seq, remat=remat, remat_policy=remat_policy,
        **arch_kw, **shape, **over)
    model_obj = CausalTransformerLM(cfg)

    zero = {"stage": zero_stage}
    if offload:
        zero["offload_optimizer"] = {"device": offload}
    if offload_param:
        pc = {"device": offload_param}
        if resident_layers:
            pc["resident_layers"] = resident_layers
        if buffer_count:
            pc["buffer_count"] = buffer_count
        zero["offload_param"] = pc
        # param-stream needs the host Adam; default its state host-side too
        zero.setdefault("offload_optimizer", {"device": "cpu"})
    ds_config = {"train_micro_batch_size_per_gpu": batch // ndev,
                 "gradient_accumulation_steps": gas,
                 "optimizer": {"type": "AdamW",
                               "params": {"lr": 1e-4,
                                          "moment_dtype": moment_dtype}},
                 dtype: {"enabled": True},
                 "zero_optimization": zero}
    if grad_accum_dtype:
        ds_config["data_types"] = {"grad_accum_dtype": grad_accum_dtype}
    if offload_param:
        # beyond-HBM init: run the initialiser on the HOST backend (the
        # full tree must never materialise in HBM — zero.Init
        # remote_device semantics), at compute dtype to halve host RAM
        import jax.numpy as jnp
        with jax.default_device(jax.devices("cpu")[0]):
            params0 = model_obj.init(
                jax.random.key(0),
                dtype=jnp.bfloat16 if dtype == "bf16" else jnp.float32)
        params0 = jax.tree_util.tree_map(np.asarray, params0)
    else:
        params0 = model_obj.init(jax.random.key(0))
    engine, *_ = deepspeed_tpu.initialize(
        model=model_obj, model_parameters=params0, config=ds_config)
    del params0
    if serial_boundary and getattr(engine, "_param_stream", None):
        engine._param_stream.boundary_pipelined = False   # ablation

    rng = np.random.default_rng(0)
    bshape = (gas, batch, seq) if gas > 1 else (batch, seq)

    def make_batch():
        return {"input_ids": rng.integers(0, cfg.vocab_size, bshape)}

    loss = engine.train_batch(batch=make_batch())          # compile+warmup
    jax.block_until_ready(loss)
    t0 = time.time()
    for _ in range(steps):
        loss = engine.train_batch(batch=make_batch())
    jax.block_until_ready(loss)
    dt = time.time() - t0

    n_chips = max(1, engine.mesh.size)
    tokens = gas * batch * seq * steps
    tps = tokens / dt
    tflops = 6.0 * cfg.num_params() * tps / 1e12 / n_chips
    kind = getattr(jax.devices()[0], "device_kind", "")
    peak = _peak_tflops(kind)
    out = {
        "model": model if isinstance(model, str) else "custom",
        "n_params": cfg.num_params(),
        "batch": batch, "gas": gas, "seq": seq, "zero_stage": zero_stage,
        "steps": steps,
        "tokens_per_sec_per_chip": round(tps / n_chips, 1),
        "model_tflops_per_chip": round(tflops, 2),
        "loss": float(loss),
        "device_kind": kind, "n_chips": n_chips,
    }
    if moment_dtype != "float32":
        out["moment_dtype"] = moment_dtype
    if grad_accum_dtype:
        out["grad_accum_dtype"] = grad_accum_dtype
    if offload_param:
        out["offload_param"] = offload_param
        out["resident_layers"] = resident_layers
        out["boundary"] = "serial" if serial_boundary else "pipelined"
    if arch != "gpt":
        out["arch"] = arch
    if peak:
        out["mfu"] = round(tflops / peak, 4)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="ds_bench train", description=__doc__.splitlines()[0])
    p.add_argument("--model", default="gpt_350m", choices=sorted(MODELS))
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--gas", type=int, default=1)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--zero-stage", type=int, default=3)
    p.add_argument("--offload", choices=["cpu", "nvme"], default=None)
    p.add_argument("--offload-param", choices=["cpu", "nvme"], default=None,
                   help="host the parameter stack (param-stream): only the "
                        "resident group + a working-set window live in HBM")
    p.add_argument("--resident-layers", type=int, default=0)
    p.add_argument("--buffer-count", type=int, default=None)
    p.add_argument("--serial-boundary", action="store_true",
                   help="ablation: serial GAS-boundary walk instead of the "
                        "threaded Adam/H2D pipeline")
    p.add_argument("--arch", choices=["gpt", "llama"], default=None,
                   help="default: auto from the model name")
    p.add_argument("--no-remat", action="store_true")
    p.add_argument("--remat-policy", default="dots_saveable")
    p.add_argument("--attn-block-q", type=int, default=None)
    p.add_argument("--attn-block-k", type=int, default=None)
    p.add_argument("--dtype", choices=["bf16", "fp16"], default="bf16")
    p.add_argument("--moment-dtype", choices=["float32", "bfloat16"],
                   default="float32",
                   help="Adam moment storage dtype (bfloat16 halves "
                        "optimizer-state HBM; stochastic rounding)")
    p.add_argument("--grad-accum-dtype", choices=["float32", "bfloat16"],
                   default=None,
                   help="grad tree / GAS-carry dtype (data_types."
                        "grad_accum_dtype; bfloat16 halves grad HBM)")
    p.add_argument("--json", action="store_true",
                   help="print one JSON line instead of a table")
    a = p.parse_args(argv)
    out = run_benchmark(
        model=a.model, batch=a.batch, gas=a.gas, seq=a.seq, steps=a.steps,
        zero_stage=a.zero_stage, offload=a.offload, remat=not a.no_remat,
        remat_policy=a.remat_policy, attn_block_q=a.attn_block_q,
        attn_block_k=a.attn_block_k, dtype=a.dtype,
        moment_dtype=a.moment_dtype, grad_accum_dtype=a.grad_accum_dtype,
        arch=a.arch, offload_param=a.offload_param,
        resident_layers=a.resident_layers, buffer_count=a.buffer_count,
        serial_boundary=a.serial_boundary)
    if a.json:
        print(json.dumps(out))
    else:
        width = max(len(k) for k in out)
        for k, v in out.items():
            print(f"  {k:<{width}}  {v}")
    return out


if __name__ == "__main__":
    main()
