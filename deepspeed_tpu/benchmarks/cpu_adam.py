"""Host Adam throughput benchmark (round-2 verdict, weak #8).

The ZeRO-Offload optimizer step is host-bound at 1B+ offloaded params, so
the fused C++ pass (``ops/csrc/cpu_adam.cpp``, OpenMP + auto-vectorised)
must demonstrably beat the numpy fallback and approach memory bandwidth —
the reference justifies its hand-written AVX the same way
(``csrc/includes/simd.h``).

Bytes moved per element per step: read p/g/m/v + write p/m/v = 7 x 4 B.

Run:  python -m deepspeed_tpu.benchmarks.cpu_adam [--numel 50000000]
Prints one JSON line per implementation plus a summary line.
"""

import argparse
import json
import time

import numpy as np

from deepspeed_tpu.ops import cpu_adam

BYTES_PER_ELEM = 7 * 4  # read p,g,m,v; write p,m,v (fp32)


def _time_impl(numel: int, reps: int, force_numpy: bool):
    rng = np.random.default_rng(0)
    p = rng.normal(size=numel).astype(np.float32)
    g = rng.normal(size=numel).astype(np.float32)
    st = cpu_adam.init_state(numel)
    saved = None
    if force_numpy:
        saved = cpu_adam._lib, cpu_adam._lib_tried
        cpu_adam._lib, cpu_adam._lib_tried = None, True
    try:
        native = cpu_adam._load_native() is not None
        ts = []
        for _ in range(reps + 1):  # first rep warms page faults / JIT caches
            t0 = time.perf_counter()
            st = cpu_adam.adam_update(p, g, st, lr=1e-4, weight_decay=0.01)
            ts.append(time.perf_counter() - t0)
        best = min(ts[1:])
    finally:
        if saved is not None:
            cpu_adam._lib, cpu_adam._lib_tried = saved
    return {
        "impl": "fused_cpp" if native else "numpy",
        "numel": numel,
        "sec_per_step": round(best, 4),
        "gbps": round(numel * BYTES_PER_ELEM / best / 1e9, 2),
        "melem_per_sec": round(numel / best / 1e6, 1),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--numel", type=int, default=50_000_000,
                    help="elements per step (50M fp32 = 200MB params, the "
                         "shape of a ~1B-param model's offload sub-group)")
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args(argv)

    rows = [_time_impl(args.numel, args.reps, force_numpy=False)]
    if rows[0]["impl"] == "fused_cpp":
        rows.append(_time_impl(args.numel, args.reps, force_numpy=True))
    for r in rows:
        print(json.dumps(r))
    if len(rows) == 2:
        summary = {
            "metric": "cpu_adam_fused_vs_numpy_speedup",
            "value": round(rows[1]["sec_per_step"] / rows[0]["sec_per_step"],
                           2),
            "unit": "x",
            "fused_gbps": rows[0]["gbps"],
            "numpy_gbps": rows[1]["gbps"],
        }
        print(json.dumps(summary))
        rows.append(summary)
    return rows


if __name__ == "__main__":
    main()
