"""Async-I/O micro-benchmark (reference ``csrc/aio/py_test/ds_aio_bench``).

Measures GB/s of the io_uring engine at several queue depths / block sizes
against the thread-pool fallback tier, on the same pre-faulted pinned
buffer, and prints one JSON line per configuration.

Run:  python -m deepspeed_tpu.benchmarks.aio [--size-mb 256] [--file PATH]
"""

import argparse
import json
import os
import tempfile
import time

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle


def _bench_read(handle, buf, path, reps):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        handle.async_pread(buf, path)
        handle.wait()
        ts.append(time.perf_counter() - t0)
    return buf.nbytes / min(ts) / 1e9


def _bench_write(handle, buf, path, reps):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        handle.async_pwrite(buf, path)
        handle.wait()
        ts.append(time.perf_counter() - t0)
    return buf.nbytes / min(ts) / 1e9


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=int, default=256)
    ap.add_argument("--file", default=None,
                    help="target file (put it on NVMe to bench the device; "
                         "default: a tempfile)")
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args(argv)

    nbytes = args.size_mb << 20
    tmpdir = None
    if args.file is None:
        tmpdir = tempfile.mkdtemp(prefix="ds_aio_bench_")
        path = os.path.join(tmpdir, "blob.bin")
    else:
        path = args.file

    seed_handle = AsyncIOHandle()
    buf = seed_handle.new_cpu_locked_tensor(nbytes, np.uint8)
    buf[:] = 1
    seed_handle.sync_pwrite(buf, path)

    results = []
    for qd, bs in ((1, 1 << 20), (8, 1 << 20), (16, 1 << 20), (16, 4 << 20)):
        h = AsyncIOHandle(block_size=bs, queue_depth=qd)
        tier = "io_uring" if h.uses_io_uring() else "threadpool"
        row = {"tier": tier, "queue_depth": qd, "block_kb": bs >> 10,
               "read_gbps": round(_bench_read(h, buf, path, args.reps), 3),
               "write_gbps": round(_bench_write(h, buf, path, args.reps), 3)}
        results.append(row)
        print(json.dumps(row))
    for threads in (4, 8):
        h = AsyncIOHandle(thread_count=threads)
        h._engine = None
        row = {"tier": "threadpool", "threads": threads,
               "block_kb": h.get_block_size() >> 10,
               "read_gbps": round(_bench_read(h, buf, path, args.reps), 3),
               "write_gbps": round(_bench_write(h, buf, path, args.reps), 3)}
        results.append(row)
        print(json.dumps(row))

    seed_handle.free_cpu_locked_tensor(buf)
    if tmpdir:
        try:
            os.unlink(path)
            os.rmdir(tmpdir)
        except OSError:
            pass
    return results


if __name__ == "__main__":
    main()
