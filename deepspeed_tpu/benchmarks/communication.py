"""Communication micro-benchmark — the ``ds_bench`` CLI.

Parity: reference ``benchmarks/communication/run_all.py`` + ``bin/ds_bench``
(all_reduce / all_gather / reduce_scatter / all_to_all / broadcast / pt2pt
with ``--scan`` over sizes; reports latency, algbw, busbw).

TPU flavor: each collective is a ``shard_map``-wrapped ``jax.lax``
collective over a 1-D mesh of all local devices, jitted then timed with
``block_until_ready``.  Bus-bandwidth factors follow the standard
nccl-tests accounting.
"""

import argparse
import time
from functools import partial

import numpy as np

COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
               "broadcast", "pt2pt")


def _busbw_factor(coll, n):
    if coll == "all_reduce":
        return 2.0 * (n - 1) / n
    if coll in ("all_gather", "reduce_scatter", "all_to_all"):
        return (n - 1) / n
    return 1.0  # broadcast / pt2pt


def build_collective_fn(coll, mesh, axis="world"):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]

    if coll == "all_reduce":
        def body(x):
            return jax.lax.psum(x, axis)
        in_spec, out_spec = P(axis), P(axis)
    elif coll == "all_gather":
        def body(x):
            return jax.lax.all_gather(x, axis, tiled=True)
        in_spec, out_spec = P(axis), P(axis)
    elif coll == "reduce_scatter":
        def body(x):
            return jax.lax.psum_scatter(x, axis, tiled=True)
        in_spec, out_spec = P(axis), P(axis)
    elif coll == "all_to_all":
        def body(x):
            return jax.lax.all_to_all(x.reshape(n, -1), axis, 0, 0,
                                      tiled=True).reshape(-1)
        in_spec, out_spec = P(axis), P(axis)
    elif coll == "broadcast":
        def body(x):
            src = jax.lax.all_gather(x, axis, tiled=False)[0]
            return src
        in_spec, out_spec = P(axis), P(axis)
    elif coll == "pt2pt":
        def body(x):
            return jax.lax.ppermute(
                x, axis, [(i, (i + 1) % n) for i in range(n)])
        in_spec, out_spec = P(axis), P(axis)
    else:
        raise ValueError(f"unknown collective '{coll}'")

    fn = shard_map(body, mesh=mesh, in_specs=in_spec, out_specs=out_spec)
    return jax.jit(fn)


def run_collective(coll, size_bytes, mesh, axis="world", trials=20,
                   warmups=5, dtype="float32"):
    """Times one collective at one size; returns dict with latency/bw."""
    import jax
    import jax.numpy as jnp

    n = mesh.shape[axis]
    dt = jnp.dtype(dtype)
    count = max(n, int(size_bytes) // dt.itemsize)
    count -= count % n  # divisible by the axis for scatter/a2a
    if count == 0:
        count = n
    x = jnp.zeros((count,), dt)
    fn = build_collective_fn(coll, mesh, axis)
    out = jax.block_until_ready(fn(x))  # compile
    for _ in range(warmups):
        out = jax.block_until_ready(fn(x))
    t0 = time.perf_counter()
    for _ in range(trials):
        out = jax.block_until_ready(fn(x))
    elapsed = (time.perf_counter() - t0) / trials
    del out
    size = count * dt.itemsize
    algbw = size / elapsed  # B/s
    busbw = algbw * _busbw_factor(coll, n)
    return {"collective": coll, "size_bytes": size, "world": n,
            "latency_us": elapsed * 1e6, "algbw_GBps": algbw / 1e9,
            "busbw_GBps": busbw / 1e9}


def scan_sizes(min_pow=10, max_pow=24):
    return [2 ** p for p in range(min_pow, max_pow + 1)]


def print_header(coll, n):
    print(f"\n---- {coll}  (world={n}) " + "-" * 40)
    print(f"{'size':>12} {'latency(us)':>14} {'algbw(GB/s)':>13} "
          f"{'busbw(GB/s)':>13}")


def main(argv=None):
    parser = argparse.ArgumentParser(description="deepspeed_tpu comm bench")
    parser.add_argument("--collective", type=str, default="all_reduce",
                        choices=COLLECTIVES + ("all",))
    parser.add_argument("--scan", action="store_true",
                        help="sweep sizes 1KB..16MB")
    parser.add_argument("--size", type=int, default=2 ** 22,
                        help="payload bytes when not scanning")
    parser.add_argument("--trials", type=int, default=20)
    parser.add_argument("--warmups", type=int, default=5)
    parser.add_argument("--dtype", type=str, default="float32")
    parser.add_argument("--maxsize", type=int, default=24,
                        help="log2 of the largest scanned size")
    args = parser.parse_args(argv)

    import jax
    from jax.sharding import Mesh
    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("world",))

    colls = COLLECTIVES if args.collective == "all" else (args.collective,)
    sizes = scan_sizes(max_pow=args.maxsize) if args.scan else [args.size]
    results = []
    for coll in colls:
        print_header(coll, mesh.shape["world"])
        for size in sizes:
            r = run_collective(coll, size, mesh, trials=args.trials,
                               warmups=args.warmups, dtype=args.dtype)
            results.append(r)
            print(f"{r['size_bytes']:>12} {r['latency_us']:>14.1f} "
                  f"{r['algbw_GBps']:>13.2f} {r['busbw_GBps']:>13.2f}")
    return results


if __name__ == "__main__":
    main()
