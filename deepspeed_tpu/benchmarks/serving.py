"""Serving benchmark: continuous batching vs sequential generation.

Parity role: the reference's inference benchmarks report per-token latency
for one stream (``benchmarks/inference/gpt-bench.py``); this adds the
serving-throughput view — aggregate tokens/s over a request mix — where
the paged continuous-batching engine earns its keep.

Run:  python -m deepspeed_tpu.benchmarks.serving [--model gpt2_125m]
      [--requests 16] [--max-batch 8] [--prompt-len 128] [--gen 64]
Prints one JSON line per mode.
"""

import argparse
import json
import time

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2_125m",
                    choices=["tiny", "gpt2_125m", "gpt2_1_5b"])
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--gen", type=int, default=64)
    ap.add_argument("--page-size", type=int, default=128)
    ap.add_argument("--decode-chunk", type=int, default=8,
                    help="tokens per device dispatch in the chunked mode "
                         "(0 disables the chunked measurement)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from deepspeed_tpu.inference.serving import ServingEngine
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)

    cfg = getattr(TransformerConfig, args.model)() \
        if args.model != "tiny" else TransformerConfig.tiny(hidden_size=64,
                                                            n_heads=4)
    cfg = type(cfg)(**{**cfg.__dict__, "remat": False})
    model = CausalTransformerLM(cfg)
    dtype = jnp.float32 if args.cpu else jnp.bfloat16
    params = model.init(jax.random.key(0), dtype=dtype)

    rng = np.random.default_rng(0)
    # ragged prompts around the nominal length (realistic mix)
    lens = rng.integers(max(4, args.prompt_len // 2), args.prompt_len + 1,
                        args.requests)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).tolist() for n in lens]
    max_seq = args.prompt_len + args.gen + args.page_size

    # -- continuous batching -------------------------------------------
    eng = ServingEngine(model, params, max_batch=args.max_batch,
                        page_size=args.page_size, max_seq=max_seq,
                        dtype=dtype)
    # warmup compiles (prefill buckets + decode step) on a throwaway
    eng.generate([prompts[0]], max_new_tokens=2)

    t0 = time.perf_counter()
    outs = eng.generate(prompts, max_new_tokens=args.gen)
    dt = time.perf_counter() - t0
    gen_tokens = sum(len(o) - n for o, n in zip(outs, lens))
    print(json.dumps({
        "mode": "continuous_batching",
        "requests": args.requests, "max_batch": args.max_batch,
        "gen_tokens": int(gen_tokens), "wall_s": round(dt, 3),
        "tokens_per_sec": round(gen_tokens / dt, 1),
    }))

    # -- continuous batching, chunked on-device decode -----------------
    if args.decode_chunk > 1:
        eng = ServingEngine(model, params, max_batch=args.max_batch,
                            page_size=args.page_size, max_seq=max_seq,
                            dtype=dtype, decode_chunk=args.decode_chunk)
        eng.generate([prompts[0]], max_new_tokens=2)   # warmup compiles
        t0 = time.perf_counter()
        outs_c = eng.generate(prompts, max_new_tokens=args.gen)
        dt = time.perf_counter() - t0
        assert outs_c == outs, \
            "chunked greedy decode diverged from per-token decode"
        gen_tokens = sum(len(o) - n for o, n in zip(outs_c, lens))
        print(json.dumps({
            "mode": f"continuous_batching_chunk{args.decode_chunk}",
            "requests": args.requests, "max_batch": args.max_batch,
            "gen_tokens": int(gen_tokens), "wall_s": round(dt, 3),
            "tokens_per_sec": round(gen_tokens / dt, 1),
        }))

    # -- sequential single-stream baseline (reference-style) -----------
    from deepspeed_tpu.parallel import groups
    import deepspeed_tpu
    groups.reset_mesh()
    ie = deepspeed_tpu.init_inference(
        model=model, params=params,
        config={"dtype": "fp32" if args.cpu else "bf16",
                "max_out_tokens": max_seq})
    ie.generate(np.asarray(prompts[0])[None, :], max_new_tokens=2)  # warmup
    t0 = time.perf_counter()
    seq_tokens = 0
    for p in prompts[: max(2, args.requests // 4)]:   # subset: it's slow
        out = ie.generate(np.asarray(p)[None, :], max_new_tokens=args.gen)
        seq_tokens += out.shape[1] - len(p)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "mode": "sequential_single_stream",
        "requests_measured": max(2, args.requests // 4),
        "gen_tokens": int(seq_tokens), "wall_s": round(dt, 3),
        "tokens_per_sec": round(seq_tokens / dt, 1),
    }))


if __name__ == "__main__":
    main()
