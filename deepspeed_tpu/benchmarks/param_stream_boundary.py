"""Param-stream GAS-boundary + streamed-writeback benchmark (round-4
verdict, next #4).

Two measurements, both on the real local device (no synthetic SlowHandle):

* ``boundary``  — ``ParamStreamRunner._apply_boundary`` with the threaded
  Adam/H2D pipeline vs the serial reference walk.  The pipeline hides the
  H2D re-upload of updated units (resident group + pinned + first window)
  under the C++ Adam of later units.
* ``writeback`` — ``HostOffloadOptimizer.step_streamed`` (per-leaf D2H /
  per-subgroup Adam / per-leaf H2D, all overlapped) vs the serial
  D2H → step() → whole-tree cast + upload sequence the engine used before
  round 4.  Reference anchor: the per-bucket H2D streams of
  ``stage_1_and_2.py:1086``.

Run:  python -m deepspeed_tpu.benchmarks.param_stream_boundary
      [--hidden 2048] [--layers 16] [--numel 200000000] [--reps 3]
Prints one JSON line per section plus a summary line.
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer


def _runner(hidden, layers, vocab, buffer_count):
    cfg = TransformerConfig(
        vocab_size=vocab, hidden_size=hidden, n_layers=layers,
        n_heads=max(4, hidden // 128), max_seq_len=128)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {
                "stage": 0,
                "offload_param": {"device": "cpu",
                                  "buffer_count": buffer_count},
                "offload_optimizer": {"device": "cpu"},
            },
        })
    return engine._param_stream


def _fill_grads(store, rng):
    store.res_gacc[:] = rng.normal(
        size=store.res_gacc.shape).astype(store.res_gacc.dtype)
    if store.homogeneous:
        store.gaccs[:] = rng.normal(
            size=store.gaccs.shape).astype(store.gaccs.dtype)
    else:
        for g in store.gaccs:
            g[:] = rng.normal(size=g.shape).astype(g.dtype)


def _block_runner(runner):
    jax.block_until_ready(runner.resident_dev)
    for t in list(runner._pinned.values()) + list(runner._dev.values()):
        jax.block_until_ready(t)


def _time_boundary(runner, pipelined, reps, warmup=True):
    rng = np.random.default_rng(0)
    if warmup:
        _fill_grads(runner.store, rng)
        runner._apply_boundary(1e-4, None, 1, pipelined=pipelined)
        _block_runner(runner)
    ts = []
    for _ in range(reps):
        _fill_grads(runner.store, rng)
        t0 = time.perf_counter()
        runner._apply_boundary(1e-4, None, 1, pipelined=pipelined)
        _block_runner(runner)
        ts.append(time.perf_counter() - t0)
    return min(ts)


def _time_writeback(numel, sub_groups, reps):
    dev = jax.devices()[0]
    sh = jax.sharding.SingleDeviceSharding(dev)
    params = {"w": np.zeros(numel, np.float32)}
    zc = DeepSpeedZeroConfig({"stage": 3,
                              "sub_group_size": numel // sub_groups})

    def build():
        return HostOffloadOptimizer(params, zc, opt_name="adamw",
                                    opt_params={"lr": 1e-4})

    rng = np.random.default_rng(0)
    g_host = rng.normal(size=numel).astype(np.float32)
    g_dev = jax.device_put(g_host, sh)
    jax.block_until_ready(g_dev)

    opt = build()
    serial, streamed = [], []
    for i in range(reps + 1):
        # serial: D2H fetch, full Adam, whole-tree cast + upload tail
        t0 = time.perf_counter()
        host_g = {"w": np.asarray(jax.device_get(g_dev))}
        opt.step(host_g)
        new = jax.device_put(
            opt.params_tree(dtype=np.dtype("bfloat16"))["w"], sh)
        jax.block_until_ready(new)
        if i > 0:                     # first rep is warmup
            serial.append(time.perf_counter() - t0)
    opt = build()
    for i in range(reps + 1):
        t0 = time.perf_counter()
        new = opt.step_streamed({"w": g_dev}, upload_shardings={"w": sh},
                                upload_dtype=np.dtype("bfloat16"))
        jax.block_until_ready(new)
        if i > 0:
            streamed.append(time.perf_counter() - t0)
    return min(serial), min(streamed)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--buffer-count", type=int, default=5)
    ap.add_argument("--numel", type=int, default=200_000_000)
    ap.add_argument("--sub-groups", type=int, default=16)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend in-process (the JAX_PLATFORMS "
                         "env var can hang under the site backend hook)")
    args = ap.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    runner = _runner(args.hidden, args.layers, args.vocab, args.buffer_count)
    n = runner.store.num_params()
    serial_b = _time_boundary(runner, pipelined=False, reps=args.reps)
    piped_b = _time_boundary(runner, pipelined=True, reps=args.reps)
    boundary = {
        "section": "boundary", "n_params": n,
        "serial_sec": round(serial_b, 4), "pipelined_sec": round(piped_b, 4),
        "speedup_x": round(serial_b / piped_b, 3),
        "hidden": args.hidden, "layers": args.layers,
        "buffer_count": args.buffer_count,
        "device": jax.devices()[0].platform,
    }
    print(json.dumps(boundary))

    ser_w, str_w = _time_writeback(args.numel, args.sub_groups, args.reps)
    writeback = {
        "section": "writeback", "numel": args.numel,
        "serial_sec": round(ser_w, 4), "streamed_sec": round(str_w, 4),
        "speedup_x": round(ser_w / str_w, 3),
        "sub_groups": args.sub_groups,
        "device": jax.devices()[0].platform,
    }
    print(json.dumps(writeback))
    print(json.dumps({"section": "summary",
                      "boundary_speedup_x": boundary["speedup_x"],
                      "writeback_speedup_x": writeback["speedup_x"]}))
    return boundary, writeback


if __name__ == "__main__":
    main()
