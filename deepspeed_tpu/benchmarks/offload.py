"""Offload-overlap benchmark (round-2 verdict, weak #5).

Measures the wall-clock of the NVMe-swapped optimizer step with the
3-deep pipeline (async moment prefetch / C++ Adam / async write-back)
against a fully serialised baseline on the same store — the measurement
the reference's ``partitioned_optimizer_swapper`` exists to win.

Run:  python -m deepspeed_tpu.benchmarks.offload [--numel 100000000]
      [--swap-dir /path/on/nvme]
Prints one JSON line per mode plus a speedup summary.
"""

import argparse
import json
import shutil
import tempfile
import time

import numpy as np

from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer


def _build(numel, sub_group_size, swap_dir, pipelined):
    params = {"w": np.zeros(numel, np.float32)}
    zc = DeepSpeedZeroConfig({
        "stage": 3,
        "sub_group_size": sub_group_size,
        "offload_optimizer": {"device": "nvme", "nvme_path": swap_dir},
    })
    opt = HostOffloadOptimizer(params, zc, opt_name="adamw",
                               opt_params={"lr": 1e-4})
    opt.swapper.pipelined = pipelined
    return opt


def _time_steps(opt, numel, reps):
    rng = np.random.default_rng(0)
    grads = {"w": rng.normal(size=numel).astype(np.float32)}
    opt.step(grads)                   # warm: creates + initialises swap files
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        opt.step(grads)
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--numel", type=int, default=100_000_000,
                    help="flat fp32 master elements (100M = 400MB, 800MB "
                         "of swapped Adam moments)")
    ap.add_argument("--sub-groups", type=int, default=8)
    ap.add_argument("--swap-dir", default=None,
                    help="put this on the NVMe device to bench it; "
                         "default: a tempdir")
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args(argv)

    base = args.swap_dir or tempfile.mkdtemp(prefix="ds_offload_bench_")
    sub = -(-args.numel // args.sub_groups)
    rows = []
    try:
        for pipelined in (True, False):
            d = tempfile.mkdtemp(dir=base)
            opt = _build(args.numel, sub, d, pipelined)
            sec = _time_steps(opt, args.numel, args.reps)
            rows.append({
                "mode": "pipelined" if pipelined else "serial",
                "numel": args.numel, "sub_groups": args.sub_groups,
                "sec_per_step": round(sec, 4),
                "swapped_gbps": round(
                    # moments read + written per step: 2 x 2 x 4 B/elem
                    args.numel * 16 / sec / 1e9, 2),
            })
            print(json.dumps(rows[-1]))
            shutil.rmtree(d, ignore_errors=True)
    finally:
        if args.swap_dir is None:
            shutil.rmtree(base, ignore_errors=True)
    if len(rows) == 2:
        summary = {"metric": "offload_pipeline_speedup",
                   "value": round(rows[1]["sec_per_step"] /
                                  rows[0]["sec_per_step"], 2),
                   "unit": "x"}
        print(json.dumps(summary))
        rows.append(summary)
    return rows


if __name__ == "__main__":
    main()
