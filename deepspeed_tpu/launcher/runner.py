"""deepspeed CLI front-end: resource parsing + multi-host process launch.

Parity: reference ``launcher/runner.py`` (``main:380``,
``fetch_hostfile:184``, ``parse_resource_filter:245``,
``encode_world_info:345``).

TPU-first: the unit of launch is a *host process* (JAX: one process per
host drives all local chips), not one process per accelerator.  A
hostfile line ``host slots=N`` therefore means N processes on that host
(N=1 on TPU VMs; N>1 is used for CPU-simulated multi-process testing).
The spawned processes rendezvous via ``jax.distributed.initialize`` using
the ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
``JAX_PROCESS_ID`` env contract (our MASTER_ADDR/RANK analogue).
"""

import argparse
import base64
import collections
import json
import os
import shlex
import signal
import subprocess
import sys

from deepspeed_tpu.launcher.constants import (DEFAULT_MASTER_PORT,
                                              GCLOUD_TPU_LAUNCHER,
                                              MPICH_LAUNCHER,
                                              MVAPICH_LAUNCHER,
                                              OPENMPI_LAUNCHER,
                                              PDSH_LAUNCHER, SLURM_LAUNCHER)
from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher: run a training script across "
        "TPU hosts (or local processes)")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="MPI-style hostfile: lines of 'host slots=N'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="NODE_SPEC[@NODE_SPEC...]; "
                        "NODE_SPEC=NAME[:SLOT[,SLOT...]]")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="same grammar as --include; mutually exclusive")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="limit to first N nodes of the resource pool")
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus",
                        help="processes per node (slots) to use")
    parser.add_argument("--master_port", type=int,
                        default=int(os.environ.get("DS_MASTER_PORT",
                                                   DEFAULT_MASTER_PORT)))
    parser.add_argument("--master_addr", type=str,
                        default=os.environ.get("DS_MASTER_ADDR", ""))
    parser.add_argument("--launcher", type=str, default=PDSH_LAUNCHER,
                        choices=[PDSH_LAUNCHER, OPENMPI_LAUNCHER,
                                 MPICH_LAUNCHER, SLURM_LAUNCHER,
                                 MVAPICH_LAUNCHER, GCLOUD_TPU_LAUNCHER])
    parser.add_argument("--launcher_args", type=str, default="",
                        help="extra args for the cluster launcher backend")
    parser.add_argument("--force_multi", action="store_true",
                        help="force multi-node mode even for one host")
    parser.add_argument("--autotuning", type=str, default="",
                        choices=["", "tune", "run"],
                        help="run the autotuner before/instead of training")
    parser.add_argument("--elastic_training", action="store_true")
    parser.add_argument("--min_elastic_nodes", type=int, default=-1)
    parser.add_argument("--max_elastic_nodes", type=int, default=-1)
    parser.add_argument("--dry_run", action="store_true",
                        help="print the launch plan, do not spawn")
    parser.add_argument("user_script", type=str, nargs="?", default=None,
                        help="training script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER, default=[])
    return parser.parse_args(args=args)


# ----------------------------------------------------------------------
# resource pool (parity: fetch_hostfile:184 + filters :245)
# ----------------------------------------------------------------------
def _parse_hostfile_lines(lines):
    pool = collections.OrderedDict()
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            host, slots = line.split()
            key, count = slots.split("=")
            if key != "slots":
                raise ValueError(key)
            count = int(count)
        except ValueError:
            raise ValueError(
                f"hostfile line '{line}' is not of the form 'host slots=N'")
        if host in pool:
            raise ValueError(f"hostfile: duplicate host '{host}'")
        pool[host] = count
    return pool


def fetch_hostfile(hostfile_path):
    """Returns OrderedDict host -> slot count, or None when no hostfile
    exists (single-node mode)."""
    if not os.path.isfile(hostfile_path):
        logger.warning(f"no hostfile at {hostfile_path}; launching locally")
        return None
    with open(hostfile_path) as f:
        return _parse_hostfile_lines(f.readlines())


def _parse_node_spec(spec):
    if ":" in spec:
        name, slots = spec.split(":")
        return name, [int(s) for s in slots.split(",")]
    return spec, None


def parse_resource_filter(resource_pool, include_str="", exclude_str=""):
    """Apply --include/--exclude node specs to the pool.  Slot lists select
    (or remove) individual slot indices."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")

    pool = collections.OrderedDict(
        (host, list(range(n))) for host, n in resource_pool.items())

    if include_str:
        keep = collections.OrderedDict()
        for spec in include_str.split("@"):
            name, slots = _parse_node_spec(spec)
            if name not in pool:
                raise ValueError(f"--include: unknown host '{name}'")
            avail = pool[name]
            if slots is None:
                keep[name] = avail
            else:
                bad = [s for s in slots if s not in avail]
                if bad:
                    raise ValueError(
                        f"--include: host '{name}' has no slots {bad}")
                keep[name] = sorted(slots)
        return keep

    if exclude_str:
        for spec in exclude_str.split("@"):
            name, slots = _parse_node_spec(spec)
            if name not in pool:
                raise ValueError(f"--exclude: unknown host '{name}'")
            if slots is None:
                del pool[name]
            else:
                pool[name] = [s for s in pool[name] if s not in slots]
                if not pool[name]:
                    del pool[name]
        return pool

    return pool


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    return parse_resource_filter(resource_pool, include_str=inclusion,
                                 exclude_str=exclusion)


def encode_world_info(active_resources):
    """base64(json) of host -> slot list — the cross-process contract read
    by ``launch.py`` (parity: ``encode_world_info:345``)."""
    as_lists = {h: list(s) for h, s in active_resources.items()}
    return base64.urlsafe_b64encode(
        json.dumps(as_lists).encode()).decode()


def decode_world_info(world_info_base64):
    return json.loads(base64.urlsafe_b64decode(world_info_base64.encode()))


# ----------------------------------------------------------------------
# main
# ----------------------------------------------------------------------
def main(args=None):
    args = parse_args(args)

    if args.elastic_training:
        from deepspeed_tpu.elasticity import compute_elastic_config  # noqa: F401
        assert args.min_elastic_nodes > 0, \
            "--elastic_training needs --min_elastic_nodes"

    resource_pool = fetch_hostfile(args.hostfile)

    if args.autotuning:
        from deepspeed_tpu.autotuning.autotuner import Autotuner
        # the config path travels in the user script's own args
        # (REMAINDER): surface it for the tuner
        if getattr(args, "deepspeed_config", None) is None:
            args.deepspeed_config = _find_user_arg(
                args.user_args, ("--deepspeed_config", "--ds_config"))
        tuner = Autotuner(args, active_resources=resource_pool)
        tuner.tune()
        if args.autotuning == "tune":
            return 0
        # "run": swap the user script's config for the best one the tuner
        # wrote (reference: ds_config_optimal.json under the results dir)
        if tuner.optimal_config_path and args.user_args:
            args.user_args = _replace_user_arg(
                args.user_args, ("--deepspeed_config", "--ds_config"),
                tuner.optimal_config_path)

    if resource_pool is None or (len(resource_pool) == 1
                                 and not args.force_multi):
        return _launch_single_node(args, resource_pool)
    return _launch_multi_node(args, resource_pool)


def _find_user_arg(user_args, names):
    """Value of ``--flag v`` / ``--flag=v`` inside the REMAINDER args."""
    for i, a in enumerate(user_args):
        for n in names:
            if a == n and i + 1 < len(user_args):
                return user_args[i + 1]
            if a.startswith(n + "="):
                return a.split("=", 1)[1]
    return None


def _replace_user_arg(user_args, names, value):
    out = list(user_args)
    for i, a in enumerate(out):
        for n in names:
            if a == n and i + 1 < len(out):
                out[i + 1] = value
                return out
            if a.startswith(n + "="):
                out[i] = f"{n}={value}"
                return out
    return out


def _nproc_for(args, resource_pool):
    if args.num_gpus > 0:
        return args.num_gpus
    if resource_pool:
        return next(iter(resource_pool.values()))
    return 1


def _launch_single_node(args, resource_pool):
    nproc = _nproc_for(args, resource_pool)
    host = next(iter(resource_pool)) if resource_pool else "localhost"
    world = collections.OrderedDict([(host, list(range(nproc)))])
    cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
           f"--world_info={encode_world_info(world)}",
           f"--node_rank=0",
           f"--master_addr={args.master_addr or 'localhost'}",
           f"--master_port={args.master_port}"]
    if args.user_script is None:
        raise ValueError("no user script given")
    cmd += [args.user_script] + args.user_args
    if args.dry_run:
        print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    logger.info(f"cmd = {' '.join(cmd)}")
    proc = subprocess.Popen(cmd, env=os.environ.copy())

    def sig_handler(sig, frame):  # pragma: no cover
        proc.send_signal(sig)
    signal.signal(signal.SIGINT, sig_handler)
    signal.signal(signal.SIGTERM, sig_handler)
    proc.wait()
    return proc.returncode


def _launch_multi_node(args, resource_pool):
    from deepspeed_tpu.launcher.multinode_runner import build_runner
    active = parse_inclusion_exclusion(resource_pool, args.include,
                                       args.exclude)
    if args.num_nodes > 0:
        active = collections.OrderedDict(
            list(active.items())[:args.num_nodes])
    if args.num_gpus > 0:
        active = collections.OrderedDict(
            (h, list(range(args.num_gpus))) for h in active)
    if not active:
        raise ValueError("no resources left after include/exclude filters")

    if not args.master_addr:
        args.master_addr = next(iter(active))
    world_info = encode_world_info(active)
    runner = build_runner(args.launcher, args, world_info)
    env = os.environ.copy()
    cmd = runner.get_cmd(env, active)
    if args.dry_run:
        print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    if not runner.backend_exists():  # pragma: no cover - host dependent
        raise RuntimeError(f"launcher backend '{args.launcher}' not found "
                           "on PATH")
    logger.info(f"cmd = {' '.join(cmd)}")  # pragma: no cover
    result = subprocess.Popen(cmd, env=env)  # pragma: no cover
    result.wait()  # pragma: no cover
    return result.returncode  # pragma: no cover


if __name__ == "__main__":
    sys.exit(main())
