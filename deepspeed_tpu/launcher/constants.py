"""Launcher constants.  Parity: reference ``deepspeed/launcher/constants.py``."""

PDSH_LAUNCHER = "pdsh"
PDSH_MAX_FAN_OUT = 1024

OPENMPI_LAUNCHER = "openmpi"
MPICH_LAUNCHER = "mpich"
SLURM_LAUNCHER = "slurm"
MVAPICH_LAUNCHER = "mvapich"
MVAPICH_TMP_HOSTFILE = "/tmp/deepspeed_mvapich_hostfile"
GCLOUD_TPU_LAUNCHER = "gcloud-tpu"

DEFAULT_MASTER_PORT = 29500

DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
