"""Pluggable multi-node launch backends.

Parity: reference ``launcher/multinode_runner.py:15`` (``MultiNodeRunner``
ABC; PDSH:47, OpenMPI:118, MPICH:173, Slurm:222, MVAPICH:269).  TPU
addition: ``GcloudTPURunner`` drives ``gcloud compute tpus tpu-vm ssh
--worker=all`` — the idiomatic way to fan a command across a TPU pod's
hosts.
"""

import os
import shutil
import shlex
import sys
from abc import ABC, abstractmethod

from deepspeed_tpu.launcher.constants import (GCLOUD_TPU_LAUNCHER,
                                              MPICH_LAUNCHER,
                                              MVAPICH_LAUNCHER,
                                              OPENMPI_LAUNCHER, PDSH_LAUNCHER,
                                              PDSH_MAX_FAN_OUT,
                                              SLURM_LAUNCHER)


class MultiNodeRunner(ABC):

    def __init__(self, args, world_info_base64):
        self.args = args
        self.user_script = args.user_script
        self.user_arguments = list(args.user_args)
        self.world_info_base64 = world_info_base64
        self.exports = {}

    @abstractmethod
    def backend_exists(self) -> bool:
        """Whether this backend's binary is available."""

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        """The command to execute from the controller host."""

    def add_export(self, key, var):
        self.exports[key.strip()] = str(var).strip()

    @property
    def name(self):
        return type(self).__name__

    def _export_flags(self, fmt):
        out = []
        for k, v in self.exports.items():
            out += fmt(k, v)
        return out


class PDSHRunner(MultiNodeRunner):
    """Parallel-ssh fan-out; each node runs ``launch.py`` with its
    node_rank derived from ``%n`` (pdsh's per-host rank substitution is not
    portable, so we pass the hostlist and let launch.py find itself)."""

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        exports = " ".join(f"export {k}={shlex.quote(v)};"
                           for k, v in self.exports.items())
        # %n → pdsh's 0-based host index = node_rank
        inner = (f"{exports} cd {os.path.abspath('.')}; "
                 f"{sys.executable} -u -m deepspeed_tpu.launcher.launch "
                 f"--world_info={self.world_info_base64} "
                 f"--node_rank=%n "
                 f"--master_addr={self.args.master_addr} "
                 f"--master_port={self.args.master_port} "
                 f"{self.user_script} "
                 + " ".join(map(shlex.quote, self.user_arguments)))
        return ["pdsh", "-S", "-f", str(PDSH_MAX_FAN_OUT), "-w", hosts,
                inner]


class OpenMPIRunner(MultiNodeRunner):

    def backend_exists(self):
        return shutil.which("ompi_info") is not None

    def get_cmd(self, environment, active_resources):
        total = sum(len(s) for s in active_resources.values())
        cmd = ["mpirun", "-n", str(total), "-hostfile", self.args.hostfile,
               "--mca", "btl", "^openib", "--mca", "btl_tcp_if_include",
               "eth0"]
        cmd += self._export_flags(lambda k, v: ["-x", f"{k}={v}"])
        cmd += shlex.split(self.args.launcher_args)
        return cmd + [sys.executable, "-u", self.user_script] + \
            self.user_arguments


class MPICHRunner(MultiNodeRunner):

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total = sum(len(s) for s in active_resources.values())
        per_host = len(next(iter(active_resources.values())))
        cmd = ["mpirun", "-n", str(total), "-ppn", str(per_host)]
        cmd += self._export_flags(lambda k, v: ["-genv", k, v])
        cmd += shlex.split(self.args.launcher_args)
        return cmd + [sys.executable, "-u", self.user_script] + \
            self.user_arguments


class SlurmRunner(MultiNodeRunner):

    def backend_exists(self):
        return shutil.which("sinfo") is not None

    def get_cmd(self, environment, active_resources):
        total = sum(len(s) for s in active_resources.values())
        cmd = ["srun", "-n", str(total)]
        if getattr(self.args, "include", ""):
            cmd += ["--include", self.args.include]
        if getattr(self.args, "num_nodes", -1) > 0:
            cmd += ["--nodes", str(self.args.num_nodes)]
        cmd += shlex.split(self.args.launcher_args)
        exports = ",".join(f"{k}={v}" for k, v in self.exports.items())
        if exports:
            cmd += [f"--export=ALL,{exports}"]
        return cmd + [sys.executable, "-u", self.user_script] + \
            self.user_arguments


class MVAPICHRunner(MPICHRunner):
    """MVAPICH shares mpirun's CLI; differences are env-var tuning only."""

    def backend_exists(self):
        mpiname = shutil.which("mpiname")
        return mpiname is not None

    def get_cmd(self, environment, active_resources):
        self.add_export("MV2_SMP_USE_CMA", "0")
        return super().get_cmd(environment, active_resources)


class GcloudTPURunner(MultiNodeRunner):
    """Fan the launcher across a TPU pod's hosts with gcloud.  Requires
    ``--launcher_args "--zone=... --project=... tpu-name"`` (last token is
    the TPU name).  Each worker resolves its own node_rank from the TPU
    metadata (JAX does this automatically on TPU VMs, so only the script
    and env need distributing)."""

    def backend_exists(self):
        return shutil.which("gcloud") is not None

    def get_cmd(self, environment, active_resources):
        extra = shlex.split(self.args.launcher_args)
        assert extra, ("gcloud-tpu launcher needs --launcher_args "
                       "'[flags] TPU_NAME'")
        tpu_name = extra[-1]
        flags = extra[:-1]
        exports = " ".join(f"export {k}={shlex.quote(v)};"
                           for k, v in self.exports.items())
        inner = (f"{exports} cd {os.path.abspath('.')}; "
                 f"{sys.executable} -u {self.user_script} "
                 + " ".join(map(shlex.quote, self.user_arguments)))
        return (["gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
                 "--worker=all"] + flags + [f"--command={inner}"])


_RUNNERS = {
    PDSH_LAUNCHER: PDSHRunner,
    OPENMPI_LAUNCHER: OpenMPIRunner,
    MPICH_LAUNCHER: MPICHRunner,
    SLURM_LAUNCHER: SlurmRunner,
    MVAPICH_LAUNCHER: MVAPICHRunner,
    GCLOUD_TPU_LAUNCHER: GcloudTPURunner,
}


def build_runner(name, args, world_info_base64) -> MultiNodeRunner:
    if name not in _RUNNERS:
        raise ValueError(f"unknown launcher '{name}' "
                         f"(choices: {sorted(_RUNNERS)})")
    return _RUNNERS[name](args, world_info_base64)
