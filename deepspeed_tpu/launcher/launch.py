"""Per-node process spawner.

Parity: reference ``launcher/launch.py:129`` — decodes the world-info
blob, forks the local training processes with the distributed env set, and
propagates signals / reaps children (``sigkill_handler:316``).

Env contract per process (read by ``comm.init_distributed`` /
``jax.distributed.initialize``):

* ``RANK`` / ``LOCAL_RANK`` / ``WORLD_SIZE`` — process-level (parity)
* ``MASTER_ADDR`` / ``MASTER_PORT``
* ``JAX_COORDINATOR_ADDRESS`` = master:port, ``JAX_NUM_PROCESSES``,
  ``JAX_PROCESS_ID`` — the JAX rendezvous trio
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", type=str, required=True)
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", type=str, default="localhost")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--dry_run", action="store_true")
    parser.add_argument("--enable_elastic_training", action="store_true",
                        help="supervise this node's workers with the "
                             "elastic agent: a dead (or, with "
                             "--heartbeat_timeout, silently hung) worker "
                             "restarts the node's generation at the "
                             "surviving world size (reference: "
                             "torch-elastic LocalElasticAgent)")
    parser.add_argument("--ds_config", type=str, default=None,
                        help="DeepSpeed config json with the 'elasticity' "
                             "section (required with elastic training)")
    parser.add_argument("--heartbeat_timeout", type=float, default=0,
                        help="seconds without a worker heartbeat "
                             "($DS_ELASTIC_HEARTBEAT_FILE touch) before a "
                             "silent worker counts as dead; 0 = exit-code "
                             "liveness only")
    parser.add_argument("--max_elastic_restarts", type=int, default=100)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER, default=[])
    return parser.parse_args(args=args)


def build_process_envs(world_info, node_rank, master_addr, master_port):
    """Per-local-process env dicts for this node."""
    hosts = list(world_info.keys())
    assert 0 <= node_rank < len(hosts), \
        f"node_rank {node_rank} out of range for {len(hosts)} hosts"
    global_rank_offset = sum(len(world_info[h]) for h in hosts[:node_rank])
    world_size = sum(len(s) for s in world_info.values())
    this_slots = world_info[hosts[node_rank]]

    envs = []
    for local_rank, _slot in enumerate(this_slots):
        rank = global_rank_offset + local_rank
        env = {
            "RANK": str(rank),
            "LOCAL_RANK": str(local_rank),
            "WORLD_SIZE": str(world_size),
            "MASTER_ADDR": master_addr,
            "MASTER_PORT": str(master_port),
            "JAX_COORDINATOR_ADDRESS": f"{master_addr}:{master_port}",
            "JAX_NUM_PROCESSES": str(world_size),
            "JAX_PROCESS_ID": str(rank),
        }
        envs.append(env)
    return envs


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    process_envs = build_process_envs(world_info, args.node_rank,
                                      args.master_addr, args.master_port)
    if args.dry_run:
        for env in process_envs:
            print(json.dumps(env))
        return 0

    cmd = [sys.executable, "-u", args.user_script] + args.user_args

    if args.enable_elastic_training:
        # SINGLE-NODE elastic supervision (the role of torch-elastic's
        # LocalElasticAgent the reference extends): the agent owns the
        # spawn/monitor/restart loop; the env trio + the recomputed
        # elastic batch config ($DS_ELASTIC_CONFIG) are regenerated per
        # generation for the surviving world size.  Multi-node elastic
        # needs a cross-node rendezvous this launcher does not provide —
        # use the cooperative ScaleEvent path (DSElasticAgent.run) there.
        assert len(world_info) == 1, \
            "--enable_elastic_training supervises ONE node's workers; " \
            f"got {len(world_info)} hosts in --world_info"
        assert args.ds_config, "--enable_elastic_training needs --ds_config"
        with open(args.ds_config) as f:
            ds_config = json.load(f)
        from deepspeed_tpu.elasticity import DSElasticAgent

        work_dir = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                                f"ds_elastic_{os.getpid()}")
        os.makedirs(work_dir, exist_ok=True)
        gen_cfg = os.path.join(work_dir, "ds_elastic_config.json")

        def cmd_for(rank, ws, cfg):
            # the batch config recomputed for THIS generation's world
            # size; workers read it from $DS_ELASTIC_CONFIG (or recompute
            # via compute_elastic_config from $WORLD_SIZE)
            if rank == 0:
                with open(gen_cfg, "w") as f:
                    json.dump(cfg, f)
            return cmd

        def env_for(rank, ws):
            # ONE source of truth for the distributed env contract: the
            # same builder the static path uses, on a synthetic ws-slot
            # single-node world
            env = build_process_envs({"localhost": list(range(ws))}, 0,
                                     args.master_addr,
                                     args.master_port)[rank]
            env["DS_ELASTIC_CONFIG"] = gen_cfg
            return env

        # parity with the non-elastic path's sigkill_handler: a terminated
        # launcher must not orphan its workers — SystemExit unwinds
        # through run_procs' finally, which terminates the generation
        def _on_signal(sig, frame):
            sys.exit(128 + sig)
        signal.signal(signal.SIGINT, _on_signal)
        signal.signal(signal.SIGTERM, _on_signal)

        agent = DSElasticAgent(ds_config,
                               start_world_size=len(process_envs),
                               max_restarts=args.max_elastic_restarts)
        return agent.run_procs(
            cmd_for,
            heartbeat_dir=os.path.join(work_dir, "hb"),
            heartbeat_timeout_s=args.heartbeat_timeout,
            env_for=env_for)

    procs = []
    for env_overrides in process_envs:
        env = os.environ.copy()
        env.update(env_overrides)
        logger.info(f"launching rank {env_overrides['RANK']}: {' '.join(cmd)}")
        procs.append(subprocess.Popen(cmd, env=env))

    def sigkill_handler(sig, frame):  # parity: launch.py:316
        for p in procs:
            logger.info(f"killing subprocess {p.pid}")
            try:
                p.terminate()
            except Exception:
                pass
        sys.exit(128 + sig)

    signal.signal(signal.SIGINT, sigkill_handler)
    signal.signal(signal.SIGTERM, sigkill_handler)

    alive = list(procs)
    rc = 0
    while alive:
        time.sleep(0.2)
        for p in list(alive):
            ret = p.poll()
            if ret is None:
                continue
            alive.remove(p)
            if ret != 0:
                rc = ret
                logger.error(f"process {p.pid} exited with {ret}; "
                             "terminating remaining processes")
                for q in alive:
                    q.terminate()
                alive = []
                break
    return rc


if __name__ == "__main__":
    sys.exit(main())
