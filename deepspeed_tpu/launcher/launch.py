"""Per-node process spawner.

Parity: reference ``launcher/launch.py:129`` — decodes the world-info
blob, forks the local training processes with the distributed env set, and
propagates signals / reaps children (``sigkill_handler:316``).

Env contract per process (read by ``comm.init_distributed`` /
``jax.distributed.initialize``):

* ``RANK`` / ``LOCAL_RANK`` / ``WORLD_SIZE`` — process-level (parity)
* ``MASTER_ADDR`` / ``MASTER_PORT``
* ``JAX_COORDINATOR_ADDRESS`` = master:port, ``JAX_NUM_PROCESSES``,
  ``JAX_PROCESS_ID`` — the JAX rendezvous trio
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", type=str, required=True)
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", type=str, default="localhost")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--dry_run", action="store_true")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER, default=[])
    return parser.parse_args(args=args)


def build_process_envs(world_info, node_rank, master_addr, master_port):
    """Per-local-process env dicts for this node."""
    hosts = list(world_info.keys())
    assert 0 <= node_rank < len(hosts), \
        f"node_rank {node_rank} out of range for {len(hosts)} hosts"
    global_rank_offset = sum(len(world_info[h]) for h in hosts[:node_rank])
    world_size = sum(len(s) for s in world_info.values())
    this_slots = world_info[hosts[node_rank]]

    envs = []
    for local_rank, _slot in enumerate(this_slots):
        rank = global_rank_offset + local_rank
        env = {
            "RANK": str(rank),
            "LOCAL_RANK": str(local_rank),
            "WORLD_SIZE": str(world_size),
            "MASTER_ADDR": master_addr,
            "MASTER_PORT": str(master_port),
            "JAX_COORDINATOR_ADDRESS": f"{master_addr}:{master_port}",
            "JAX_NUM_PROCESSES": str(world_size),
            "JAX_PROCESS_ID": str(rank),
        }
        envs.append(env)
    return envs


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    process_envs = build_process_envs(world_info, args.node_rank,
                                      args.master_addr, args.master_port)
    if args.dry_run:
        for env in process_envs:
            print(json.dumps(env))
        return 0

    procs = []
    for env_overrides in process_envs:
        env = os.environ.copy()
        env.update(env_overrides)
        cmd = [sys.executable, "-u", args.user_script] + args.user_args
        logger.info(f"launching rank {env_overrides['RANK']}: {' '.join(cmd)}")
        procs.append(subprocess.Popen(cmd, env=env))

    def sigkill_handler(sig, frame):  # parity: launch.py:316
        for p in procs:
            logger.info(f"killing subprocess {p.pid}")
            try:
                p.terminate()
            except Exception:
                pass
        sys.exit(128 + sig)

    signal.signal(signal.SIGINT, sigkill_handler)
    signal.signal(signal.SIGTERM, sigkill_handler)

    alive = list(procs)
    rc = 0
    while alive:
        time.sleep(0.2)
        for p in list(alive):
            ret = p.poll()
            if ret is None:
                continue
            alive.remove(p)
            if ret != 0:
                rc = ret
                logger.error(f"process {p.pid} exited with {ret}; "
                             "terminating remaining processes")
                for q in alive:
                    q.terminate()
                alive = []
                break
    return rc


if __name__ == "__main__":
    sys.exit(main())
