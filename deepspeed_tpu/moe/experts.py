"""Local expert bank.

Parity: reference ``deepspeed/moe/experts.py`` — ``Experts`` holds
``num_local_experts`` copies of an expert module and runs each on its chunk
of the dispatched tokens, tagging every expert parameter with
``allreduce=False`` / ``group_name`` so the engine reduces them over the
expert-data-parallel group instead of the full DP group.

TPU redesign: instead of a ModuleList loop (a trace-unrolled Python loop),
the bank stores experts as ONE stacked pytree (leading ``[E_local, ...]``
axis) and evaluates all of them with ``jax.vmap`` — one XLA program, batched
matmuls on the MXU.  The reference's param tagging becomes a pytree-path
property: everything under the ``"experts"`` key is an expert param (see
``moe.utils.is_moe_param``), which is also how the engine's sharding plan
assigns the ``ep`` axis.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


class Experts:
    """Stacked expert bank (reference ``Experts``, ``experts.py:9``)."""

    def __init__(self, expert_init: Callable[[jax.Array], Any],
                 expert_apply: Callable[[Any, jax.Array], jax.Array],
                 num_local_experts: int = 1,
                 expert_group_name: Optional[str] = None):
        """``expert_init(rng) -> params`` builds ONE expert's params;
        ``expert_apply(params, x) -> y`` runs one expert.  The bank stacks
        ``num_local_experts`` independent inits."""
        self.expert_init = expert_init
        self.expert_apply = expert_apply
        self.num_local_experts = int(num_local_experts)
        self.expert_group_name = expert_group_name

    def init(self, rng) -> Any:
        keys = jax.random.split(rng, self.num_local_experts)
        per_expert = [self.expert_init(k) for k in keys]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_expert)
        return {"experts": stacked}

    def __call__(self, params, inputs: jax.Array) -> jax.Array:
        """``inputs``: [..., E_local, capacity, d] with the expert axis at
        -3 (the reference chunks dim=1; our dispatch already groups tokens
        per expert).  Returns the same shape."""
        bank = params["experts"]
        e_axis = inputs.ndim - 3
        chunks = jnp.moveaxis(inputs, e_axis, 0)
        out = jax.vmap(self.expert_apply)(bank, chunks)
        return jnp.moveaxis(out, 0, e_axis)
