"""TP token scatter/gather for MoE blocks.

Parity: reference ``deepspeed/moe/mappings.py`` (adapted there from
Megatron's mpu/mappings.py) — ``gather_tokens`` all-gathers
sequence-partitioned activations over the tensor-parallel group before an
MoE block (whose all-to-all runs over the *expert*-parallel group and must
see full tokens), and ``drop_tokens`` re-partitions them afterwards.  Both
are autograd duals: gather's backward is drop, drop's backward is gather
(the reference's ``_GatherTokens``/``_DropTokens`` autograd functions).

TPU design: ``custom_vjp`` functions built on the comm facade's named-axis
collectives, usable inside ``shard_map`` over the ``tp`` mesh axis.  When no
``tp`` axis is bound (pure-SPMD callers or tp=1) they are the identity, the
analogue of the reference's ``mpu is None`` bail-out (``mappings.py:94``).
"""

from functools import partial

import jax

from deepspeed_tpu.comm import comm
from deepspeed_tpu.ops._shard_map import axis_size


def _tp_bound() -> bool:
    try:
        axis_size("tp")
        return True
    except NameError:
        return False


def _gather(x, dim):
    return comm.all_gather(x, group="tp", axis=dim, tiled=True)


def _drop(x, dim):
    rank = jax.lax.axis_index("tp")
    size = axis_size("tp")
    assert x.shape[dim] % size == 0, (
        f"drop_tokens: dimension {dim} ({x.shape[dim]}) is not divisible "
        f"by tensor parallel world size ({size})")
    chunk = x.shape[dim] // size
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=dim)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _gather_tokens(input_, dim):
    return _gather(input_, dim)


def _gather_fwd(input_, dim):
    return _gather(input_, dim), None


def _gather_bwd(dim, _res, g):
    return (_drop(g, dim),)


_gather_tokens.defvjp(_gather_fwd, _gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _drop_tokens(input_, dim):
    return _drop(input_, dim)


def _drop_fwd(input_, dim):
    return _drop(input_, dim), None


def _drop_bwd(dim, _res, g):
    return (_gather(g, dim),)


_drop_tokens.defvjp(_drop_fwd, _drop_bwd)


def gather_tokens(input_, dim: int = 0):
    """All-gather ``input_`` along ``dim`` over the tp axis (reference
    ``gather_tokens``, ``mappings.py:92``); backward drops to this rank's
    chunk.  Identity when no ``tp`` axis is in scope."""
    if not _tp_bound():
        return input_
    return _gather_tokens(input_, dim)


def drop_tokens(input_, dim: int = 0):
    """Keep this tp rank's chunk of ``input_`` along ``dim`` (reference
    ``drop_tokens``, ``mappings.py:98``); backward all-gathers the grads.
    Identity when no ``tp`` axis is in scope."""
    if not _tp_bound():
        return input_
    return _drop_tokens(input_, dim)
