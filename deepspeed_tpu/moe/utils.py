"""MoE parameter-group utilities.

Parity: reference ``deepspeed/moe/utils.py`` — detecting MoE models,
telling expert params from shared params, and splitting optimizer param
groups so expert params get their own groups (reduced over the
expert-data-parallel group, not the full DP group).

TPU design: params are pytree leaves, so "is this an expert param" is a
*path* property (the reference tags tensors with ``allreduce=False`` /
``group_name`` attributes at Experts construction; our ``Experts`` bank and
the transformer's MoE layers both place expert weights under an
``"experts"`` key, and the engine's sharding plan assigns the ``ep`` axis by
the same rule).  Group splitting returns label pytrees + group dicts in the
shape ``optax.multi_transform`` consumes, which is the optax-native form of
the reference's per-group optimizer construction.
"""

import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

# expert subtrees in this repo: moe.layer.MoE uses "experts", the
# transformer's fused MoE blocks use "moe" (models/transformer.py:340)
_EXPERT_PATH_RE = re.compile(r"\['(experts|moe)'\]|(^|\.)(experts|moe)(\.|$)")
# the router gate is a SHARED param (reduced over full DP, replicated by the
# sharding plan — transformer tp_rules: "moe.*wg" -> P()) even though it
# lives under the moe subtree
_GATE_LEAF_RE = re.compile(r"\['(wg|gate|router)(_b)?'\]|(^|\.)(wg|gate|router)(_b)?($|\.)")


def has_moe_layers(model_or_params) -> Tuple[bool, int]:
    """(has_moe, num_experts) — reference ``has_moe_layers`` walks modules;
    we accept a model (``moe_num_experts`` config attr or an ``moe`` layer
    attr) or a params pytree (any path containing the expert key)."""
    cfg = getattr(model_or_params, "config", None)
    n = getattr(cfg, "moe_num_experts", None) if cfg is not None else None
    if n:
        return True, int(n)
    num = getattr(model_or_params, "num_experts", None)
    if num:
        return True, int(num)
    try:
        paths = [jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_leaves_with_path(model_or_params)]
    except Exception:
        return False, 0
    moe_paths = [p for p in paths if is_moe_param(p)]
    if not moe_paths:
        return False, 0
    # expert count = LEADING axis of a stacked expert WEIGHT (ndim>=3):
    # the model zoo's per-layer moe leaves are [E, in, out] and an Experts
    # bank stacks [E_local, ...] (moe/experts.py:10) — both put the expert
    # axis first.  Models that also carry a layers axis expose
    # moe_num_experts via config, which the attribute path above prefers,
    # so no [L, E, ...] leaf reaches this fallback.
    for (p, leaf) in jax.tree_util.tree_leaves_with_path(model_or_params):
        if is_moe_param(jax.tree_util.keystr(p)) and np.ndim(leaf) >= 3:
            return True, int(np.shape(leaf)[0])
    for (p, leaf) in jax.tree_util.tree_leaves_with_path(model_or_params):
        if is_moe_param(jax.tree_util.keystr(p)) and np.ndim(leaf) >= 1:
            return True, int(np.shape(leaf)[0])
    return True, 0


def is_moe_param(path_or_key) -> bool:
    """Path predicate (reference checks the ``allreduce=False`` tensor tag,
    ``utils.py:20``)."""
    key = path_or_key if isinstance(path_or_key, str) \
        else jax.tree_util.keystr(path_or_key)
    return (_EXPERT_PATH_RE.search(key) is not None
            and _GATE_LEAF_RE.search(key) is None)


def split_params_into_shared_and_expert_params(params):
    """Two same-structure trees with ``None`` at the other kind's leaves
    (reference returns two lists; trees keep the path info JAX needs)."""
    def shared(path, leaf):
        return None if is_moe_param(path) else leaf

    def expert(path, leaf):
        return leaf if is_moe_param(path) else None

    return (jax.tree_util.tree_map_with_path(shared, params),
            jax.tree_util.tree_map_with_path(expert, params))


def split_params_grads_into_shared_and_expert_params(grads):
    """Same split over a grads tree (reference ``utils.py:37`` — used for
    separate grad-norm/overflow computation)."""
    return split_params_into_shared_and_expert_params(grads)


def moe_param_labels(params, shared_label: str = "shared",
                     expert_label: str = "moe") -> Any:
    """Label pytree for ``optax.multi_transform`` — the optax-native form
    of the reference's split param groups."""
    return jax.tree_util.tree_map_with_path(
        lambda p, _: expert_label if is_moe_param(p) else shared_label,
        params)


def split_params_into_different_moe_groups_for_optimizer(
        param_groups, max_group_size: Optional[int] = 178956971
        ) -> Tuple[Dict, ...]:
    """Reference ``utils.py:64``: for each input group, pull expert params
    into new groups (tagged ``moe=True`` and named by their expert group),
    optionally chunked so no group exceeds ``max_group_size`` elements.

    Groups are dicts ``{"name": str, "params": {path: leaf}, ...}`` —
    params keyed by pytree path string rather than tensor identity."""
    if isinstance(param_groups, tuple):
        param_groups = list(param_groups)
    elif isinstance(param_groups, dict):
        param_groups = [param_groups]
    elif not isinstance(param_groups, list):
        raise ValueError(f"Unknown param group type of {type(param_groups)}")

    out_groups: List[Dict] = []
    moe_groups: List[Dict] = []
    for group in param_groups:
        flat = group["params"]
        if not isinstance(flat, dict):
            flat = {jax.tree_util.keystr(p): leaf for p, leaf in
                    jax.tree_util.tree_leaves_with_path(flat)}
        shared = {k: v for k, v in flat.items() if not is_moe_param(k)}
        expert = {k: v for k, v in flat.items() if is_moe_param(k)}
        out_groups.append({**group, "params": shared})
        if not expert:
            continue
        base = {k: v for k, v in group.items() if k not in ("params", "name")}
        name = f"{group.get('name', 'group')}_moe"
        if max_group_size is None:
            moe_groups.append({**base, "name": name, "moe": True,
                               "params": expert})
            continue
        cur: Dict[str, Any] = {}
        cur_size = 0
        chunks: List[Dict[str, Any]] = []
        for k, v in expert.items():
            n = int(np.size(v))
            if cur and cur_size + n > max_group_size:
                chunks.append(cur)
                cur, cur_size = {}, 0
            cur[k] = v
            cur_size += n
        if cur:
            chunks.append(cur)
        for i, chunk in enumerate(chunks):
            moe_groups.append({**base, "name": f"{name}_{i}", "moe": True,
                               "params": chunk})
    return tuple(out_groups + moe_groups)
