"""Sharded MoE: gating + expert dispatch.

Parity: reference ``deepspeed/moe/sharded_moe.py`` (``top1gating:177``,
``top2gating:278`` — gumbel noise, capacity, load-balancing aux loss;
``_AllToAll:89``; ``MOELayer:439``: gate → dispatch all-to-all → experts →
combine all-to-all).

TPU design: dispatch/combine are einsums with a dispatch mask; sharding
constraints place tokens over the batch axes and experts over the ``ep``
axis, and the XLA partitioner materialises the two all-to-alls the reference
issues explicitly.  Capacity is static (computed from shapes at trace time)
so the program never retraces.  Everything is fp32 at the gate (reference
casts gate logits to fp32 too).
"""

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DP_AXIS, EP_AXIS, FSDP_AXIS,
                                             TP_AXIS)
from deepspeed_tpu.runtime.zero.stage_plan import maybe_constrain

TOKENS_SPEC = P((DP_AXIS, FSDP_AXIS, EP_AXIS), None)        # [tokens, d]
DISPATCH_SPEC = P(EP_AXIS, None, None)                      # [e, c, d]


class GateOutput(NamedTuple):
    l_aux: jnp.ndarray            # load-balancing loss (scalar)
    combine_weights: jnp.ndarray  # [tokens, E, C] fp32 (None in compact mode)
    dispatch_mask: jnp.ndarray    # [tokens, E, C] bool (None in compact mode)
    exp_counts: jnp.ndarray       # [E] tokens routed per expert (pre-capacity)
    # compact routing (scatter dispatch): flat slot e*C + c per assignment,
    # E*C for dropped; gate weight per assignment
    slots: jnp.ndarray = None       # [tokens, k] int32
    gate_vals: jnp.ndarray = None   # [tokens, k] fp32
    capacity: int = 0


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def top1gating(logits, capacity_factor=1.0, min_capacity=4,
               noisy_gate_policy: Optional[str] = None, rng=None,
               drop_tokens=True, used_token_mask=None,
               build_dense=True) -> GateOutput:
    """Top-1 gating (Switch). logits: [tokens, E] fp32.

    Mirrors reference ``top1gating``: optional jitter/RSample noise, position
    within expert via masked cumsum, tokens beyond capacity dropped, aux loss
    = E * mean(me·ce).  ``build_dense=False`` skips materializing the
    [tokens, E, C] combine/dispatch tensors and returns only the compact
    (slots, gate_vals) routing the scatter dispatch consumes.
    """
    tokens, E = logits.shape
    C = _capacity(tokens, E, capacity_factor, min_capacity)
    if not drop_tokens:
        C = tokens  # worst case: everything to one expert

    logits = logits.astype(jnp.float32)
    if noisy_gate_policy == "RSample" and rng is not None:
        noisy = logits + jax.random.gumbel(rng, logits.shape)
    elif noisy_gate_policy == "Jitter" and rng is not None:
        noisy = logits * jax.random.uniform(rng, logits.shape, minval=0.98,
                                            maxval=1.02)
    else:
        noisy = logits

    gates = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(noisy, axis=-1)                        # [tokens]
    mask1 = _one_hot(idx, E)                                # [tokens, E]
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[:, None]

    exp_counts = jnp.sum(mask1, axis=0)
    # aux loss (reference l_aux = E * sum(me*ce))
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # position of each token within its expert queue
    pos_in_expert = jnp.cumsum(mask1, axis=0) - mask1       # [tokens, E]
    pos = jnp.sum(pos_in_expert * mask1, axis=-1)           # [tokens]
    keep = (pos < C)[:, None] * mask1                        # drop overflow

    gate_val = jnp.sum(gates * keep, axis=-1)               # [tokens]
    kept = jnp.sum(keep, axis=-1) > 0                       # [tokens]
    slots = jnp.where(kept, idx.astype(jnp.int32) * C
                      + pos.astype(jnp.int32), E * C)[:, None]
    gate_vals = (gate_val * kept)[:, None]
    if not build_dense:
        return GateOutput(l_aux=l_aux, combine_weights=None,
                          dispatch_mask=None, exp_counts=exp_counts,
                          slots=slots, gate_vals=gate_vals, capacity=C)
    loc = _one_hot(pos.astype(jnp.int32), C)                # [tokens, C]
    combine = gate_val[:, None, None] * keep[:, :, None] * loc[:, None, :]
    dispatch = combine > 0
    return GateOutput(l_aux=l_aux, combine_weights=combine,
                      dispatch_mask=dispatch, exp_counts=exp_counts,
                      slots=slots, gate_vals=gate_vals, capacity=C)


def top2gating(logits, capacity_factor=1.0, min_capacity=4, rng=None,
               second_policy="Rsample", build_dense=True) -> GateOutput:
    """Top-2 gating (GShard).  Capacity doubles (2 slots per token)."""
    tokens, E = logits.shape
    C = _capacity(tokens, E, capacity_factor * 2.0, min_capacity)

    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)

    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, E)
    logits_no1 = jnp.where(mask1 > 0, -jnp.inf, logits)
    if rng is not None and second_policy.lower() == "rsample":
        logits_no1 = logits_no1 + jax.random.gumbel(rng, logits.shape)
    idx2 = jnp.argmax(logits_no1, axis=-1)
    mask2 = _one_hot(idx2, E)

    exp_counts = jnp.sum(mask1 + mask2, axis=0)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    pos1 = jnp.cumsum(mask1, axis=0) - mask1
    pos2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0)[None]
    p1 = jnp.sum(pos1 * mask1, axis=-1)
    p2 = jnp.sum(pos2 * mask2, axis=-1)
    keep1 = (p1 < C)[:, None] * mask1
    keep2 = (p2 < C)[:, None] * mask2

    g1 = jnp.sum(gates * keep1, axis=-1)
    g2 = jnp.sum(gates * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    kept1 = jnp.sum(keep1, axis=-1) > 0
    kept2 = jnp.sum(keep2, axis=-1) > 0
    s1 = jnp.where(kept1, idx1.astype(jnp.int32) * C
                   + p1.astype(jnp.int32), E * C)
    s2 = jnp.where(kept2, idx2.astype(jnp.int32) * C
                   + p2.astype(jnp.int32), E * C)
    slots = jnp.stack([s1, s2], axis=1)
    gate_vals = jnp.stack([g1 * kept1, g2 * kept2], axis=1)
    if not build_dense:
        return GateOutput(l_aux=l_aux, combine_weights=None,
                          dispatch_mask=None, exp_counts=exp_counts,
                          slots=slots, gate_vals=gate_vals, capacity=C)
    loc1 = _one_hot(p1.astype(jnp.int32), C)
    loc2 = _one_hot(p2.astype(jnp.int32), C)
    combine = (g1[:, None, None] * keep1[:, :, None] * loc1[:, None, :] +
               g2[:, None, None] * keep2[:, :, None] * loc2[:, None, :])
    dispatch = combine > 0
    return GateOutput(l_aux=l_aux, combine_weights=combine,
                      dispatch_mask=dispatch, exp_counts=exp_counts,
                      slots=slots, gate_vals=gate_vals, capacity=C)


def topkgating(logits, k: int, capacity_factor=1.0, min_capacity=4,
               norm_topk=True, build_dense=True, drop_tokens=True,
               noisy_gate_policy=None, rng=None) -> GateOutput:
    """General top-k gating (k statically unrolled; the reference stops
    at k=2, but the modern MoE zoo — Qwen2-MoE/DBRX/OLMoE — routes top-4
    to top-8).  Same machinery as :func:`top2gating`: per-rank masked
    argmax, slot priority = (choice rank, token order), capacity
    ``tokens/E * cf * k``; aux loss keeps the reference-0.8.3 rank-1/E
    convention for k<=2 and switches to upstream general-topk's full-mask
    ``E*E/k`` scaling for k>2 (see the in-body comment), and
    ``norm_topk`` renormalizes over SURVIVING assignments (post-drop,
    like top2gating / the reference; Mixtral / Qwen2-MoE
    ``norm_topk_prob``).  False keeps raw softmax mass.
    ``drop_tokens=False`` sets C=tokens (an expert can never queue more
    than one assignment per token).  ``noisy_gate_policy`` perturbs the
    SELECTION logits only (RSample gumbel / Jitter), like top1gating."""
    tokens, E = logits.shape
    C = _capacity(tokens, E, capacity_factor * float(k), min_capacity)
    if not drop_tokens:
        C = tokens

    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)

    if noisy_gate_policy == "RSample" and rng is not None:
        select = logits + jax.random.gumbel(rng, logits.shape)
    elif noisy_gate_policy == "Jitter" and rng is not None:
        select = logits * jax.random.uniform(rng, logits.shape,
                                             minval=0.98, maxval=1.02)
    else:
        select = logits

    masks, idxs = [], []
    masked = select
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)
        m = _one_hot(idx, E)
        idxs.append(idx)
        masks.append(m)
        masked = jnp.where(m > 0, -jnp.inf, masked)

    exp_counts = sum(jnp.sum(m, axis=0) for m in masks)
    me = jnp.mean(gates, axis=0)
    if k <= 2:
        # reference 0.8.3 convention (top1/top2gating): balance loss from
        # the rank-1 assignment, scale E
        ce = jnp.mean(masks[0], axis=0)
        l_aux = jnp.sum(me * ce) * E
    else:
        # upstream general-topk convention: FULL top-k mask, scale E*E/k
        # (torch.mean(me*ce)*E*E/k == sum(me*ce)*E/k) — so k>2 training
        # (Qwen2-MoE/DBRX-style) sees the same balance pressure as the
        # framework it mirrors
        ce = jnp.mean(sum(masks).astype(jnp.float32), axis=0)
        l_aux = jnp.sum(me * ce) * E / k

    prev_counts = jnp.zeros((E,), jnp.float32)
    keeps, locs, kept_flags = [], [], []
    for m in masks:
        pos_in_expert = jnp.cumsum(m, axis=0) - m + prev_counts[None]
        p = jnp.sum(pos_in_expert * m, axis=-1)
        keep = (p < C)[:, None] * m
        keeps.append(keep)
        locs.append(p)
        kept_flags.append(jnp.sum(keep, axis=-1) > 0)
        prev_counts = prev_counts + jnp.sum(m, axis=0)

    # gate mass from SURVIVING assignments; renormalize after the drop
    g_list = [jnp.sum(gates * keep, axis=-1) for keep in keeps]
    if norm_topk:
        denom = jnp.maximum(sum(g_list), 1e-9)
        g_list = [g / denom for g in g_list]

    slot_cols = [jnp.where(kept, idx.astype(jnp.int32) * C
                           + p.astype(jnp.int32), E * C)
                 for idx, p, kept in zip(idxs, locs, kept_flags)]
    gval_cols = [g * kept for g, kept in zip(g_list, kept_flags)]
    slots = jnp.stack(slot_cols, axis=1)
    gate_vals = jnp.stack(gval_cols, axis=1)
    if not build_dense:
        return GateOutput(l_aux=l_aux, combine_weights=None,
                          dispatch_mask=None, exp_counts=exp_counts,
                          slots=slots, gate_vals=gate_vals, capacity=C)
    combine = sum(
        g[:, None, None] * keep[:, :, None]
        * _one_hot(p.astype(jnp.int32), C)[:, None, :]
        for g, keep, p in zip(g_list, keeps, locs))
    dispatch = combine > 0
    return GateOutput(l_aux=l_aux, combine_weights=combine,
                      dispatch_mask=dispatch, exp_counts=exp_counts,
                      slots=slots, gate_vals=gate_vals, capacity=C)


class TopKGate:
    """Parity shim of reference ``TopKGate:351`` as a functional object."""

    def __init__(self, model_dim, num_experts, k=1, capacity_factor=1.0,
                 eval_capacity_factor=1.0, min_capacity=4,
                 noisy_gate_policy=None, drop_tokens=True,
                 norm_topk_prob=True):
        assert 1 <= k <= num_experts, (k, num_experts)
        self.norm_topk_prob = norm_topk_prob
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens

    def init(self, rng):
        scale = 1.0 / math.sqrt(self.model_dim)
        return {"wg": jax.random.normal(
            rng, (self.model_dim, self.num_experts), jnp.float32) * scale}

    def __call__(self, gate_params, x, train=True, rng=None,
                 build_dense=True) -> GateOutput:
        logits = x.astype(jnp.float32) @ gate_params["wg"]
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity,
                              self.noisy_gate_policy if train else None,
                              rng=rng, drop_tokens=self.drop_tokens,
                              build_dense=build_dense)
        if self.k == 2 and self.norm_topk_prob:
            # second-expert sampling noise only during training (eval must
            # be deterministic, matching the top-1 path)
            return top2gating(logits, cf, self.min_capacity,
                              rng=rng if train else None,
                              build_dense=build_dense)
        # k > 2 (or k=2 without renormalization): Qwen2-MoE/DBRX-era
        # routing; selection noise only during training
        return topkgating(logits, self.k, cf, self.min_capacity,
                          norm_topk=self.norm_topk_prob,
                          build_dense=build_dense,
                          drop_tokens=self.drop_tokens,
                          noisy_gate_policy=(self.noisy_gate_policy
                                             if train else None),
                          rng=rng if train else None)


def moe_layer_forward(gate: TopKGate, gate_params, expert_params, expert_fn,
                      x, train=True, rng=None, dispatch_impl="scatter"):
    """The MOELayer hot path (reference ``MOELayer.forward:439``).

    x: [B, S, D] → tokens [B*S, D]; expert_params leaves have leading E dim
    sharded over ``ep``; returns (out [B,S,D], l_aux, exp_counts).

    The sharding constraints around dispatch/combine reproduce the
    reference's explicit all-to-alls: tokens are sharded over the batch
    axes, the dispatched tensor over ``ep`` — the transition is an
    all-to-all over ICI.

    ``dispatch_impl``:

    * ``"scatter"`` (default) — compact routing: each kept assignment
      scatter-adds its token into slot ``e*C + c`` of the [E·C, D] buffer
      and combine gathers back with the gate weight.  O(T·k·D) work; the
      dense [T, E, C] tensors are never built.
    * ``"einsum"`` — the GShard-style one-hot einsums (O(T·E·C·D) FLOPs,
      quadratic in tokens at fixed capacity factor).  Kept as the oracle:
      both paths produce identical outputs (same cumsum slot priority).
    """
    B, S, D = x.shape
    tokens = x.reshape(B * S, D)
    tokens = maybe_constrain(tokens, TOKENS_SPEC)

    out = gate(gate_params, tokens, train=train, rng=rng,
               build_dense=dispatch_impl == "einsum")
    if dispatch_impl == "einsum":
        # dispatch: [tokens, E, C] × [tokens, D] → [E, C, D] (all-to-all #1)
        dispatched = jnp.einsum("tec,td->ecd",
                                out.dispatch_mask.astype(x.dtype), tokens)
    else:
        C, E, k = out.capacity, out.exp_counts.shape[0], out.slots.shape[1]
        flat_slots = out.slots.reshape(-1)                 # [T*k]
        tokens_k = jnp.broadcast_to(
            tokens[:, None, :], (tokens.shape[0], k, D)).reshape(-1, D)
        # row E*C absorbs dropped assignments; distinct slots → no collide
        buf = jnp.zeros((E * C + 1, D), x.dtype)
        buf = buf.at[flat_slots].add(tokens_k)             # all-to-all #1
        dispatched = buf[:E * C].reshape(E, C, D)

    dispatched = maybe_constrain(dispatched, DISPATCH_SPEC)
    expert_out = expert_fn(expert_params, dispatched)      # [E, C, D]
    expert_out = maybe_constrain(expert_out, DISPATCH_SPEC)

    if dispatch_impl == "einsum":
        # combine: [tokens, E, C] × [E, C, D] → [tokens, D] (all-to-all #2)
        combined = jnp.einsum("tec,ecd->td",
                              out.combine_weights.astype(x.dtype),
                              expert_out)
    else:
        # replicate before the combine gather (this IS all-to-all #2's
        # traffic): XLA's partitioned gather over the unevenly sharded
        # [E*C+1, D] buffer reads wrong rows under ep sharding, silently
        # corrupting combined outputs vs the unsharded oracle.  The
        # gather also stays on the EVEN [E*C, D] buffer with dropped
        # assignments (slot == E*C) clipped and masked to zero — a
        # gather from a concat-padded [E*C+1, D] buffer miscompiles
        # under vmap (pipeline stages batch this layer): the partitioner
        # re-shards the uneven concat behind the replication constraint
        # and the batched gather again reads wrong rows
        eo = maybe_constrain(expert_out.reshape(E * C, D), P(None, None))
        safe = jnp.clip(out.slots, 0, E * C - 1)
        gathered = eo[safe] * \
            (out.slots < E * C)[..., None]                 # dropped read 0
        combined = jnp.sum(
            gathered * out.gate_vals[..., None].astype(x.dtype),
            axis=1)                                        # all-to-all #2
    combined = maybe_constrain(combined, TOKENS_SPEC)
    return combined.reshape(B, S, D), out.l_aux, out.exp_counts
