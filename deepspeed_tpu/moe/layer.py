"""MoE layer — user-facing module.

Parity: reference ``deepspeed/moe/layer.py:15`` (``MoE``: wraps an expert
module with a TopKGate + MOELayer, expert-parallel groups created from
``ep_size``) and ``moe/experts.py`` (``Experts``: per-rank expert stack).

TPU design: experts are ONE stacked params pytree with leading dim
``num_experts`` sharded over the ``ep`` mesh axis — the per-rank expert lists
and process groups of the reference dissolve into that sharding.  The expert
computation is a vmap/einsum over the expert dim so all experts run in one
batched matmul (MXU-friendly), instead of a Python loop over expert modules.
"""

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.moe.sharded_moe import TopKGate, moe_layer_forward
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import EP_AXIS, FSDP_AXIS, TP_AXIS


class MoE:
    """Functional MoE FFN: init() → params, __call__(params, x) →
    (out, l_aux, exp_counts)."""

    def __init__(self, hidden_size, ffn_hidden_size=None, num_experts=1, k=1,
                 capacity_factor=1.0, eval_capacity_factor=1.0,
                 min_capacity=4, noisy_gate_policy: Optional[str] = None,
                 drop_tokens=True, activation="gelu",
                 use_residual=False):
        self.hidden_size = hidden_size
        self.ffn_dim = ffn_hidden_size or 4 * hidden_size
        self.num_experts = num_experts
        self.use_residual = use_residual
        self.activation = activation
        self.gate = TopKGate(hidden_size, num_experts, k, capacity_factor,
                             eval_capacity_factor, min_capacity,
                             noisy_gate_policy, drop_tokens)

    # ------------------------------------------------------------------
    def init(self, rng, dtype=jnp.float32):
        kg, k1, k2, k3 = jax.random.split(rng, 4)
        E, D, F = self.num_experts, self.hidden_size, self.ffn_dim

        def dense(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32) /
                    math.sqrt(fan_in)).astype(dtype)

        params = {
            "gate": self.gate.init(kg),
            "experts": {
                "w_up": dense(k1, (E, D, F), D),
                "b_up": jnp.zeros((E, F), dtype),
                "w_down": dense(k2, (E, F, D), F),
                "b_down": jnp.zeros((E, D), dtype),
            },
        }
        if self.use_residual:
            params["residual_mlp"] = {
                "w_up": dense(k3, (D, F), D),
                "w_down": dense(jax.random.fold_in(k3, 1), (F, D), F),
            }
            params["coefficient"] = jnp.zeros((D, 2), dtype)
        return params

    # ------------------------------------------------------------------
    def tp_rules(self):
        """Sharding for expert weights: expert dim over ep, ffn dim over tp
        (column/row parallel within each expert)."""
        return [
            (r"experts.*w_up", P(EP_AXIS, None, TP_AXIS)),
            (r"experts.*b_up", P(EP_AXIS, TP_AXIS)),
            (r"experts.*w_down", P(EP_AXIS, TP_AXIS, None)),
            (r"experts.*b_down", P(EP_AXIS, None)),
        ]

    # ------------------------------------------------------------------
    def _expert_fn(self, expert_params, dispatched):
        """dispatched: [E, C, D] → [E, C, D]; one batched einsum per matmul
        so every expert's FFN runs on the MXU together."""
        act = jax.nn.gelu if self.activation == "gelu" else jax.nn.silu
        h = jnp.einsum("ecd,edf->ecf", dispatched, expert_params["w_up"])
        h = act(h + expert_params["b_up"][:, None, :])
        out = jnp.einsum("ecf,efd->ecd", h, expert_params["w_down"])
        return out + expert_params["b_down"][:, None, :]

    def __call__(self, params, x, train=True, rng=None):
        out, l_aux, exp_counts = moe_layer_forward(
            self.gate, params["gate"], params["experts"], self._expert_fn,
            x, train=train, rng=rng)
        if self.use_residual:
            mlp = params["residual_mlp"]
            act = jax.nn.gelu if self.activation == "gelu" else jax.nn.silu
            res = act(x @ mlp["w_up"]) @ mlp["w_down"]
            coef = jax.nn.softmax(x @ params["coefficient"], axis=-1)
            out = out * coef[..., 0:1] + res * coef[..., 1:2]
        return out, l_aux, exp_counts
