from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.moe.sharded_moe import (TopKGate, top1gating, top2gating,
                                           moe_layer_forward)
from deepspeed_tpu.moe.experts import Experts
from deepspeed_tpu.moe.mappings import drop_tokens, gather_tokens
from deepspeed_tpu.moe.utils import (
    has_moe_layers, is_moe_param,
    split_params_grads_into_shared_and_expert_params,
    split_params_into_different_moe_groups_for_optimizer,
    split_params_into_shared_and_expert_params)
