from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.moe.sharded_moe import (TopKGate, top1gating, top2gating,
                                           moe_layer_forward)
