"""ZeRO config.

Parity: reference ``deepspeed/runtime/zero/config.py:79``
(``DeepSpeedZeroConfig``) + ``offload_config.py`` (``OffloadDeviceEnum``).
Keys keep reference spellings.  Keys that configured CUDA-side bucketing
mechanics (bucket sizes, overlap_comm) are accepted and recorded but are
advisory on TPU: XLA schedules and overlaps the collectives itself; we keep
them because autotuning and user configs set them.  The ``overlap`` block
is the exception — it is NOT advisory: it turns on the explicit gather
pipeline / bucketed reduce-scatter in ``stage_plan.layer_scan`` and the
engine (see ``DeepSpeedZeroOverlapConfig``).
"""

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    device = OffloadDeviceEnum.none
    nvme_path = None
    # device-resident streamed working sets (reference: number of aio/pinned
    # buffers in AsyncPartitionedParameterSwapper).  Controls BOTH sides of
    # the stream: the fwd/bwd loops keep a window of ``buffer_count``
    # per-layer working sets on device (prefetch depth = buffer_count-1
    # layers ahead) and backward bounds in-flight gradient D2H trees to the
    # same count; >=2 for double buffering.  Default 2 = the minimal HBM
    # footprint (the capacity-sized models offload_param exists for);
    # raise it to deepen the prefetch pipeline when HBM allows
    buffer_count = 2
    buffer_size = 100_000_000
    max_in_cpu = 1_000_000_000
    pin_memory = False
    # TPU extension: pin the first N layers' working sets in HBM across the
    # whole step (uploaded once per optimizer step instead of once per
    # fwd/bwd traversal) — the dial between max model size (0) and max
    # throughput (n_layers)
    resident_layers = 0


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device = OffloadDeviceEnum.none
    nvme_path = None
    buffer_count = 4
    pin_memory = False
    pipeline_read = False
    pipeline_write = False
    fast_init = False
    ratio = 1.0


class DeepSpeedZeroOverlapConfig(DeepSpeedConfigModel):
    """``zero_optimization.overlap``: the explicit comm/compute overlap
    layer for the ZeRO-3 step (stage_plan.layer_scan + the engine's
    bucketed grad reduce-scatter).  Unlike the advisory ``overlap_comm``
    key this block changes the traced program: the forward scan gathers
    layer k+1's parameters while layer k computes (``gather_prefetch_depth``
    buffers in flight) and backward's grad reduction is issued in
    ``rs_bucket_bytes`` buckets as layers' grads finalize.  Overlap may
    reorder communication, never math — ``enabled=false`` is bit-for-bit
    the serial step."""
    enabled = False
    # forward gather pipeline: how many layers ahead the all-gather runs.
    # 1 = gather layer k+1 while k computes (double buffering: two gathered
    # working sets live); depth d keeps d+1 buffers resident
    gather_prefetch_depth = 1
    # backward reduce-scatter bucketing: grads are flushed in buckets of at
    # most this many bytes, last layers first, so the reduction of layer
    # k's grads overlaps the backward compute of layers < k
    rs_bucket_bytes = 50_000_000

    def _validate(self):
        if int(self.gather_prefetch_depth) < 1:
            raise ValueError(
                "zero_optimization.overlap.gather_prefetch_depth must be "
                f">= 1, got {self.gather_prefetch_depth}")
        if int(self.rs_bucket_bytes) <= 0:
            raise ValueError(
                "zero_optimization.overlap.rs_bucket_bytes must be > 0, "
                f"got {self.rs_bucket_bytes}")
        self.gather_prefetch_depth = int(self.gather_prefetch_depth)
        self.rs_bucket_bytes = int(self.rs_bucket_bytes)


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage = 0
    contiguous_gradients = True
    reduce_scatter = True
    reduce_bucket_size = 500_000_000
    allgather_partitions = True
    allgather_bucket_size = 500_000_000
    overlap_comm = None
    overlap = None
    load_from_fp32_weights = True
    elastic_checkpoint = False
    offload_param = None
    offload_optimizer = None
    sub_group_size = 1_000_000_000
    cpu_offload_param = None
    cpu_offload_use_pin_memory = None
    cpu_offload = None
    prefetch_bucket_size = 50_000_000
    param_persistence_threshold = 100_000
    model_persistence_threshold = 2 ** 63 - 1
    max_live_parameters = 1_000_000_000
    max_reuse_distance = 1_000_000_000
    gather_16bit_weights_on_model_save = False
    ignore_unused_parameters = True
    legacy_stage1 = False
    round_robin_gradients = False

    _deprecated_ = {
        "stage3_prefetch_bucket_size": "prefetch_bucket_size",
        "stage3_param_persistence_threshold": "param_persistence_threshold",
        "stage3_model_persistence_threshold": "model_persistence_threshold",
        "stage3_max_live_parameters": "max_live_parameters",
        "stage3_max_reuse_distance": "max_reuse_distance",
        "stage3_gather_16bit_weights_on_model_save": "gather_16bit_weights_on_model_save",
        "stage3_gather_fp16_weights_on_model_save": "gather_16bit_weights_on_model_save",
    }

    def _validate(self):
        assert self.stage in (0, 1, 2, 3), f"invalid ZeRO stage {self.stage}"
        # legacy bool cpu_offload -> offload_optimizer dict
        if self.cpu_offload:
            self.offload_optimizer = self.offload_optimizer or {"device": "cpu"}
        if isinstance(self.offload_param, dict):
            self.offload_param = DeepSpeedZeroOffloadParamConfig(self.offload_param)
        if isinstance(self.offload_optimizer, dict):
            self.offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig(
                self.offload_optimizer)
        if isinstance(self.overlap, dict):
            self.overlap = DeepSpeedZeroOverlapConfig(self.overlap)
        elif self.overlap is None:
            self.overlap = DeepSpeedZeroOverlapConfig({})

    @property
    def offload_optimizer_device(self):
        if self.offload_optimizer is None:
            return OffloadDeviceEnum.none
        return self.offload_optimizer.device

    @property
    def offload_param_device(self):
        if self.offload_param is None:
            return OffloadDeviceEnum.none
        return self.offload_param.device
