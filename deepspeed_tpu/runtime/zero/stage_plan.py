"""ZeRO stages as sharding plans — the heart of the TPU redesign.

The reference implements ZeRO with imperative machinery: flattened partition
buffers, per-param grad hooks, bucketed reduce-scatter, prefetch hooks
(``stage_1_and_2.py:102``, ``stage3.py:65``, ``partitioned_param_coordinator.py:44``).
On TPU none of that machinery is needed: ZeRO is *a placement policy*, and the
XLA SPMD partitioner materialises the identical communication schedule from
sharding annotations:

========  =================  ==================  ==================
stage     params             gradients           optimizer state
========  =================  ==================  ==================
0 (DDP)   replicated         all-reduce          replicated
1         replicated         all-reduce          fsdp-sharded
2         replicated         reduce-scatter      fsdp-sharded
3 (FSDP)  fsdp-sharded       reduce-scatter      fsdp-sharded
========  =================  ==================  ==================

* "fsdp-sharded": each leaf is sharded on its largest eligible dim over the
  ``fsdp`` mesh axis (flattened-buffer partitioning in the reference; per-dim
  sharding here so XLA can fuse the collectives with compute).
* stage-2 reduce-scatter falls out of constraining grads to the sharded spec:
  the partitioner rewrites all-reduce → reduce-scatter + (lazy) all-gather.
* stage-3 all-gather-on-demand + prefetch (reference param coordinator trace
  machinery) falls out of XLA's latency-hiding scheduler when the forward is a
  ``lax.scan`` over layers: the gather of layer *i+1* overlaps layer *i*'s
  compute.
* ``param_persistence_threshold`` (reference ``zero/config.py``) maps to "keep
  small leaves replicated" — same memory/latency trade.

TP composes: the model provides per-leaf ``PartitionSpec`` rules over the
``tp``/``sp`` axes; the plan adds ``fsdp`` on a free dim.
"""

import re
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DP_AXIS, FSDP_AXIS, SP_AXIS,
                                             TP_AXIS)


def _spec_get(spec: Optional[P], ndim: int):
    """Normalise a PartitionSpec to a per-dim tuple of axis names."""
    if spec is None:
        return [None] * ndim
    entries = list(spec) + [None] * (ndim - len(spec))
    return entries[:ndim]


def _axes_in(entry):
    if entry is None:
        return []
    if isinstance(entry, (tuple, list)):
        return list(entry)
    return [entry]


def add_axis_to_spec(spec: Optional[P], shape, axis_name: str, axis_size: int,
                     mesh_shape=None, prefer_dim: Optional[int] = None) -> P:
    """Return ``spec`` with ``axis_name`` added on the largest eligible dim.

    A dim is eligible when the global extent is divisible by ``axis_size``
    times the product of mesh axes already sharding it.  Falls back to the
    original spec (replicated over ``axis_name``) when nothing divides —
    matching the reference behaviour of leaving un-partitionable tensors whole
    on every rank.
    """
    if axis_size <= 1 or len(shape) == 0:
        return spec if spec is not None else P()
    mesh_shape = mesh_shape or {}
    entries = _spec_get(spec, len(shape))
    candidates = []
    for d, (dim, entry) in enumerate(zip(shape, entries)):
        used = _axes_in(entry)
        if axis_name in used:
            return spec
        existing = 1
        for a in used:
            existing *= mesh_shape.get(a, 1)
        candidates.append((d, dim, existing))
    order = sorted(candidates, key=lambda t: -t[1])
    if prefer_dim is not None:
        order = sorted(order, key=lambda t: (t[0] != prefer_dim, -t[1]))
    for d, dim, existing in order:
        if dim % (axis_size * existing) == 0:
            entry = entries[d]
            if entry is None:
                entries[d] = axis_name
            else:
                entries[d] = tuple(_axes_in(entry) + [axis_name])
            return P(*entries)
    # nothing divides: keep the base spec, truncated to the leaf's rank
    # (a rule written for a 3-D weight may match an auxiliary 1-D leaf,
    # e.g. quantization scales)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def _leaf_size(leaf) -> int:
    shape = getattr(leaf, "shape", ())
    return int(np.prod(shape)) if shape else 1


class ZeroShardingPlan:
    """Produces NamedShardings for params / grads / optimizer state / batch.

    ``tp_rules``: optional list of ``(path_regex, PartitionSpec)`` supplying
    tensor/sequence-parallel specs per parameter (the model's sharding map).
    """

    def __init__(self, mesh, stage: int = 0,
                 tp_rules=None,
                 param_persistence_threshold: int = 0,
                 offload_optimizer: bool = False,
                 offload_param: bool = False):
        assert stage in (0, 1, 2, 3)
        self.mesh = mesh
        self.stage = stage
        self.tp_rules = [(re.compile(pat), spec) for pat, spec in (tp_rules or [])]
        self.param_persistence_threshold = param_persistence_threshold
        self.offload_optimizer = offload_optimizer
        self.offload_param = offload_param
        self.fsdp_size = mesh.shape.get(FSDP_AXIS, 1)

    # ------------------------------------------------------------------
    def _tp_spec_for(self, path: str, leaf) -> Optional[P]:
        for pat, spec in self.tp_rules:
            if pat.search(path):
                return spec
        return None

    def _fsdp_spec(self, path: str, leaf) -> P:
        """Full stage-3 spec: tp spec + fsdp on a free dim."""
        base = self._tp_spec_for(path, leaf)
        if self._leaf_persists(leaf):
            return base if base is not None else P()
        return add_axis_to_spec(base, getattr(leaf, "shape", ()),
                                FSDP_AXIS, self.fsdp_size,
                                mesh_shape=dict(self.mesh.shape))

    def _replicated_spec(self, path: str, leaf) -> P:
        base = self._tp_spec_for(path, leaf)
        return base if base is not None else P()

    def _leaf_persists(self, leaf) -> bool:
        # small tensors stay replicated (reference param_persistence_threshold)
        return _leaf_size(leaf) < self.param_persistence_threshold

    # ------------------------------------------------------------------
    # Public: spec pytrees (for with_sharding_constraint) and sharding
    # pytrees (for jit in/out shardings + device_put)
    # ------------------------------------------------------------------
    def param_specs(self, params) -> Any:
        fn = self._fsdp_spec if self.stage >= 3 else self._replicated_spec
        return self._map_with_path(fn, params)

    def grad_specs(self, params) -> Any:
        fn = self._fsdp_spec if self.stage >= 2 else self._replicated_spec
        return self._map_with_path(fn, params)

    def master_param_specs(self, params) -> Any:
        """fp32 master copies partition like optimizer state from stage 1 up
        (reference: stage-1 partitions the fp32 flat buffer)."""
        fn = self._fsdp_spec if self.stage >= 1 else self._replicated_spec
        return self._map_with_path(fn, params)

    def opt_state_specs(self, tx, params) -> Any:
        """Optimizer-state specs aligned leaf-for-leaf with params via
        ``optax.tree_map_params``; non-param leaves (step counts) replicate."""
        import optax
        opt_shape = jax.eval_shape(tx.init, params)
        pspecs = self.master_param_specs(params)
        return optax.tree_map_params(
            tx, lambda _, spec: spec, opt_shape, pspecs,
            transform_non_params=lambda _: P())

    def batch_spec(self, ndim: int = 2, sequence_dim: Optional[int] = None) -> P:
        """Batch dim sharded over every data axis (incl. ep — EP overlays DP);
        optional sequence dim over ``sp`` (Ulysses input layout)."""
        from deepspeed_tpu.parallel.topology import BATCH_AXES
        entries = [None] * ndim
        entries[0] = tuple(BATCH_AXES)
        sp = self.mesh.shape.get(SP_AXIS, 1)
        if sequence_dim is not None and sp > 1:
            entries[sequence_dim] = SP_AXIS
        return P(*entries)

    # sharding (NamedSharding) versions --------------------------------
    def _to_sharding(self, spec_tree):
        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec), spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    def param_shardings(self, params):
        return self._to_sharding(self.param_specs(params))

    def grad_shardings(self, params):
        return self._to_sharding(self.grad_specs(params))

    def opt_state_shardings(self, tx, params):
        return self._to_sharding(self.opt_state_specs(tx, params))

    def batch_sharding(self, ndim=2, sequence_dim=None):
        return NamedSharding(self.mesh, self.batch_spec(ndim, sequence_dim))

    def replicated_sharding(self):
        return NamedSharding(self.mesh, P())

    # ------------------------------------------------------------------
    @staticmethod
    def _map_with_path(fn, tree):
        def wrap(path, leaf):
            return fn(jax.tree_util.keystr(path), leaf)
        return jax.tree_util.tree_map_with_path(wrap, tree)


def device_put_global(tree, shardings):
    """``jax.device_put`` that also works on multi-host meshes.

    ``device_put`` refuses shardings with non-addressable devices; on a pod
    every process holds the same host value (SPMD init), so the global
    array is assembled per-device from the host copy
    (``make_array_from_callback`` hands each local device its slice —
    the single-controller path stays a plain device_put)."""
    def put(x, sh):
        if sh is None:
            return x
        if jax.process_count() == 1 or sh.is_fully_addressable:
            return jax.device_put(x, sh)
        host = np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) \
            else np.asarray(x)
        return jax.make_array_from_callback(
            host.shape, sh, lambda idx: host[idx])
    return jax.tree_util.tree_map(put, tree, shardings)


def active_mesh():
    """The ambient mesh installed by ``with mesh:`` — None outside."""
    try:
        from jax._src import mesh as mesh_lib
        m = mesh_lib.thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:
        return None


def maybe_constrain(x, spec: P):
    """with_sharding_constraint that is a no-op outside a mesh context (so
    model code runs unsharded in plain tests/inference)."""
    mesh = active_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def constrain(tree, spec_tree, mesh):
    """with_sharding_constraint over a pytree of PartitionSpecs.

    Uses flatten_up_to so it is robust to PartitionSpec's own pytree
    registration (P must be treated as a leaf of ``spec_tree``).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    spec_leaves = treedef.flatten_up_to(spec_tree)
    out = [jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s))
           for x, s in zip(leaves, spec_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)
