"""ZeRO stages as sharding plans — the heart of the TPU redesign.

The reference implements ZeRO with imperative machinery: flattened partition
buffers, per-param grad hooks, bucketed reduce-scatter, prefetch hooks
(``stage_1_and_2.py:102``, ``stage3.py:65``, ``partitioned_param_coordinator.py:44``).
On TPU none of that machinery is needed: ZeRO is *a placement policy*, and the
XLA SPMD partitioner materialises the identical communication schedule from
sharding annotations:

========  =================  ==================  ==================
stage     params             gradients           optimizer state
========  =================  ==================  ==================
0 (DDP)   replicated         all-reduce          replicated
1         replicated         all-reduce          fsdp-sharded
2         replicated         reduce-scatter      fsdp-sharded
3 (FSDP)  fsdp-sharded       reduce-scatter      fsdp-sharded
========  =================  ==================  ==================

* "fsdp-sharded": each leaf is sharded on its largest eligible dim over the
  ``fsdp`` mesh axis (flattened-buffer partitioning in the reference; per-dim
  sharding here so XLA can fuse the collectives with compute).
* stage-2 reduce-scatter falls out of constraining grads to the sharded spec:
  the partitioner rewrites all-reduce → reduce-scatter + (lazy) all-gather.
* stage-3 all-gather-on-demand + prefetch (reference param coordinator trace
  machinery): with ``zero_optimization.overlap`` disabled this is left to
  XLA's latency-hiding scheduler over the ``lax.scan`` forward; enabled, it
  is EXPLICIT — :func:`layer_scan` restructures the scan into a
  double-buffered gather pipeline (layer *i+1*'s all-gather issued, and
  pinned by an ``optimization_barrier``, while layer *i* computes), and
  :func:`simulate_forward_schedule` + the interval algebra in
  ``monitor/attribution.py`` make "the gather overlaps compute" a CHECKED
  invariant (tests/unit/test_zero_overlap.py), not a hope.
* ``param_persistence_threshold`` (reference ``zero/config.py``) maps to "keep
  small leaves replicated" — same memory/latency trade.

TP composes: the model provides per-leaf ``PartitionSpec`` rules over the
``tp``/``sp`` axes; the plan adds ``fsdp`` on a free dim.
"""

import contextlib
import contextvars
import functools
import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DP_AXIS, FSDP_AXIS, SP_AXIS,
                                             TP_AXIS)


def _spec_get(spec: Optional[P], ndim: int):
    """Normalise a PartitionSpec to a per-dim tuple of axis names."""
    if spec is None:
        return [None] * ndim
    entries = list(spec) + [None] * (ndim - len(spec))
    return entries[:ndim]


def _axes_in(entry):
    if entry is None:
        return []
    if isinstance(entry, (tuple, list)):
        return list(entry)
    return [entry]


def add_axis_to_spec(spec: Optional[P], shape, axis_name: str, axis_size: int,
                     mesh_shape=None, prefer_dim: Optional[int] = None) -> P:
    """Return ``spec`` with ``axis_name`` added on the largest eligible dim.

    A dim is eligible when the global extent is divisible by ``axis_size``
    times the product of mesh axes already sharding it.  Falls back to the
    original spec (replicated over ``axis_name``) when nothing divides —
    matching the reference behaviour of leaving un-partitionable tensors whole
    on every rank.
    """
    if axis_size <= 1 or len(shape) == 0:
        return spec if spec is not None else P()
    mesh_shape = mesh_shape or {}
    entries = _spec_get(spec, len(shape))
    candidates = []
    for d, (dim, entry) in enumerate(zip(shape, entries)):
        used = _axes_in(entry)
        if axis_name in used:
            return spec
        existing = 1
        for a in used:
            existing *= mesh_shape.get(a, 1)
        candidates.append((d, dim, existing))
    order = sorted(candidates, key=lambda t: -t[1])
    if prefer_dim is not None:
        order = sorted(order, key=lambda t: (t[0] != prefer_dim, -t[1]))
    for d, dim, existing in order:
        if dim % (axis_size * existing) == 0:
            entry = entries[d]
            if entry is None:
                entries[d] = axis_name
            else:
                entries[d] = tuple(_axes_in(entry) + [axis_name])
            return P(*entries)
    # nothing divides: keep the base spec, truncated to the leaf's rank
    # (a rule written for a 3-D weight may match an auxiliary 1-D leaf,
    # e.g. quantization scales)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def _leaf_size(leaf) -> int:
    shape = getattr(leaf, "shape", ())
    return int(np.prod(shape)) if shape else 1


class ZeroShardingPlan:
    """Produces NamedShardings for params / grads / optimizer state / batch.

    ``tp_rules``: optional list of ``(path_regex, PartitionSpec)`` supplying
    tensor/sequence-parallel specs per parameter (the model's sharding map).
    """

    def __init__(self, mesh, stage: int = 0,
                 tp_rules=None,
                 param_persistence_threshold: int = 0,
                 offload_optimizer: bool = False,
                 offload_param: bool = False):
        assert stage in (0, 1, 2, 3)
        self.mesh = mesh
        self.stage = stage
        self.tp_rules = [(re.compile(pat), spec) for pat, spec in (tp_rules or [])]
        self.param_persistence_threshold = param_persistence_threshold
        self.offload_optimizer = offload_optimizer
        self.offload_param = offload_param
        self.fsdp_size = mesh.shape.get(FSDP_AXIS, 1)

    # ------------------------------------------------------------------
    def _tp_spec_for(self, path: str, leaf) -> Optional[P]:
        for pat, spec in self.tp_rules:
            if pat.search(path):
                return spec
        return None

    def _fsdp_spec(self, path: str, leaf) -> P:
        """Full stage-3 spec: tp spec + fsdp on a free dim."""
        base = self._tp_spec_for(path, leaf)
        if self._leaf_persists(leaf):
            return base if base is not None else P()
        return add_axis_to_spec(base, getattr(leaf, "shape", ()),
                                FSDP_AXIS, self.fsdp_size,
                                mesh_shape=dict(self.mesh.shape))

    def _replicated_spec(self, path: str, leaf) -> P:
        base = self._tp_spec_for(path, leaf)
        return base if base is not None else P()

    def _leaf_persists(self, leaf) -> bool:
        # small tensors stay replicated (reference param_persistence_threshold)
        return _leaf_size(leaf) < self.param_persistence_threshold

    # ------------------------------------------------------------------
    # Public: spec pytrees (for with_sharding_constraint) and sharding
    # pytrees (for jit in/out shardings + device_put)
    # ------------------------------------------------------------------
    def param_specs(self, params) -> Any:
        fn = self._fsdp_spec if self.stage >= 3 else self._replicated_spec
        return self._map_with_path(fn, params)

    def grad_specs(self, params) -> Any:
        fn = self._fsdp_spec if self.stage >= 2 else self._replicated_spec
        return self._map_with_path(fn, params)

    def master_param_specs(self, params) -> Any:
        """fp32 master copies partition like optimizer state from stage 1 up
        (reference: stage-1 partitions the fp32 flat buffer)."""
        fn = self._fsdp_spec if self.stage >= 1 else self._replicated_spec
        return self._map_with_path(fn, params)

    def opt_state_specs(self, tx, params) -> Any:
        """Optimizer-state specs aligned leaf-for-leaf with params via
        ``optax.tree_map_params``; non-param leaves (step counts) replicate."""
        import optax
        opt_shape = jax.eval_shape(tx.init, params)
        pspecs = self.master_param_specs(params)
        return optax.tree_map_params(
            tx, lambda _, spec: spec, opt_shape, pspecs,
            transform_non_params=lambda _: P())

    def batch_spec(self, ndim: int = 2, sequence_dim: Optional[int] = None) -> P:
        """Batch dim sharded over every data axis (incl. ep — EP overlays DP);
        optional sequence dim over ``sp`` (Ulysses input layout)."""
        from deepspeed_tpu.parallel.topology import BATCH_AXES
        entries = [None] * ndim
        entries[0] = tuple(BATCH_AXES)
        sp = self.mesh.shape.get(SP_AXIS, 1)
        if sequence_dim is not None and sp > 1:
            entries[sequence_dim] = SP_AXIS
        return P(*entries)

    # sharding (NamedSharding) versions --------------------------------
    def _to_sharding(self, spec_tree):
        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec), spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    def param_shardings(self, params):
        return self._to_sharding(self.param_specs(params))

    def grad_shardings(self, params):
        return self._to_sharding(self.grad_specs(params))

    def opt_state_shardings(self, tx, params):
        return self._to_sharding(self.opt_state_specs(tx, params))

    def batch_sharding(self, ndim=2, sequence_dim=None):
        return NamedSharding(self.mesh, self.batch_spec(ndim, sequence_dim))

    def replicated_sharding(self):
        return NamedSharding(self.mesh, P())

    # ------------------------------------------------------------------
    @staticmethod
    def _map_with_path(fn, tree):
        def wrap(path, leaf):
            return fn(jax.tree_util.keystr(path), leaf)
        return jax.tree_util.tree_map_with_path(wrap, tree)


def device_put_global(tree, shardings):
    """``jax.device_put`` that also works on multi-host meshes.

    ``device_put`` refuses shardings with non-addressable devices; on a pod
    every process holds the same host value (SPMD init), so the global
    array is assembled per-device from the host copy
    (``make_array_from_callback`` hands each local device its slice —
    the single-controller path stays a plain device_put)."""
    def put(x, sh):
        if sh is None:
            return x
        if jax.process_count() == 1 or sh.is_fully_addressable:
            return jax.device_put(x, sh)
        host = np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) \
            else np.asarray(x)
        return jax.make_array_from_callback(
            host.shape, sh, lambda idx: host[idx])
    return jax.tree_util.tree_map(put, tree, shardings)


def active_mesh():
    """The ambient mesh installed by ``with mesh:`` — None outside."""
    try:
        from jax._src import mesh as mesh_lib
        m = mesh_lib.thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:
        return None


def maybe_constrain(x, spec: P):
    """with_sharding_constraint that is a no-op outside a mesh context (so
    model code runs unsharded in plain tests/inference)."""
    mesh = active_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def constrain(tree, spec_tree, mesh):
    """with_sharding_constraint over a pytree of PartitionSpecs.

    Uses flatten_up_to so it is robust to PartitionSpec's own pytree
    registration (P must be treated as a leaf of ``spec_tree``).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    spec_leaves = treedef.flatten_up_to(spec_tree)
    out = [jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s))
           for x, s in zip(leaves, spec_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


# ----------------------------------------------------------------------
# Explicit comm/compute overlap (``zero_optimization.overlap``)
# ----------------------------------------------------------------------
# FROZEN overlap gauge vocabulary — the engine's per-step overlap
# telemetry.  Mirrored byte-for-byte in scripts/check_telemetry_schema.py
# (OVERLAP_GAUGES there) with a lockstep test; extend both together.
OVERLAP_GAUGES = (
    "comm/overlap/exposed_ms",
    "comm/overlap/overlapped_ms",
    "comm/overlap/gather_buckets",
    "comm/overlap/rs_buckets",
    "comm/overlap/prefetch_depth",
)


class OverlapContext:
    """Trace-scope state for :func:`layer_scan`'s gather pipeline.

    Installed by :func:`overlap_scope` (the engine wraps its step builder
    in one, so the context is live exactly while jit traces the step —
    retraces included).  Carries the config knobs plus an optional
    ``spec_fn(path, stacked_leaf) -> PartitionSpec`` returning the BASE
    (tensor-parallel) spec of each stacked leaf: the gather target for a
    layer slice is that spec minus the leading layer dim — i.e. gather
    over ``fsdp`` only, leaving Megatron TP partitioning (and therefore
    the compute math) untouched.  ``on_gather(nbytes, n_layers)`` is the
    trace-time comm-census hook.  The ``layers``/``gathered_bytes``/...
    attributes are filled in at trace time by the last pipelined scan and
    read back by the engine's telemetry tail."""

    def __init__(self, gather_prefetch_depth: int = 1,
                 param_persistence_threshold: int = 0,
                 spec_fn=None, on_gather=None):
        self.gather_prefetch_depth = max(1, int(gather_prefetch_depth))
        self.param_persistence_threshold = int(param_persistence_threshold)
        self.spec_fn = spec_fn
        self.on_gather = on_gather
        # trace-time stats of the most recent pipelined scan
        self.scans = 0
        self.layers = 0
        self.gathered_bytes = 0
        self.pipelined_leaves = 0
        self.persistent_leaves = 0


_OVERLAP: contextvars.ContextVar = contextvars.ContextVar(
    "zero_overlap", default=None)


def current_overlap() -> Optional[OverlapContext]:
    """The ambient :class:`OverlapContext`, or None (serial scan)."""
    return _OVERLAP.get()


@contextlib.contextmanager
def overlap_scope(ctx: Optional[OverlapContext]):
    """Install ``ctx`` for the duration of the block (None = serial)."""
    token = _OVERLAP.set(ctx)
    try:
        yield ctx
    finally:
        _OVERLAP.reset(token)


@jax.custom_vjp
def _pin(pair):
    """``optimization_barrier`` with an identity gradient.

    JAX ships no differentiation rule for the barrier primitive, and the
    pipeline must be differentiable (the gather runs inside the model
    forward).  The barrier pins collective ISSUE ORDER on the primal
    path; autodiff sees a plain identity, so cotangents flow through
    untouched — values and grads stay bit-identical."""
    return jax.lax.optimization_barrier(pair)


def _pin_fwd(pair):
    return jax.lax.optimization_barrier(pair), None


def _pin_bwd(_, ct):
    return (ct,)


_pin.defvjp(_pin_fwd, _pin_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _gather_to(x, sharding):
    """``with_sharding_constraint`` on the PRIMAL path only.

    Differentiating through a sharding constraint annotates the
    cotangent with the same (gathered) sharding, which steers the SPMD
    partitioner toward an all-reduce-to-replicated gradient for the
    slice where the serial scan leaves the choice (typically a direct
    reduce-scatter into the layer-sharded stacked leaf) to the cost
    model.  Different collective, different summation grouping, ulp
    drift.  A forward-only annotation moves the gather's issue point
    without touching how backward partitions — the whole point of the
    overlap layer ("reorder communication, never math")."""
    return jax.lax.with_sharding_constraint(x, sharding)


def _gather_to_fwd(x, sharding):
    return jax.lax.with_sharding_constraint(x, sharding), None


def _gather_to_bwd(sharding, _, ct):
    return (ct,)


_gather_to.defvjp(_gather_to_fwd, _gather_to_bwd)


def _slice_gather_spec(base_spec: Optional[P], stacked_ndim: int) -> P:
    """Gather target for one layer slice of a stacked ``[L, ...]`` leaf:
    the stacked leaf's base (TP) spec with the leading layer dim dropped.
    No ``fsdp`` entry ever appears (the plan adds fsdp on top of the base
    spec), so constraining a slice to this spec is exactly "all-gather
    the ZeRO-3 shards, keep the TP split"."""
    entries = _spec_get(base_spec, stacked_ndim)[1:]
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def layer_scan(body, init, xs, length=None):
    """``jax.lax.scan`` over stacked layers, with an optional explicit
    parameter-gather pipeline (``zero_optimization.overlap``).

    With no :func:`overlap_scope` active this IS ``jax.lax.scan(body,
    init, xs)`` — bit-for-bit the seed forward.  Under an active context
    the scan is restructured into a double-buffered prefetch pipeline
    with ``depth = gather_prefetch_depth``:

    * ``depth`` per-layer working sets ("buffers") ride the carry;
      buffer rotation is donation-safe (XLA aliases the slots in the
      loop body — no per-iteration allocation).
    * pipelined leaves are delivered through the scan's NATIVE xs
      mechanism, but rotated ``depth`` layers ahead (``jnp.roll(leaf,
      -depth, axis=0)``): iteration *k* receives layer ``k + depth``'s
      slice, constrains it to the slice's replicated-over-fsdp spec (the
      explicit all-gather), and parks it in the buffer queue while the
      body consumes layer *k*'s slice from the queue head.  An
      ``optimization_barrier`` ties the fresh gather to the consumed
      buffer, pinning its issue point UNDER layer *k*'s compute where
      XLA's latency-hiding scheduler may or may not have put it.
    * small slices (``param_persistence_threshold``) skip the pipe:
      persistent leaves stay on the unrotated xs path, exactly as in the
      serial scan.

    Math is untouched — and the STRUCTURE of the backward pass is the
    serial scan-transpose, which is what makes the trajectory
    bit-identical rather than merely close: because slices ride the
    native xs path, each layer's parameter cotangent is produced by the
    very same in-loop transpose machinery (same dot, same
    reduce/scatter placement) as the serial scan, lands in the rotated
    grad stack, and is un-rotated by the transpose of ``roll`` — a pure
    permutation (``collective-permute``), no arithmetic.  The wrapped
    tail deliveries (layers ``0..depth-1`` arriving at iterations
    ``L-depth..L-1``) are never consumed, so their cotangent rows are
    zero; the prefill gathers (issued before the loop) carry those
    layers' cotangents instead, and the two accumulate by ``x + 0``
    adds.  Only the gathers' ISSUE POINTS move; per-layer values and
    parameter gradients are bit-identical to the serial scan (checked in
    tests/unit/test_zero_overlap.py).  One caveat survives at the full
    engine level: the SPMD partitioner may STAGE a multi-axis grad
    all-reduce differently between the two programs (flat vs
    grouped-per-axis), which reorders the same cross-rank sum at the
    ulp level — its own communication reordering, outside this
    transform's control.
    """
    ctx = current_overlap()
    leaves = jax.tree_util.tree_leaves(xs)
    if ctx is None or not leaves:
        return jax.lax.scan(body, init, xs, length=length)
    n_layers = int(leaves[0].shape[0])
    depth = ctx.gather_prefetch_depth
    if n_layers <= 1:
        return jax.lax.scan(body, init, xs, length=length)
    mesh = active_mesh()
    thresh = ctx.param_persistence_threshold

    # per-leaf gather specs (None = persistent slice, skip the pipeline)
    flat, treedef = jax.tree_util.tree_flatten(xs)
    paths = [jax.tree_util.keystr(p) for p, _ in
             jax.tree_util.tree_flatten_with_path(xs)[0]]
    gather_specs = []
    gathered_bytes = 0
    for path, leaf in zip(paths, flat):
        slice_size = _leaf_size(leaf) // n_layers
        if slice_size < thresh or mesh is None:
            gather_specs.append(None)
            continue
        base = ctx.spec_fn(path, leaf) if ctx.spec_fn is not None else None
        gather_specs.append(_slice_gather_spec(base, leaf.ndim))
        gathered_bytes += slice_size * np.dtype(leaf.dtype).itemsize
    ctx.scans += 1
    ctx.layers = n_layers
    ctx.gathered_bytes = gathered_bytes * n_layers
    ctx.pipelined_leaves = sum(1 for s in gather_specs if s is not None)
    ctx.persistent_leaves = sum(1 for s in gather_specs if s is None)
    if ctx.on_gather is not None and ctx.pipelined_leaves:
        ctx.on_gather(ctx.gathered_bytes, n_layers)
    if ctx.pipelined_leaves == 0:
        return jax.lax.scan(body, init, xs, length=length)

    # a prefetch deeper than L-1 gathers nothing new
    depth = min(depth, n_layers - 1)
    pipe_idx = [i for i, s in enumerate(gather_specs) if s is not None]

    def constrain(i, x):
        return _gather_to(x, NamedSharding(mesh, gather_specs[i]))

    def prefill(k):
        """Layer ``k``'s pipelined slices, gathered before the loop."""
        return tuple(
            constrain(i, jax.lax.dynamic_index_in_dim(
                flat[i], k, 0, keepdims=False))
            for i in pipe_idx)

    # pipelined leaves rotate depth layers ahead on the xs path;
    # persistent leaves stay put (bitwise the serial delivery)
    shifted = [jnp.roll(leaf, -depth, axis=0) if gather_specs[i] is not None
               else leaf for i, leaf in enumerate(flat)]
    bufs = tuple(prefill(i) for i in range(depth))

    def step(carry, xk):
        state, bufs = carry
        # xk's pipelined slices are layer k+depth's: constrain = gather
        nxt = tuple(constrain(i, xk[i]) for i in pipe_idx)
        # the barrier ties layer k+depth's gather to layer k's input:
        # the gather must be ISSUED before the body that consumes cur
        # can retire, i.e. it runs under layer k's compute
        cur, nxt = _pin((bufs[0], nxt))
        merged = list(xk)
        for slot, i in enumerate(pipe_idx):
            merged[i] = cur[slot]
        state, y = body(state, jax.tree_util.tree_unflatten(treedef, merged))
        return (state, bufs[1:] + (nxt,)), y

    (state, _), ys = jax.lax.scan(step, (init, bufs), tuple(shifted))
    return state, ys


def _leaf_nbytes(leaf) -> int:
    return _leaf_size(leaf) * np.dtype(leaf.dtype).itemsize


def plan_reduce_buckets(leaves, bucket_bytes: int):
    """Partition grad-leaf indices into reduce-scatter buckets.

    Buckets are filled in REVERSE flatten order — the last layers' grads
    are final first during backward, so flushing them first lets each
    bucket's reduction overlap the backward compute of earlier layers
    (the reference's registration-order-reversed IPG bucketing,
    ``stage3.py __reduce_and_partition_ipg_grads``).  Every bucket holds
    at least one leaf; a single leaf larger than ``bucket_bytes`` gets a
    bucket of its own."""
    buckets, cur, cur_bytes = [], [], 0
    for i in reversed(range(len(leaves))):
        nb = _leaf_nbytes(leaves[i])
        if cur and cur_bytes + nb > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return buckets


def simulate_forward_schedule(n_layers: int, compute_ms: float,
                              gather_ms: float, prefetch_depth: int = 0):
    """Analytic schedule of the scan-forward gather pipeline.

    Models exactly what :func:`layer_scan` emits: ``prefetch_depth = 0``
    is the serial schedule (gather k, then compute k, back to back — the
    seed's worst case, where nothing overlaps); ``depth >= 1`` issues
    gather *k* at the start of iteration ``k - depth`` with the comm
    channel serializing gathers.  Returns the ``comm``/``compute``
    interval lists (seconds — feed them to ``decompose_step`` or the
    interval algebra directly) plus the derived exposure:

    * serial: ``exposed_comm_frac = g / (g + c)``
    * depth >= 1, ``g <= c``: only the prefill gather is exposed —
      ``exposed_comm_frac = g / (g + L*c)``

    tests/unit/test_zero_overlap.py holds the layer_scan docstring to
    this model; ``bench.py cpu_overlap`` holds the measured multi-rank
    step to it."""
    g = float(gather_ms) / 1000.0
    c = float(compute_ms) / 1000.0
    comm, compute = [], []
    if prefetch_depth <= 0:
        t = 0.0
        for _ in range(n_layers):
            comm.append((t, t + g))
            compute.append((t + g, t + g + c))
            t += g + c
    else:
        depth = int(prefetch_depth)
        comp_start = [0.0] * n_layers
        prev_comm_end = prev_comp_end = 0.0
        for k in range(n_layers):
            ready = prev_comm_end if k < depth else \
                max(prev_comm_end, comp_start[k - depth])
            comm.append((ready, ready + g))
            prev_comm_end = ready + g
            comp_start[k] = max(prev_comp_end, prev_comm_end)
            compute.append((comp_start[k], comp_start[k] + c))
            prev_comp_end = comp_start[k] + c
    from deepspeed_tpu.monitor.attribution import (overlap_length,
                                                   total_length)
    step_s = compute[-1][1] if compute else 0.0
    exposed_s = total_length(comm) - overlap_length(comm, compute)
    return {
        "comm": comm,
        "compute": compute,
        "step_ms": step_s * 1000.0,
        "comm_ms": total_length(comm) * 1000.0,
        "exposed_comm_ms": exposed_s * 1000.0,
        "exposed_comm_frac": exposed_s / step_s if step_s > 0 else 0.0,
    }
