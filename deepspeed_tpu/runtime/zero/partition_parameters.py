"""``zero.Init`` and ``GatheredParameters`` — the param-partitioning surface.

Parity: reference ``runtime/zero/partition_parameters.py`` (``Init:539``
monkey-patches module construction so params are partitioned at creation;
``GatheredParameters`` temporarily all-gathers partitioned params;
``_convert_to_deepspeed_param:765`` adds all_gather/partition methods).

TPU design: params are an explicit pytree, so "partition at construction"
is one ``device_put`` with the stage-3 sharding plan — no interception
machinery.  ``Init`` is a context manager whose ``partition()`` places a
freshly-initialised tree; inside the context, ``init(fn, *args)`` runs the
initialiser and places the result (streaming per-leaf so the full
replicated tree never materialises on one chip).  ``GatheredParameters``
yields a host-replicated view for surgery and re-partitions modified leaves
on exit.
"""

import contextlib
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.parallel import groups
from deepspeed_tpu.runtime.zero.stage_plan import ZeroShardingPlan
from deepspeed_tpu.utils.logging import logger


class Init:

    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear: bool = True, remote_device: str = None,
                 pin_memory: bool = False, config_dict_or_path=None,
                 config=None, enabled: bool = True, dtype=None,
                 mpu=None, mesh=None, tp_rules=None):
        self.enabled = enabled
        self.mesh = mesh if mesh is not None else groups.get_mesh()
        self.dtype = dtype
        self.remote_device = remote_device
        self.tp_rules = tp_rules
        self.plan: Optional[ZeroShardingPlan] = None
        if self.enabled and self.mesh is not None:
            self.plan = ZeroShardingPlan(self.mesh, stage=3,
                                         tp_rules=tp_rules)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    # ------------------------------------------------------------------
    def partition(self, params: Any) -> Any:
        """Place a params pytree with stage-3 (fsdp) sharding."""
        if not self.enabled or self.plan is None:
            return params
        sh = self.plan._to_sharding(self.plan.param_specs(params))
        if self.dtype is not None:
            import jax.numpy as jnp
            params = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x).astype(self.dtype)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                else jnp.asarray(x), params)
        with self.mesh:
            return jax.device_put(params, sh)

    def init(self, init_fn, *args, **kwargs) -> Any:
        """Run ``init_fn`` and partition its result (the
        construct-partitioned behaviour of reference ``zero.Init``)."""
        return self.partition(init_fn(*args, **kwargs))


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank: Optional[int] = 0,
                       fwd_module=None, enabled: bool = True):
    """Host-replicated view of (possibly sharded) params.

    Usage::

        with GatheredParameters(params) as full:
            full["tok_embed"][0] = 0         # numpy surgery
        # exit: nothing to re-partition — caller re-places `full` when
        # modifications should persist (functional params are immutable)

    Yields a dict of host numpy arrays (gathered across shards).
    """
    if not enabled:
        yield params
        return
    gathered = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array)
        else np.asarray(x), params)
    yield gathered


def shutdown_init_context():
    """Parity no-op (reference tears down the __init__ monkey-patch)."""
    return None
