"""``zero.Init`` and ``GatheredParameters`` — the param-partitioning surface.

Parity: reference ``runtime/zero/partition_parameters.py`` (``Init:539``
monkey-patches module construction so params are partitioned at creation;
``GatheredParameters`` temporarily all-gathers partitioned params and
writes the modifier rank's changes back on exit;
``_convert_to_deepspeed_param:765`` adds all_gather/partition methods).

TPU design: params are an explicit pytree, so "partition at construction"
is one ``device_put`` with the stage-3 sharding plan — no interception
machinery.  ``Init`` is a context manager whose ``partition()`` places a
freshly-initialised tree; inside the context, ``init(fn, *args)`` runs the
initialiser and places the result (streaming per-leaf so the full
replicated tree never materialises on one chip).  ``GatheredParameters``
yields a mutable host view and re-partitions it on exit — the reference's
modifier-rank write-back, except the "broadcast from rank 0" is the
``device_put`` itself (host surgery is SPMD-identical on every process).
"""

import contextlib
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.parallel import groups
from deepspeed_tpu.runtime.zero.stage_plan import ZeroShardingPlan
from deepspeed_tpu.utils.logging import logger


class Init:

    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear: bool = True, remote_device: str = None,
                 pin_memory: bool = False, config_dict_or_path=None,
                 config=None, enabled: bool = True, dtype=None,
                 mpu=None, mesh=None, tp_rules=None):
        self.enabled = enabled
        self.mesh = mesh if mesh is not None else groups.get_mesh()
        self.dtype = dtype
        self.remote_device = remote_device
        self.tp_rules = tp_rules
        self.plan: Optional[ZeroShardingPlan] = None
        if self.enabled and self.mesh is not None:
            self.plan = ZeroShardingPlan(self.mesh, stage=3,
                                         tp_rules=tp_rules)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    # ------------------------------------------------------------------
    def partition(self, params: Any) -> Any:
        """Place a params pytree with stage-3 (fsdp) sharding.

        ``remote_device == "cpu"/"nvme"`` keeps the tree HOST-resident
        (numpy) — the reference's off-device construction
        (``partition_parameters.py:539``): the engine's param-stream mode
        (``runtime/zero/param_stream.py``) consumes it without the full
        tree ever materializing in HBM."""
        if not self.enabled:
            return params
        if self.remote_device in ("cpu", "nvme"):
            import jax.numpy as jnp

            def host(x):
                arr = np.asarray(jax.device_get(x)) \
                    if isinstance(x, jax.Array) else np.asarray(x)
                if self.dtype is not None and \
                        jnp.issubdtype(arr.dtype, jnp.floating):
                    arr = arr.astype(np.dtype(jnp.dtype(self.dtype).name))
                return arr
            return jax.tree_util.tree_map(host, params)
        if self.plan is None:
            return params
        sh = self.plan._to_sharding(self.plan.param_specs(params))
        if self.dtype is not None:
            import jax.numpy as jnp
            params = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x).astype(self.dtype)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                else jnp.asarray(x), params)
        with self.mesh:
            return jax.device_put(params, sh)

    def init(self, init_fn, *args, **kwargs) -> Any:
        """Run ``init_fn`` and partition its result (the
        construct-partitioned behaviour of reference ``zero.Init``)."""
        return self.partition(init_fn(*args, **kwargs))


class GatheredView(dict):
    """Mutable host view yielded by :func:`GatheredParameters`.

    Mutate leaves in place (numpy) or assign new values; after the context
    exits, ``.repartitioned`` holds the device tree with every change
    re-partitioned onto the original shardings."""

    repartitioned: Any = None


def _repartition(view, shardings, dtypes):
    def place(g, sh, dt):
        arr = np.asarray(g)
        if dt is not None and arr.dtype != dt:
            arr = arr.astype(dt)
        return jax.device_put(arr, sh) if sh is not None else arr
    return jax.tree_util.tree_map(place, view, shardings, dtypes)


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank: Optional[int] = 0,
                       fwd_module=None, enabled: bool = True):
    """Temporarily gathered, WRITABLE view of (possibly sharded) params.

    Usage (raw pytree)::

        with GatheredParameters(params) as full:
            full["tok_embed"][0] = 0          # numpy surgery
        params = full.repartitioned           # changes, sharded as before

    Usage (engine): pass the engine itself and its ``state.params`` are
    gathered AND the surgery is written back into ``engine.state`` on exit
    (the reference mutates module params the same way)::

        with GatheredParameters(engine) as full:
            full["tok_embed"][0] = 0
        # engine.state.params now carries the change, still sharded

    ``modifier_rank`` is accepted for API parity: host surgery runs
    SPMD-identically on every process, and the re-partitioning
    ``device_put`` plays the broadcast role.
    """
    engine = None
    if hasattr(params, "state") and hasattr(params, "plan"):
        engine = params
        params = engine.state.params
    if not enabled:
        yield params
        return
    shardings = jax.tree_util.tree_map(
        lambda x: x.sharding if isinstance(x, jax.Array) else None, params)
    dtypes = jax.tree_util.tree_map(
        lambda x: np.dtype(x.dtype) if hasattr(x, "dtype")
        else np.asarray(x).dtype, params)
    # np.array(): force a writable host copy (device_get may return a
    # read-only view of the transfer buffer)
    gathered = jax.tree_util.tree_map(
        lambda x: np.array(jax.device_get(x)) if isinstance(x, jax.Array)
        else np.array(x), params)
    view = GatheredView(gathered) if isinstance(gathered, dict) else gathered
    try:
        yield view
    finally:
        # modifier_rank=None = read-only inspection (reference semantics:
        # no write-back); and with neither a GatheredView nor an engine
        # there is no way to hand the result back — skip the transfer
        writeback = modifier_rank is not None and \
            (engine is not None or isinstance(view, GatheredView))
        if writeback:
            base = dict(view) if isinstance(view, GatheredView) else view
            placed = _repartition(base, shardings, dtypes)
            if isinstance(view, GatheredView):
                view.repartitioned = placed
            if engine is not None:
                engine.state = engine.state.replace(params=placed)
                logger.info("GatheredParameters: wrote modified params back "
                            "into the engine state (re-partitioned)")


def shutdown_init_context():
    """Parity no-op (reference tears down the __init__ monkey-patch)."""
    return None
