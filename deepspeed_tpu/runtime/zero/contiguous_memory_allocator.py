"""Contiguous host-buffer allocator.

Parity: reference ``runtime/zero/contiguous_memory_allocator.py``
(``ContiguousMemoryAllocator``: sub-allocates tensors out of one flat buffer
and defragments by moving live tensors down — used to keep ZeRO partition
buffers unfragmented).

TPU design: device memory is XLA's; the allocator manages *host* staging
buffers for the offload/swap engines (pinned flat numpy), where the same
fragmentation problem exists.
"""

from typing import Dict

import numpy as np

from deepspeed_tpu.utils.logging import logger


class ContiguousMemoryAllocator:

    def __init__(self, size: int, dtype=np.float32):
        self.buffer = np.zeros(size, dtype)
        self.size = size
        # offset -> length of free blocks
        self.contiguous_sizes: Dict[int, int] = {0: size}
        # tensor_id -> (offset, numel)
        self.tensor_map: Dict[int, tuple] = {}
        self.total_free = size
        self._next_id = 0

    # ------------------------------------------------------------------
    def allocate_tensor(self, numel: int) -> tuple:
        """Returns (tensor_id, view).  Defragments when no free block fits
        but total free space suffices (reference behaviour)."""
        assert numel <= self.total_free, \
            f"allocator full: need {numel}, free {self.total_free}"
        if not any(sz >= numel for sz in self.contiguous_sizes.values()):
            self.defragment()
        offset = min(off for off, sz in self.contiguous_sizes.items()
                     if sz >= numel)
        block = self.contiguous_sizes.pop(offset)
        if block > numel:
            self.contiguous_sizes[offset + numel] = block - numel
        self.total_free -= numel
        tid = self._next_id
        self._next_id += 1
        self.tensor_map[tid] = (offset, numel)
        return tid, self.buffer[offset:offset + numel]

    def release_tensor(self, tid: int):
        offset, numel = self.tensor_map.pop(tid)
        self.contiguous_sizes[offset] = numel
        self.total_free += numel
        self._merge_free()

    def get_tensor(self, tid: int) -> np.ndarray:
        offset, numel = self.tensor_map[tid]
        return self.buffer[offset:offset + numel]

    # ------------------------------------------------------------------
    def _merge_free(self):
        merged = {}
        for off in sorted(self.contiguous_sizes):
            sz = self.contiguous_sizes[off]
            if merged:
                last = max(merged)
                if last + merged[last] == off:
                    merged[last] += sz
                    continue
            merged[off] = sz
        self.contiguous_sizes = merged

    def defragment(self):
        """Compact live tensors to the front, preserving contents."""
        live = sorted(self.tensor_map.items(), key=lambda kv: kv[1][0])
        cursor = 0
        for tid, (offset, numel) in live:
            if offset != cursor:
                self.buffer[cursor:cursor + numel] = \
                    self.buffer[offset:offset + numel]
                self.tensor_map[tid] = (cursor, numel)
            cursor += numel
        self.contiguous_sizes = {cursor: self.size - cursor} \
            if cursor < self.size else {}
        logger.debug(f"defragmented: {len(live)} tensors, "
                     f"{self.total_free} free")

    def max_allocatable(self) -> int:
        return max(self.contiguous_sizes.values(), default=0)
