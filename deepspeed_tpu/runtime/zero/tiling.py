"""Tiled linear — split huge matmuls into a tile grid.

Parity: reference ``runtime/zero/tiling.py:29`` (``TiledLinear``: split an
``in_features x out_features`` linear into a grid of sub-linears so ZeRO-3
can partition/fetch pieces independently and memory stays bounded).

TPU design: XLA already shards big matmuls across the mesh, so the residual
use case is *memory-bounded single-tile compute* — e.g. a 8192x256k vocab
projection whose activation+logit buffers blow HBM.  ``tiled_linear``
iterates output tiles under ``jax.checkpoint`` (activations of tile i are
freed before tile i+1), trading recompute in the backward for peak memory —
the same trade the reference makes by splitting the module.
"""

from typing import Optional

import jax
import jax.numpy as jnp


def tiled_linear(x, w, b=None, in_splits: int = 1, out_splits: int = 1,
                 use_checkpoint: bool = True):
    """y = x @ w (+ b), computed over an ``in_splits × out_splits`` tile
    grid.  x: [..., d_in]; w: [d_in, d_out]."""
    d_in, d_out = w.shape
    assert d_in % in_splits == 0, (d_in, in_splits)
    assert d_out % out_splits == 0, (d_out, out_splits)
    ti, to = d_in // in_splits, d_out // out_splits

    def out_tile(j):
        wj = jax.lax.dynamic_slice_in_dim(w, j * to, to, axis=1)

        def compute(x, wj):
            acc = jnp.zeros(x.shape[:-1] + (to,), x.dtype)
            for i in range(in_splits):
                xi = jax.lax.dynamic_slice_in_dim(x, i * ti, ti, axis=-1)
                wij = jax.lax.dynamic_slice_in_dim(wj, i * ti, ti, axis=0)
                acc = acc + xi @ wij
            return acc
        fn = jax.checkpoint(compute) if use_checkpoint else compute
        return fn(x, wj)

    out = jnp.concatenate([out_tile(j) for j in range(out_splits)], axis=-1)
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


class TiledLinear:
    """Module-style parity surface (reference class constructor args)."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 in_splits: int = 1, out_splits: int = 1,
                 input_is_already_split: bool = False,
                 combine_out_splits: bool = True):
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.in_splits = in_splits
        self.out_splits = out_splits

    def init(self, rng, dtype=jnp.float32):
        import math
        k = 1.0 / math.sqrt(self.in_features)
        w = jax.random.uniform(rng, (self.in_features, self.out_features),
                               dtype, -k, k)
        p = {"weight": w}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.out_features,), dtype)
        return p

    def __call__(self, params, x):
        return tiled_linear(x, params["weight"], params.get("bias"),
                            self.in_splits, self.out_splits)

    forward = __call__
