"""ZeRO-Offload / ZeRO-Infinity: host + NVMe optimizer state offload.

Parity: reference ``runtime/zero/stage3.py`` tensor-swapping hookup
(``_configure_tensor_swapping:479``), the swap-engine package
``deepspeed/runtime/swap_tensor/`` (``optimizer_utils.py``,
``partitioned_optimizer_swapper.py``: swap_in/swap_out state machines with
pinned buffers + aio), and ``DeepSpeedCPUAdam``
(``csrc/adam/cpu_adam.cpp``) which performs the offloaded update on host.

TPU design
----------
On GPU, offload streams per-bucket over PCIe with CUDA streams.  On TPU the
device step is one XLA program, so offload is a *mode of the engine*:

- the fp32 master params and Adam moments live in ONE flat host buffer each
  (numpy; the flat layout is the reference's flattened partition buffer).
  On a multi-host pod (ZeRO stage 3) each process's buffers cover only its
  addressable fsdp shards (``ShardedFlatLayout`` — the per-DP-rank fp32
  partition of reference ``stage3.py``) and the updated shards are stitched
  back into global device arrays;
- the device holds compute-dtype (bf16/fp16) params only — that is the
  memory saving;
- gradients stream device→host once per optimizer step, the fused C++
  SIMD/OpenMP Adam (``ops/cpu_adam.py``) updates the master in sub-groups
  (reference ``sub_group_size`` bounding working memory), and the updated
  master streams back cast to compute dtype;
- with ``offload_optimizer.device == "nvme"`` (ZeRO-Infinity) the Adam
  moments per sub-group live in files on TPU-VM NVMe and a double-buffered
  swapper (async aio read of sub-group *i+1* while updating *i*, async
  write-back of *i-1*) keeps host RAM bounded by ``buffer_count`` buffers —
  the same overlap the reference gets from its aio thread pool.
"""

import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops import cpu_adam
from deepspeed_tpu.runtime import ZeROOptimizer
from deepspeed_tpu.ops.aio import AsyncIOHandle
from deepspeed_tpu.utils.logging import logger

SWAP_SUBDIR = "zero_stage_offload"


class FlatLayout:
    """Maps a params pytree to one flat fp32 vector and back (the reference's
    apex-style ``flatten``/``unflatten`` — ``csrc/utils/flatten_unflatten.cpp``
    — as a layout object).

    Only floating leaves enter the flat buffer (they are what the optimizer
    updates); integer/bool leaves are captured at construction and passed
    through ``unflatten`` untouched, mirroring how the engine's device pytree
    preserves non-float leaves.
    """

    def __init__(self, tree):
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        # jnp.issubdtype: bf16 is an ml_dtypes extension that
        # np.issubdtype does NOT classify as floating
        self.is_float = [jnp.issubdtype(np.asarray(x).dtype, jnp.floating)
                         for x in leaves]
        self.static_leaves = {i: np.asarray(x) for i, x in enumerate(leaves)
                              if not self.is_float[i]}
        self.shapes = [tuple(np.shape(x)) if f else None
                       for x, f in zip(leaves, self.is_float)]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes
                      if s is not None]
        self.offsets = np.concatenate([[0], np.cumsum(self.sizes)]).astype(np.int64)
        self.total = int(self.offsets[-1])

    def flatten(self, tree, out: Optional[np.ndarray] = None) -> np.ndarray:
        leaves = self.treedef.flatten_up_to(tree)
        if out is None:
            out = np.empty(self.total, np.float32)
        fi = 0
        for leaf, is_f in zip(leaves, self.is_float):
            if not is_f:
                continue
            off, size = self.offsets[fi], self.sizes[fi]
            out[off:off + size] = np.asarray(leaf, np.float32).reshape(-1)
            fi += 1
        return out

    def unflatten(self, flat: np.ndarray, dtype=None):
        leaves = []
        fi = 0
        for i, is_f in enumerate(self.is_float):
            if not is_f:
                leaves.append(self.static_leaves[i])
                continue
            off, size = self.offsets[fi], self.sizes[fi]
            x = flat[off:off + size].reshape(self.shapes[i])
            leaves.append(x.astype(dtype) if dtype is not None else x)
            fi += 1
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def pieces(self, tree):
        """Flat-order stream units for ``step_streamed``: yields
        ``(offset, size, fetch)`` where ``fetch()`` materialises that
        range's gradient values on host (fp32, raveled)."""
        leaves = self.treedef.flatten_up_to(tree)
        fi = 0
        for leaf, is_f in zip(leaves, self.is_float):
            if not is_f:
                continue
            off, size = int(self.offsets[fi]), self.sizes[fi]
            fi += 1
            yield off, size, (lambda l=leaf: np.asarray(
                jax.device_get(l), np.float32).reshape(-1))


def _shard_key(shard, shape):
    """Canonical hashable key for a shard's global index."""
    out = []
    for s, dim in zip(shard.index, shape):
        out.append((0 if s.start is None else int(s.start),
                    dim if s.stop is None else int(s.stop)))
    return tuple(out)


class ShardedFlatLayout:
    """``FlatLayout`` over the PROCESS-LOCAL shards of a sharded device
    tree — the multi-host ZeRO-Offload partition (reference: each DP rank's
    fp32 flat partition buffer in ``stage3.py``; here the partition is
    whatever fsdp/tp sharding the plan chose, read straight from the
    arrays' shardings).

    Flat order: float leaves in tree order; within a leaf, distinct local
    shard indices sorted.  Replicated device groups store one copy.
    """

    def __init__(self, dev_tree):
        leaves, self.treedef = jax.tree_util.tree_flatten(dev_tree)
        self.is_float = [jnp.issubdtype(x.dtype, jnp.floating)
                         for x in leaves]
        # non-float leaves: keep every distinct LOCAL shard's value (a
        # sharded int leaf must not collapse to shard 0's data)
        self.static_leaves: Dict[int, list] = {}
        for i, x in enumerate(leaves):
            if self.is_float[i]:
                continue
            groups: Dict[tuple, list] = {}
            for sh in x.addressable_shards:
                groups.setdefault(_shard_key(sh, x.shape),
                                  []).append(sh.device)
            self.static_leaves[i] = [
                (key, devs, np.asarray(
                    next(s for s in x.addressable_shards
                         if _shard_key(s, x.shape) == key).data))
                for key, devs in sorted(groups.items())]
        self.global_shapes = [tuple(x.shape) for x in leaves]
        # per float leaf: ordered [(index_key, [devices])]
        self.leaf_groups: List[List[Tuple[tuple, list]]] = []
        sizes = []
        for leaf, is_f in zip(leaves, self.is_float):
            if not is_f:
                continue
            groups: Dict[tuple, list] = {}
            for sh in leaf.addressable_shards:
                groups.setdefault(_shard_key(sh, leaf.shape),
                                  []).append(sh.device)
            ordered = sorted(groups.items())
            self.leaf_groups.append(ordered)
            for key, _ in ordered:
                sizes.append(int(np.prod([hi - lo for lo, hi in key])))
        self.sizes = sizes
        self.offsets = np.concatenate(
            [[0], np.cumsum(sizes)]).astype(np.int64) if sizes else \
            np.zeros(1, np.int64)
        self.total = int(self.offsets[-1])

    # -- streaming / flatten -------------------------------------------
    def pieces(self, dev_tree):
        """(offset, size, fetch) per local shard, flat order."""
        leaves = self.treedef.flatten_up_to(dev_tree)
        pi = 0
        gi = 0
        for leaf, is_f in zip(leaves, self.is_float):
            if not is_f:
                continue
            by_key = {_shard_key(sh, leaf.shape): sh
                      for sh in leaf.addressable_shards}
            for key, _ in self.leaf_groups[gi]:
                off, size = int(self.offsets[pi]), self.sizes[pi]
                sh = by_key[key]
                yield off, size, (lambda s=sh: np.asarray(
                    s.data, np.float32).reshape(-1))
                pi += 1
            gi += 1
        assert pi == len(self.sizes), "device tree shards do not match layout"

    def flatten(self, dev_tree, out: Optional[np.ndarray] = None):
        if out is None:
            out = np.empty(self.total, np.float32)
        for off, size, fetch in self.pieces(dev_tree):
            out[off:off + size] = fetch()
        return out

    # -- device assembly -----------------------------------------------
    def to_device(self, flat: np.ndarray, shardings, dtype=None):
        """Assemble the global device tree from the local flat buffer:
        one single-device array per local device per leaf, stitched with
        ``jax.make_array_from_single_device_arrays`` (each process supplies
        only its addressable shards — the multi-host-safe inverse of
        ``unflatten`` + ``device_put``)."""
        sh_leaves = self.treedef.flatten_up_to(shardings)
        out_leaves = []
        pi = 0
        gi = 0
        for i, (is_f, gshape) in enumerate(
                zip(self.is_float, self.global_shapes)):
            sharding = sh_leaves[i]
            if not is_f:
                arrs = []
                for key, devices, host in self.static_leaves[i]:
                    for d in devices:
                        arrs.append(jax.device_put(host, d))
                out_leaves.append(jax.make_array_from_single_device_arrays(
                    gshape, sharding, arrs))
                continue
            arrs = []
            for key, devices in self.leaf_groups[gi]:
                off, size = int(self.offsets[pi]), self.sizes[pi]
                pi += 1
                shape = tuple(hi - lo for lo, hi in key)
                host = flat[off:off + size].reshape(shape)
                if dtype is not None:
                    host = host.astype(dtype)
                for d in devices:
                    arrs.append(jax.device_put(host, d))
            gi += 1
            out_leaves.append(jax.make_array_from_single_device_arrays(
                gshape, sharding, arrs))
        return jax.tree_util.tree_unflatten(self.treedef, out_leaves)


class OptimizerStateSwapper:
    """NVMe swap state machine for per-sub-group optimizer moments.

    Parity: reference ``swap_tensor/partitioned_optimizer_swapper.py``
    (``swap_in_optimizer_state`` / ``swap_out_optimizer_state`` over aio with
    pinned buffers).  ``buffer_count`` host buffers ring-rotate; reads for the
    next sub-group and write-backs of the previous one are queued async and
    waited for only when the buffer is needed again.

    The swapper is a client of the tiered store
    (``runtime/tiered_store.py``): every ``sg{g}_t{t}`` moment slot is a
    registered NVMe-tier entry, all reads/writes ride the store's
    separate reader/writer aio queues (so a write-back of sub-group *i*
    still overlaps the update of *i+1*), and ``release()`` seals the
    swap directory with the checkpoint-protocol manifest — a torn swap
    file shows up as ``partial`` under ``resilience.validate_tag`` /
    ``ds_ckpt_fsck``, and every file on disk is manifest-listed (no
    stranded swap files).
    """

    def __init__(self, swap_dir: str, n_tensors: int, subgroup_sizes: List[int],
                 buffer_count: int = 4, aio_config: Optional[dict] = None):
        from deepspeed_tpu.runtime.tiered_store import (PlacementPolicy,
                                                        TieredStore)
        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        self.n_tensors = n_tensors  # moments per sub-group (adam: 2)
        self.sizes = subgroup_sizes
        # mutable measurement seam: setting pipelined=False serialises every
        # read/write (no prefetch, sync write-back) — the baseline for the
        # overlap benchmark (tests/unit/test_offload_overlap.py,
        # benchmarks/offload.py set this post-construction)
        self.pipelined = True
        # the store keeps separate read/write aio queues (reference:
        # distinct aio submit queues) and owns the swap-file catalog
        self.store = TieredStore(
            name="optimizer_swap", nvme_dir=swap_dir, nvme_subdir=None,
            policy=PlacementPolicy(default_tier="nvme"),
            aio_config=aio_config)
        for g in range(len(subgroup_sizes)):
            for t in range(n_tensors):
                self.store.register_swap(self._key(g, t),
                                         subgroup_sizes[g])
        bufsize = max(subgroup_sizes) if subgroup_sizes else 0
        self.buffer_count = max(2, buffer_count)
        self._buffers = [
            [self.store.alloc_pinned(bufsize)
             for _ in range(n_tensors)]
            for _ in range(self.buffer_count)]
        # which subgroup each buffer currently holds (-1 = free)
        self._holds = [-1] * self.buffer_count
        # slots with an in-flight write-back (their buffers must not be
        # reused until the writer queue drains)
        self._writing = set()
        self._initialized = [False] * len(subgroup_sizes)

    @staticmethod
    def _key(group: int, tensor: int) -> str:
        return f"sg{group}_t{tensor}"

    # measurement seam: the overlap benchmark/tests inject a slow aio
    # stand-in through the pre-refactor attribute names — forward them
    # to the store's queues so the injection still intercepts all I/O
    @property
    def _reader(self):
        return self.store._reader

    @_reader.setter
    def _reader(self, handle):
        self.store._reader = handle

    @property
    def _writer(self):
        return self.store._writer

    @_writer.setter
    def _writer(self, handle):
        self.store._writer = handle

    def _path(self, group: int, tensor: int) -> str:
        return self.store.path_for(self._key(group, tensor))

    def _buffer_for(self, group: int) -> int:
        slot = group % self.buffer_count
        return slot

    def swap_in(self, group: int, prefetch: bool = False) -> List[np.ndarray]:
        """Returns the host buffers holding sub-group ``group``'s moments
        (zero-filled on first touch — reference ``fast_init``)."""
        slot = self._buffer_for(group)
        size = self.sizes[group]
        views = [b[:size] for b in self._buffers[slot]]
        if self._holds[slot] == group:
            self.store.reader_wait()  # ensure any async read landed
            return views
        if slot in self._writing:
            self.store.writer_wait()  # buffer has a pending write-back
            self._writing.clear()
        if not self._initialized[group]:
            for v in views:
                v[:] = 0.0
        else:
            for t, v in enumerate(views):
                self.store.read_into(
                    self._key(group, t), v,
                    async_op=prefetch and self.pipelined)
        self._holds[slot] = group
        return views

    def swap_out(self, group: int, sync: bool = False):
        slot = self._buffer_for(group)
        assert self._holds[slot] == group, "swap_out of non-resident group"
        size = self.sizes[group]
        sync = sync or not self.pipelined
        for t, buf in enumerate(self._buffers[slot]):
            self.store.write_from(self._key(group, t), buf[:size],
                                  sync=sync)
        if not sync:
            self._writing.add(slot)
        self._initialized[group] = True

    def release(self):
        self.store.wait_all()
        self._writing.clear()
        self._holds = [-1] * self.buffer_count
        # seal: manifest + commit marker over the swap files, so fsck
        # can classify the directory and torn files are detectable
        if any(self._initialized):
            self.store.commit()


class HostOffloadOptimizer(ZeROOptimizer):
    """The offloaded optimizer: flat fp32 master + host Adam/Adagrad moments,
    optionally NVMe-swapped per sub-group.

    The engine drives it:  ``step(grads_tree) → params_tree(dtype)``.
    """

    def __init__(self, params_tree, zero_config, opt_name: str = "adamw",
                 opt_params: Optional[dict] = None, rank: int = 0,
                 world_size: int = 1, layout=None):
        opt_params = dict(opt_params or {})
        if layout is not None:
            # pre-built (e.g. ShardedFlatLayout over placed device params —
            # the multi-host partition); master filled from the same tree
            self.layout = layout
            self.master = layout.flatten(params_tree)
        else:
            self.layout = FlatLayout(params_tree)
            self.master = self.layout.flatten(
                jax.tree_util.tree_map(np.asarray, params_tree))
        self.opt_name = opt_name
        self.lr = float(opt_params.get("lr", 1e-3))
        betas = opt_params.get("betas", (0.9, 0.999))
        self.beta1, self.beta2 = float(betas[0]), float(betas[1])
        self.eps = float(opt_params.get("eps", 1e-8))
        self.weight_decay = float(opt_params.get("weight_decay", 0.0))
        self.adamw_mode = bool(opt_params.get(
            "adam_w_mode", opt_params.get(
                "adamw_mode", opt_name in ("adamw", "fusedadam", "cpuadam"))))
        self.step_count = 0
        self.rank = rank
        self.world_size = world_size

        total = self.layout.total
        sub = int(min(getattr(zero_config, "sub_group_size", 1 << 30) or 1 << 30,
                      total)) or total
        self.subgroups: List[Tuple[int, int]] = [
            (lo, min(lo + sub, total)) for lo in range(0, total, sub)]

        self.n_moments = 1 if opt_name == "adagrad" else 2
        oc = zero_config.offload_optimizer
        self.nvme = (zero_config.offload_optimizer_device == "nvme")
        self.swapper = None
        if self.nvme:
            nvme_path = (oc.nvme_path if oc and oc.nvme_path else "/tmp")
            swap_dir = os.path.join(str(nvme_path), SWAP_SUBDIR,
                                    f"rank{rank}")
            self.swapper = OptimizerStateSwapper(
                swap_dir, self.n_moments,
                [hi - lo for lo, hi in self.subgroups],
                buffer_count=(oc.buffer_count if oc else 4))
            logger.info(f"ZeRO-Infinity optimizer swap → {swap_dir} "
                        f"({len(self.subgroups)} sub-groups)")
        else:
            self.moments = [np.zeros(total, np.float32)
                            for _ in range(self.n_moments)]

    # ------------------------------------------------------------------
    def _apply_subgroup(self, gi: int, flat_grads: np.ndarray, lr: float):
        lo, hi = self.subgroups[gi]
        if self.swapper is not None:
            moments = self.swapper.swap_in(gi)
            # prefetch the next sub-group's moments while updating this one
            if gi + 1 < len(self.subgroups):
                self.swapper.swap_in(gi + 1, prefetch=True)
        else:
            moments = [m[lo:hi] for m in self.moments]
        p, g = self.master[lo:hi], flat_grads[lo:hi]
        if self.opt_name == "adagrad":
            cpu_adam.adagrad_update(p, g, moments[0], lr=lr,
                                    eps=self.eps,
                                    weight_decay=self.weight_decay)
        else:
            st = cpu_adam.CPUAdamState(m=moments[0], v=moments[1],
                                       step=self.step_count - 1)
            cpu_adam.adam_update(p, g, st, lr=lr, beta1=self.beta1,
                                 beta2=self.beta2, eps=self.eps,
                                 weight_decay=self.weight_decay,
                                 adamw_mode=self.adamw_mode)
        if self.swapper is not None:
            self.swapper.swap_out(gi)

    def step(self, grads_tree, lr: Optional[float] = None):
        """One offloaded optimizer step.  ``grads_tree``: host (numpy) fp32
        gradients, same treedef as params."""
        lr = self.lr if lr is None else float(lr)
        flat_grads = self.layout.flatten(grads_tree)
        self.step_count += 1
        for gi in range(len(self.subgroups)):
            self._apply_subgroup(gi, flat_grads, lr)
        if self.swapper is not None:
            self.swapper.release()

    def step_streamed(self, grads_tree, lr: Optional[float] = None,
                      clip_coef: Optional[float] = None,
                      upload_shardings=None, upload_dtype=None):
        """``step`` fed directly by DEVICE gradients, pipelined: all D2H
        transfers are issued up front (``copy_to_host_async``), then each
        flat-order leaf is awaited individually and a sub-group's fused
        Adam runs as soon as the transfer frontier passes it — transfer of
        leaf i+1 overlaps the update covering leaf i (the role of the
        reference's grad-bucket D2H streams in
        ``stage3.py``/``cpu_adam`` interplay).  NVMe moment prefetch
        (``_apply_subgroup``) stacks on top.

        ``upload_shardings`` (+ optional ``upload_dtype``): a shardings
        pytree matching the params — as the Adam frontier passes each
        leaf, its updated master slice is unflattened, cast, and
        ``jax.device_put`` immediately (async dispatch), so the H2D of
        leaf i rides under the Adam of leaves i+1.. — the streamed
        write-back the reference gets from per-bucket H2D streams
        (``stage_1_and_2.py:1086``); no whole-tree host cast + serial
        upload at the end of the step.  Returns the new device tree (None
        without ``upload_shardings``), giving a 4-deep pipeline:
        D2H grads / NVMe moments / C++ Adam (OpenMP, GIL released) / H2D
        params."""
        lr = self.lr if lr is None else float(lr)
        leaves = self.layout.treedef.flatten_up_to(grads_tree)
        for leaf, is_f in zip(leaves, self.layout.is_float):
            if is_f and hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()       # start every D2H now
        flat_grads = np.empty(self.layout.total, np.float32)
        self.step_count += 1

        sh_leaves = None
        out_leaves = None
        up_fi = 0          # next float leaf (flat order) to upload
        # float-ordinal -> leaf-index map so each upload_through call resumes
        # at the frontier instead of rescanning the leaf list from 0 (the
        # rescan made the bookkeeping O(leaves * subgroups) per step)
        float_idx = [i for i, f in enumerate(self.layout.is_float) if f]
        if upload_shardings is not None:
            assert isinstance(self.layout, FlatLayout), \
                "streamed upload needs the single-host FlatLayout"
            sh_leaves = self.layout.treedef.flatten_up_to(upload_shardings)
            out_leaves = [None] * len(leaves)

        def upload_through(applied: int):
            """Upload every float leaf fully covered by the applied-Adam
            frontier (master offsets < ``applied`` are final)."""
            nonlocal up_fi
            if out_leaves is None:
                return
            while up_fi < len(float_idx):
                end = int(self.layout.offsets[up_fi + 1])
                if end > applied:
                    return
                i = float_idx[up_fi]
                off = int(self.layout.offsets[up_fi])
                host = self.master[off:end].reshape(self.layout.shapes[i])
                if upload_dtype is not None:
                    host = host.astype(upload_dtype)
                out_leaves[i] = jax.device_put(host, sh_leaves[i])
                up_fi += 1

        gi = 0
        for off, size, fetch in self.layout.pieces(grads_tree):
            arr = fetch()
            if clip_coef is not None:
                arr = arr * clip_coef
            flat_grads[off:off + size] = arr
            frontier = off + size
            while gi < len(self.subgroups) and \
                    self.subgroups[gi][1] <= frontier:
                self._apply_subgroup(gi, flat_grads, lr)
                gi += 1
                upload_through(self.subgroups[gi - 1][1])
        while gi < len(self.subgroups):
            self._apply_subgroup(gi, flat_grads, lr)
            gi += 1
            upload_through(self.subgroups[gi - 1][1])
        if self.swapper is not None:
            self.swapper.release()
        if out_leaves is None:
            return None
        # non-float leaves pass through; every float leaf is uploaded by now
        for i, is_f in enumerate(self.layout.is_float):
            if not is_f:
                out_leaves[i] = jax.device_put(self.layout.static_leaves[i],
                                               sh_leaves[i])
        return jax.tree_util.tree_unflatten(self.layout.treedef, out_leaves)

    def device_params(self, shardings, dtype=None):
        """Assemble the updated master straight into a global DEVICE tree
        (multi-host path; requires a ShardedFlatLayout)."""
        assert isinstance(self.layout, ShardedFlatLayout), \
            "device_params needs the sharded layout (multi-host offload)"
        return self.layout.to_device(self.master, shardings, dtype=dtype)

    def params_tree(self, dtype=None):
        if isinstance(self.layout, ShardedFlatLayout):
            raise RuntimeError(
                "params_tree() is a single-host API: a multi-host offload "
                "master holds only this process's shards — use "
                "device_params(shardings) for the global device tree")
        return self.layout.unflatten(self.master, dtype=dtype)

    # ------------------------------------------------------------------
    # checkpointing (reference: per-DP-rank *_optim_states.pt shards)
    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        if self.swapper is not None:
            moments = [np.empty(self.layout.total, np.float32)
                       for _ in range(self.n_moments)]
            for gi, (lo, hi) in enumerate(self.subgroups):
                views = self.swapper.swap_in(gi)
                for m, v in zip(moments, views):
                    m[lo:hi] = v
            self.swapper.release()
        else:
            moments = self.moments
        return {"master": self.master, "step": self.step_count,
                **{f"moment{i}": m for i, m in enumerate(moments)}}

    def load_state_dict(self, sd: Dict[str, Any]):
        if sd["master"].shape != self.master.shape:
            raise ValueError(
                f"offload master size mismatch: checkpoint has "
                f"{sd['master'].shape[0]} elements, this optimizer expects "
                f"{self.master.shape[0]} — the checkpoint was saved with a "
                "different param partition or an older flat layout (bf16 "
                "leaves were once excluded); re-save from device state or "
                "convert via checkpoint/zero_to_fp32")
        self.master[:] = sd["master"]
        self.step_count = int(sd["step"])
        moments = [sd[f"moment{i}"] for i in range(self.n_moments)]
        if self.swapper is not None:
            for gi, (lo, hi) in enumerate(self.subgroups):
                views = self.swapper.swap_in(gi)
                for v, m in zip(views, moments):
                    v[:] = m[lo:hi]
                self.swapper.swap_out(gi, sync=True)
            self.swapper.release()
        else:
            for dst, src in zip(self.moments, moments):
                dst[:] = src

    def save(self, save_dir: str, tag: str):
        path = os.path.join(save_dir, tag)
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, f"zero_offload_rank{self.rank}.npz"),
                 **self.state_dict())

    def load(self, load_dir: str, tag: str) -> bool:
        f = os.path.join(load_dir, tag, f"zero_offload_rank{self.rank}.npz")
        if not os.path.exists(f):
            return False
        with np.load(f) as z:
            self.load_state_dict({k: z[k] for k in z.files})
        return True


class PartitionedParamSwapper:
    """NVMe offload of (compute-dtype) parameters themselves —
    ZeRO-Infinity's param swapping / ZeRO-Inference weight streaming.

    Parity: reference ``swap_tensor/partitioned_param_swapper.py``
    (``AsyncPartitionedParameterSwapper``: swap_in/swap_out params by id with
    ``available_swap_in_buffers``) used by ``partition_parameters.py`` when
    ``remote_device == "nvme"``.

    Keys are pytree paths; values round-trip through per-leaf files.  The
    inference engine streams layer k+1 (async) while layer k computes.
    """

    def __init__(self, swap_dir: str, dtype=np.float16, buffer_count: int = 5,
                 aio_config: Optional[dict] = None):
        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        self.dtype = np.dtype(dtype)
        self.handle = AsyncIOHandle(**(aio_config or {}))
        self._meta: Dict[str, Tuple[tuple, np.dtype]] = {}
        self._resident: Dict[str, np.ndarray] = {}
        self.buffer_count = buffer_count

    def _path(self, key: str) -> str:
        safe = key.replace("/", "_").replace("'", "").replace("[", "_") \
                  .replace("]", "").replace(" ", "")
        return os.path.join(self.swap_dir, f"{safe}.swp")

    def swap_out(self, key: str, array, release: bool = True):
        arr = np.asarray(array)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(self.dtype)
        self._meta[key] = (arr.shape, arr.dtype)
        self.handle.sync_pwrite(arr.reshape(-1), self._path(key))
        if not release:
            self._resident[key] = arr

    def swap_out_tree(self, tree):
        """Offload a whole params pytree; returns the list of keys."""
        keys = []
        def visit(path, leaf):
            key = jax.tree_util.keystr(path)
            self.swap_out(key, leaf)
            keys.append(key)
            return None
        jax.tree_util.tree_map_with_path(visit, tree)
        return keys

    def swap_in(self, key: str, async_op: bool = False) -> np.ndarray:
        if key in self._resident:
            return self._resident[key]
        shape, dtype = self._meta[key]
        buf = np.empty(int(np.prod(shape)) if shape else 1, dtype)
        if async_op:
            self.handle.async_pread(buf, self._path(key))
        else:
            self.handle.sync_pread(buf, self._path(key))
        out = buf.reshape(shape)
        self._resident[key] = out
        while len(self._resident) > self.buffer_count:
            self._resident.pop(next(iter(self._resident)))
        return out

    def synchronize_reads(self):
        self.handle.wait()

    def release(self, key: Optional[str] = None):
        if key is None:
            self._resident.clear()
        else:
            self._resident.pop(key, None)

    def swappable_tensor(self, array) -> bool:
        return np.asarray(array).size >= 1
