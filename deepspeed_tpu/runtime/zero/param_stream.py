"""Training-time parameter offload — ZeRO-Offload/Infinity's other half.

Parity: reference ``runtime/zero/partition_parameters.py:539``
(``zero.Init(remote_device="cpu"/"nvme")`` hosts params off-device at
construction), ``partitioned_param_coordinator.py:458``
(``__prefetch_nvme_param_partitions`` streams the working set in ahead of
use) and ``stage3.py:479`` (``_configure_tensor_swapping``).  This is the
capability behind the reference's headline "13B params on one 32 GB V100"
(``docs/_posts/2020-09-09-ZeRO-Offload.md:9``): the model's parameters do
NOT live in accelerator memory — only a small streamed working set does.

TPU design
----------
The reference drives param offload with per-submodule fetch/release hooks
and an execution-trace prefetcher.  Under XLA a jitted program's operands
must be device-resident before launch, so the streaming must happen at the
*program boundary*: the training step becomes a Python-level loop over
per-layer jitted programs (the transformer stack is homogeneous, so there
is exactly ONE compiled layer program reused L times), and the coordinator
double-buffers ``jax.device_put`` uploads of layer ``l+1`` while layer
``l``'s program runs — JAX dispatch is async, so H2D rides under compute
exactly like the reference's prefetch stream.

* Host state per layer: fp32 master + Adam moments (one flat vector each,
  the reference's flattened partition buffer) + a compute-dtype **mirror**
  that is what actually uploads (bf16 halves H2D traffic vs fp32).
* Device state: the resident group (embeddings / head / final norm — the
  reference's ``param_persistence_threshold`` idea applied at model scope)
  plus at most ``buffer_count`` streamed layer working sets.
* Backward = per-layer VJP of the same layer program, walking the stack in
  reverse with the same double-buffered streaming; layer-input activations
  are stashed at layer boundaries (exactly per-layer activation
  checkpointing, so numerics match the scan-over-layers training path).
* Gradients stream D2H (``copy_to_host_async``) into a host accumulation
  buffer; at the GAS boundary the fused C++ Adam
  (``ops/csrc/cpu_adam.cpp``) updates each layer's master and refreshes
  its mirror — composing with the optimizer-state machinery the
  device-resident offload mode already uses.
* ``offload_param.device == "nvme"`` backs master/moments/accumulators
  with ``np.memmap`` under ``nvme_path`` (ZeRO-Infinity), bounding host
  RAM the way the reference's aio swapper bounds pinned memory.
* ``resident_layers = R`` pins the first R layers' working sets on device
  across the whole step (uploaded once per optimizer step instead of once
  per traversal) — the knob between "everything streamed" (max model
  size) and "everything resident" (max throughput).

Sharding composes: each uploaded working set is placed with the plan's
tp/fsdp sharding for that layer, so multi-chip param streaming shards the
working set over the mesh like everything else.  ep (MoE list stacks take
the heterogeneous per-layer layouts) and sp (activations shard over sp;
params don't) compose the same way.  PP does NOT compose: the pipelined
step is one jitted SPMD scan with no per-layer program boundary to stream
through — the same line the reference draws (ZeRO-3 param partitioning is
incompatible with PP, reference ``engine.py:1541``); PP composes with
ZeRO-Offload via ``offload_optimizer`` instead (``pipe/engine.py``).
"""

import math
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.monitor.telemetry import get_telemetry
from deepspeed_tpu.ops import cpu_adam
from deepspeed_tpu.runtime.zero.config import OffloadDeviceEnum
from deepspeed_tpu.runtime.zero.offload import FlatLayout
from deepspeed_tpu.runtime.zero.stage_plan import device_put_global
from deepspeed_tpu.utils.logging import logger

STREAM_SUBDIR = "zero_param_stream"


def _np_dtype(dtype) -> np.dtype:
    return np.dtype(jnp.dtype(dtype).name) if not isinstance(dtype, np.dtype) \
        else dtype


def _tree_bytes(tree) -> int:
    """Total byte size of a pytree's leaves (host or device arrays)."""
    return sum(int(l.size) * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(tree))


def _alloc(shape, dtype, nvme_dir: Optional[str], name: str) -> np.ndarray:
    """Host buffer, optionally NVMe-backed (ZeRO-Infinity: ``np.memmap``
    keeps host RAM bounded; the OS page cache plays the pinned-buffer
    role of the reference's aio swapper).  ``nvme_dir=None`` = plain RAM.
    Param-state buffers (masters/mirrors/grad accumulators) and optimizer
    moments get separately chosen dirs so ``offload_optimizer: nvme`` can
    swap the moments without dragging the hot upload mirrors to disk."""
    if nvme_dir is None:
        return np.zeros(shape, dtype)
    os.makedirs(nvme_dir, exist_ok=True)
    path = os.path.join(nvme_dir, f"{name}.mm")
    mm = np.lib.format.open_memmap(path, mode="w+", dtype=dtype, shape=shape)
    return mm


def _chunked_sq(arr: np.ndarray, chunk: int = 1 << 24) -> float:
    """Sum of squares with fp32 upcast in bounded chunks — a bf16 grad
    accumulator never materialises a whole-unit fp32 copy just for the
    norm."""
    flat = arr.reshape(-1)
    total = 0.0
    for i in range(0, flat.size, chunk):
        c = flat[i:i + chunk].astype(np.float32, copy=False)
        total += float(np.dot(c, c))
    return total


def _tail_align_spec(spec: Optional[P], ndim: int) -> Optional[P]:
    """Align a tp-rule PartitionSpec written for STACKED leaves
    (leading n_layers dim) to a single-layer leaf: keep the LAST ndim
    entries.  Rules already matching the rank pass through."""
    if spec is None:
        return None
    entries = list(spec)
    if len(entries) > ndim:
        entries = entries[len(entries) - ndim:]
    return P(*entries)


class HostParamStore:
    """Host-side master/moments/mirror for the resident group + each layer.

    Unit ``-1`` is the resident group; units ``0..L-1`` are layers.
    Homogeneous (stacked-origin) layers share one ``FlatLayout`` and pack
    their vectors as rows of 2-D arrays; heterogeneous (MoE list) layers
    get per-layer layouts and buffers.
    """

    #: default for ``moments_nvme_dir``: moments live on the same tier as
    #: the param state (callers pass an explicit dir — or None for RAM —
    #: when offload_optimizer.device differs from offload_param.device)
    FOLLOW_PARAM_TIER = "__follow_param_tier__"

    def __init__(self, resident_tree, layer_trees: List[Any],
                 opt_params: Optional[dict] = None, opt_name: str = "adamw",
                 compute_dtype=jnp.bfloat16, nvme_dir: Optional[str] = None,
                 grad_dtype=np.float32,
                 moments_nvme_dir=FOLLOW_PARAM_TIER):
        opt_params = dict(opt_params or {})
        betas = opt_params.get("betas", (0.9, 0.999))
        self.beta1, self.beta2 = float(betas[0]), float(betas[1])
        self.eps = float(opt_params.get("eps", 1e-8))
        self.weight_decay = float(opt_params.get("weight_decay", 0.0))
        self.adamw_mode = bool(opt_params.get(
            "adam_w_mode", opt_params.get(
                "adamw_mode", opt_name in ("adamw", "fusedadam", "cpuadam"))))
        self.opt_name = opt_name
        self.n_moments = 1 if opt_name == "adagrad" else 2
        self.step_count = 0
        self._sq_cache: Dict[int, float] = {}
        self.compute_dtype = _np_dtype(compute_dtype)
        self.grad_dtype = _np_dtype(grad_dtype)
        self.nvme_dir = nvme_dir
        # moments may live on a different tier than the param state
        # (offload_optimizer.device is independent of offload_param.device)
        self.moments_nvme_dir = (nvme_dir
                                 if moments_nvme_dir == self.FOLLOW_PARAM_TIER
                                 else moments_nvme_dir)
        mdir = self.moments_nvme_dir
        self.n_layers = len(layer_trees)

        # every plane is catalogued in the tiered store (host tier = RAM,
        # nvme tier = memmap), so the tier/* gauges price the footprint;
        # allocation semantics are register_plane's (identical to the old
        # module-level _alloc)
        from deepspeed_tpu.runtime.tiered_store import (PlacementPolicy,
                                                        TieredStore)
        self.tiered = TieredStore(
            name="param_stream",
            policy=PlacementPolicy(default_tier="host"))

        def _alloc(shape, dtype, d, name):
            return self.tiered.register_plane(name, shape, dtype,
                                              nvme_dir=d)

        host = jax.tree_util.tree_map(np.asarray, resident_tree)
        self.res_layout = FlatLayout(host)
        self.res_master = _alloc((self.res_layout.total,), np.float32,
                                 nvme_dir, "res_master")
        self.res_layout.flatten(host, out=self.res_master)
        self.res_moments = [_alloc((self.res_layout.total,), np.float32,
                                   mdir, f"res_m{i}")
                            for i in range(self.n_moments)]
        self.res_gacc = _alloc((self.res_layout.total,), self.grad_dtype,
                               nvme_dir, "res_gacc")

        host_layers = [jax.tree_util.tree_map(np.asarray, t)
                       for t in layer_trees]
        all_layouts = [FlatLayout(t) for t in host_layers]
        l0 = all_layouts[0]
        # homogeneity requires identical PER-LEAF shapes, not just structure
        # + total count: equal-total layers with transposed/differently
        # shaped leaves must take the heterogeneous path or layer 0's layout
        # would unflatten their weights into wrong views
        self.homogeneous = all(
            lay.shapes == l0.shapes and
            jax.tree_util.tree_structure(t) ==
            jax.tree_util.tree_structure(host_layers[0])
            for lay, t in zip(all_layouts[1:], host_layers[1:]))
        if self.homogeneous:
            self.layouts = [l0] * self.n_layers
            F = l0.total
            self.masters = _alloc((self.n_layers, F), np.float32,
                                  nvme_dir, "layer_master")
            self.moments = [_alloc((self.n_layers, F), np.float32,
                                   mdir, f"layer_m{i}")
                            for i in range(self.n_moments)]
            self.mirrors = _alloc((self.n_layers, F), self.compute_dtype,
                                  nvme_dir, "layer_mirror")
            self.gaccs = _alloc((self.n_layers, F), self.grad_dtype,
                                nvme_dir, "layer_gacc")
            for l, t in enumerate(host_layers):
                l0.flatten(t, out=self.masters[l])
                self.mirrors[l] = self.masters[l].astype(self.compute_dtype)
        else:
            self.layouts = all_layouts
            self.masters = [_alloc((lay.total,), np.float32, nvme_dir,
                                   f"layer{l}_master")
                            for l, lay in enumerate(self.layouts)]
            self.moments = [[_alloc((lay.total,), np.float32, mdir,
                                    f"layer{l}_m{i}")
                             for l, lay in enumerate(self.layouts)]
                            for i in range(self.n_moments)]
            self.mirrors = [_alloc((lay.total,), self.compute_dtype,
                                   nvme_dir, f"layer{l}_mirror")
                            for l, lay in enumerate(self.layouts)]
            self.gaccs = [_alloc((lay.total,), self.grad_dtype, nvme_dir,
                                 f"layer{l}_gacc")
                          for l, lay in enumerate(self.layouts)]
            for l, t in enumerate(host_layers):
                self.layouts[l].flatten(t, out=self.masters[l])
                self.mirrors[l][:] = self.masters[l].astype(self.compute_dtype)

    # -- accessors -----------------------------------------------------
    def _master(self, l):
        return self.res_master if l < 0 else self.masters[l]

    def _gacc(self, l):
        return self.res_gacc if l < 0 else self.gaccs[l]

    def _moms(self, l):
        if l < 0:
            return self.res_moments
        return [m[l] for m in self.moments]

    def mirror_tree(self, l: int):
        """Host compute-dtype tree for layer ``l`` (upload-ready views)."""
        return self.layouts[l].unflatten(self.mirrors[l])

    def resident_tree(self, dtype=None):
        return self.res_layout.unflatten(
            self.res_master, dtype=dtype or self.compute_dtype)

    def num_params(self) -> int:
        return self.res_layout.total + sum(l.total for l in self.layouts)

    # -- gradient accumulation -----------------------------------------
    def accumulate(self, l: int, flat: np.ndarray, first: bool):
        g = self._gacc(l)
        if first:
            if flat.dtype == g.dtype:
                g[:] = flat
            else:
                g[:] = flat.astype(g.dtype)
        else:
            # in-place += with upcast handled by numpy
            np.add(g, flat.astype(g.dtype, copy=False), out=g,
                   casting="unsafe")

    def zero_grads(self):
        self._sq_cache.clear()
        self.res_gacc[:] = 0
        if self.homogeneous:
            self.gaccs[:] = 0
        else:
            for g in self.gaccs:
                g[:] = 0

    def cache_unit_sq(self, l: int):
        """Record unit ``l``'s squared-norm contribution NOW (called as its
        final gradient lands, so the norm pass overlaps the remaining D2H
        stream instead of re-reading every accumulator at the boundary)."""
        self._sq_cache[l] = _chunked_sq(self._gacc(l))

    def grad_sq_norm(self) -> float:
        """Squared global norm of the ACCUMULATED grads (host pass — the
        offloaded analogue of the engine's fp32 ``_global_norm_f32``).
        Units cached by :meth:`cache_unit_sq` are not re-read."""
        total = 0.0
        for l in range(-1, self.n_layers):
            total += (self._sq_cache[l] if l in self._sq_cache
                      else _chunked_sq(self._gacc(l)))
        return total

    # -- optimizer -----------------------------------------------------
    def begin_step(self):
        self.step_count += 1

    def apply_unit(self, l: int, lr: float, clip_coef: Optional[float],
                   gas: int):
        """Fused C++ Adam/Adagrad on unit ``l``'s master from its grad
        accumulator, then refresh the upload mirror.  ``gas`` divides the
        accumulated sum into the mean (engine scales by 1/gas in its scan;
        here accumulation is a raw sum so the division lands once)."""
        g = self._gacc(l).astype(np.float32, copy=False)
        if gas > 1:
            g = g / np.float32(gas)
        if clip_coef is not None:
            g = g * np.float32(clip_coef)
        if g is self._gacc(l):   # fp32 accumulator, no scale: don't mutate
            g = g.copy()
        p = self._master(l)
        moms = self._moms(l)
        if self.opt_name == "adagrad":
            cpu_adam.adagrad_update(p, g, moms[0], lr=lr, eps=self.eps,
                                    weight_decay=self.weight_decay)
        else:
            st = cpu_adam.CPUAdamState(m=moms[0], v=moms[1],
                                       step=self.step_count - 1)
            cpu_adam.adam_update(p, g, st, lr=lr, beta1=self.beta1,
                                 beta2=self.beta2, eps=self.eps,
                                 weight_decay=self.weight_decay,
                                 adamw_mode=self.adamw_mode)
        if l >= 0:
            self.mirrors[l][:] = p.astype(self.compute_dtype)
        self._gacc(l)[:] = 0
        self._sq_cache.pop(l, None)

    # -- checkpoint ----------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        out = {"step": self.step_count, "res_master": self.res_master}
        for i, m in enumerate(self.res_moments):
            out[f"res_m{i}"] = m
        if self.homogeneous:
            out["masters"] = self.masters
            for i, m in enumerate(self.moments):
                out[f"m{i}"] = m
        else:
            for l in range(self.n_layers):
                out[f"master{l}"] = self.masters[l]
                for i in range(self.n_moments):
                    out[f"m{i}_{l}"] = self.moments[i][l]
        return out

    def load_state_dict(self, sd: Dict[str, Any],
                        load_optimizer_states: bool = True):
        if load_optimizer_states:
            self.step_count = int(sd["step"])
            for i, m in enumerate(self.res_moments):
                m[:] = sd[f"res_m{i}"]
        self.res_master[:] = sd["res_master"]
        if self.homogeneous:
            self.masters[:] = sd["masters"]
            if load_optimizer_states:
                for i, m in enumerate(self.moments):
                    m[:] = sd[f"m{i}"]
            for l in range(self.n_layers):
                self.mirrors[l] = self.masters[l].astype(self.compute_dtype)
        else:
            for l in range(self.n_layers):
                self.masters[l][:] = sd[f"master{l}"]
                if load_optimizer_states:
                    for i in range(self.n_moments):
                        self.moments[i][l][:] = sd[f"m{i}_{l}"]
                self.mirrors[l][:] = self.masters[l].astype(self.compute_dtype)


class ParamStreamRunner:
    """Drives the streamed train step for an engine whose model exposes the
    layer-stream contract (``stream_split`` / ``stream_embed`` /
    ``stream_layer`` / ``stream_head_loss`` — ``models/transformer.py``).
    """

    def __init__(self, model, params, config, mesh, plan,
                 compute_dtype=jnp.bfloat16, grad_accum_dtype=np.float32,
                 opt_name: str = "adamw", opt_params: Optional[dict] = None):
        for meth in ("stream_split", "stream_embed", "stream_layer",
                     "stream_head_loss"):
            if not hasattr(model, meth):
                raise ValueError(
                    "offload_param needs a layer-streamable model (a "
                    f"CausalTransformerLM-style class with {meth}); got "
                    f"{type(model).__name__}.  For non-streamable models "
                    "use offload_optimizer only.")
        self.model = model
        self.mesh = mesh
        self.plan = plan
        self.compute_dtype = compute_dtype
        self.config = config
        zc = config.zero_config
        pc = zc.offload_param
        oc = zc.offload_optimizer
        base_dir = None
        # NVMe backing is chosen PER TIER: the param state (masters, hot
        # upload mirrors, grad accumulators) follows offload_param.device;
        # the Adam moments follow offload_optimizer.device (the reference
        # offloads optimizer state to NVMe independently of where params
        # live), defaulting to the param tier when unspecified.  So
        # param=cpu + optimizer=nvme swaps ONLY the moments, and
        # param=nvme + optimizer=cpu keeps the moments in RAM.
        if OffloadDeviceEnum.nvme in (zc.offload_param_device,
                                      zc.offload_optimizer_device):
            nvme_path = (pc.nvme_path if pc and pc.nvme_path else
                         (oc.nvme_path if oc and oc.nvme_path else "/tmp"))
            base_dir = os.path.join(str(nvme_path), STREAM_SUBDIR,
                                    f"rank{jax.process_index()}")
        nvme_dir = (base_dir
                    if zc.offload_param_device == OffloadDeviceEnum.nvme
                    else None)
        if zc.offload_optimizer_device == OffloadDeviceEnum.nvme:
            moments_dir = base_dir
        elif zc.offload_optimizer_device == OffloadDeviceEnum.cpu:
            moments_dir = None
        else:
            moments_dir = nvme_dir
        self.buffer_count = max(2, int(getattr(pc, "buffer_count", 2) or 2))
        self.resident_layers = int(getattr(pc, "resident_layers", 0) or 0)

        resident, layers = model.stream_split(
            jax.tree_util.tree_map(np.asarray, params))
        if isinstance(layers, (list, tuple)):
            layer_trees = list(layers)
            self.stacked = False
        else:
            L = jax.tree_util.tree_leaves(layers)[0].shape[0]
            layer_trees = [jax.tree_util.tree_map(lambda x: x[l], layers)
                           for l in range(L)]
            self.stacked = True
        self.n_layers = len(layer_trees)
        self.resident_layers = min(self.resident_layers, self.n_layers)

        self.store = HostParamStore(
            resident, layer_trees, opt_params=opt_params, opt_name=opt_name,
            compute_dtype=compute_dtype, nvme_dir=nvme_dir,
            grad_dtype=_np_dtype(grad_accum_dtype),
            moments_nvme_dir=moments_dir)

        # shardings for uploads (tp rules tail-aligned to per-layer rank,
        # fsdp added per plan stage)
        self._res_shardings = self._shardings_for(resident, prefix="")
        self._layer_shardings = [
            self._shardings_for(t, prefix="['layers']")
            for t in (layer_trees if not self.store.homogeneous
                      else layer_trees[:1])]
        if self.store.homogeneous:
            self._layer_shardings = self._layer_shardings * self.n_layers

        self.windows = None
        mcfg = getattr(model, "config", None)
        if mcfg is not None and getattr(mcfg, "local_attn_pattern", None):
            self.windows = np.asarray(mcfg.local_attn_pattern, np.int32)
        self.aux_coef = float(getattr(mcfg, "moe_aux_loss_coef", 0.0)
                              if mcfg is not None else 0.0)

        self.resident_dev = self._upload_resident()
        self._dev: Dict[int, Any] = {}       # streamed working sets
        self._pinned: Dict[int, Any] = {}    # resident_layers working sets
        self._upload_pinned()
        self._jits: Dict[str, Any] = {}
        self._adam_ex: Optional[ThreadPoolExecutor] = None
        self.boundary_pipelined = True   # ablation knob (benchmarks)
        self._tel = get_telemetry()
        # quantized-collective wire codec for the multi-host REPLICATED-
        # grad all-reduce (comm/quantize.py; "comm.quantization" block)
        from deepspeed_tpu.comm.quantize import CommQuantizer
        self.comm_quant = CommQuantizer.from_config(
            getattr(config, "comm_quantization", None))

    def _xfer_pool(self) -> ThreadPoolExecutor:
        """Single-worker pool for boundary H2D uploads: the fused C++ Adam
        keeps the MAIN thread (full OpenMP width — measured: moving Adam to
        a worker starves it of cores on small hosts), while the worker
        drains the memory-bound ``device_put`` copies of already-updated
        units underneath it."""
        if self._adam_ex is None:
            self._adam_ex = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="param_stream_xfer")
        return self._adam_ex

    # -- placement -----------------------------------------------------
    def _shardings_for(self, tree, prefix: str):
        plan = self.plan

        def spec(path, leaf):
            p = prefix + jax.tree_util.keystr(path)
            ndim = np.ndim(leaf)
            base = _tail_align_spec(plan._tp_spec_for(p, leaf), ndim)
            if plan.stage >= 3 and not plan._leaf_persists(leaf):
                from deepspeed_tpu.runtime.zero.stage_plan import \
                    add_axis_to_spec
                from deepspeed_tpu.parallel.topology import FSDP_AXIS
                base = add_axis_to_spec(base, np.shape(leaf), FSDP_AXIS,
                                        plan.fsdp_size,
                                        mesh_shape=dict(self.mesh.shape))
            return NamedSharding(self.mesh, base if base is not None else P())
        return jax.tree_util.tree_map_with_path(spec, tree)

    def _upload_resident(self):
        host = self.store.resident_tree(dtype=self.store.compute_dtype)
        return device_put_global(host, self._res_shardings)

    def _upload_pinned(self):
        for l in range(self.resident_layers):
            self._pinned[l] = device_put_global(self.store.mirror_tree(l),
                                                self._layer_shardings[l])

    def _ensure(self, l: int, use: bool = False):
        """Working set for layer ``l`` (device).  Issues the async upload if
        not already in flight — call early to prefetch, late to use.
        ``use=True`` marks the on-critical-path access: the tiered store
        books it as a prefetch hit (upload already in flight / resident)
        or a demand miss (the H2D starts now, exposed)."""
        if l < 0 or l >= self.n_layers:
            return None
        if l < self.resident_layers:
            if use:
                self.store.tiered.note_prefetch(True)
            return self._pinned[l]
        if use:
            self.store.tiered.note_prefetch(l in self._dev)
        if l not in self._dev:
            host = self.store.mirror_tree(l)
            if self._tel.enabled:
                self._tel.count("param_stream/h2d_calls")
                self._tel.count("param_stream/h2d_bytes", _tree_bytes(host))
            t0 = time.perf_counter()
            self._dev[l] = device_put_global(host, self._layer_shardings[l])
            self.store.tiered.note_transfer(
                "h2d", _tree_bytes(host), time.perf_counter() - t0)
        return self._dev[l]

    def _evict(self, keep: List[int]):
        """Drop streamed working sets not in ``keep`` (refcount drop; XLA
        frees the buffers once their last consumer retires)."""
        keep_s = set(keep)
        dropped = 0
        for l in list(self._dev):
            if l not in keep_s:
                del self._dev[l]
                dropped += 1
        if dropped:
            self.store.tiered.note_eviction(dropped)

    # -- jitted programs ----------------------------------------------
    def _jit(self, name, fn, **kw):
        if name not in self._jits:
            self._jits[name] = jax.jit(fn, **kw)
        return self._jits[name]

    def _embed_fwd(self):
        model = self.model

        def f(resident, mb, rng):
            x, positions = model.stream_embed(resident, mb, rng=rng)
            return x, positions
        return self._jit("embed_fwd", f)

    def _layer_fwd(self):
        model = self.model

        def f(layer, x, positions, aux_in, rng, window):
            x, aux = model.stream_layer(layer, x, positions, window=window,
                                        rng=rng)
            return x, aux_in + aux
        return self._jit("layer_fwd", f)

    @staticmethod
    def _finite(trees, fp16: bool):
        if not fp16:
            return jnp.asarray(True)
        return jnp.all(jnp.asarray(
            [jnp.all(jnp.isfinite(g.astype(jnp.float32)))
             for t in trees for g in jax.tree_util.tree_leaves(t)]))

    def _unscale_grads(self, tree, scale, gdt):
        """The per-program grad tail, in ONE place: unscale in fp32, store
        at grad dtype, and — multi-host only — constrain to REPLICATED so
        XLA inserts the cross-device reduction (all-reduce over ICI)
        inside the program and the result is host-readable on every
        process (each process lands identical grads and applies the
        identical update).  Single-process runs skip the constraint:
        ``device_get`` assembles sharded grads locally, and forcing
        full-size replicated grad buffers would cost the HBM headroom
        param-stream exists to create."""
        tree = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) / scale).astype(gdt), tree)
        if jax.process_count() == 1:
            return tree
        # comm census for the implicit reduction: XLA inserts it at the
        # constraint below, so no dist.* verb ever sees these bytes.
        # Dtype-true payload at gdt (the tree was just cast to it).  The
        # reduction spans every mesh axis (the constraint is fully
        # REPLICATED), so the record carries the actual axis names — on a
        # multi-slice mesh that is the DCN path, not ICI.
        from deepspeed_tpu.comm.comm import comms_logger
        # optional wire codec: model the quantized all-reduce as a
        # blockwise int8 QDQ (phase-2 re-quantization; see
        # engine._quantize_grad_wire for the trace-level rationale)
        saved = 0
        if self.comm_quant.active():
            tree, saved = self.comm_quant.qdq_tree(tree, "all_reduce")
        nbytes = _tree_bytes(tree)
        comms_logger.append("all_reduce", nbytes - saved,
                            ",".join(self.mesh.axis_names),
                            dtype=str(jnp.dtype(gdt)),
                            world=jax.process_count(),
                            wire_dtype="int8" if saved else None,
                            bytes_saved=saved if saved else None)
        repl = NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map(
            lambda g: jax.lax.with_sharding_constraint(g, repl), tree)

    def _head_fwd_bwd(self):
        model = self.model
        gdt = jnp.dtype(self.store.grad_dtype.name)
        fp16 = self.config.fp16_enabled

        def f(resident, x, mb, scale):
            def loss_f(res, xx):
                return model.stream_head_loss(res, xx, mb)
            ce, vjp = jax.vjp(loss_f, resident, x)
            dres, dx = vjp(scale.astype(jnp.float32))
            dres = self._unscale_grads(dres, scale, gdt)
            return ce, dres, dx, self._finite([dres, dx], fp16)
        return self._jit("head_fwd_bwd", f)

    def _layer_bwd(self):
        model = self.model
        gdt = jnp.dtype(self.store.grad_dtype.name)
        aux_coef = self.aux_coef
        fp16 = self.config.fp16_enabled

        def f(layer, x_in, positions, dx_out, scale, rng, window):
            def fwd(lay, xx):
                return model.stream_layer(lay, xx, positions, window=window,
                                          rng=rng)
            (x_out, aux), vjp = jax.vjp(fwd, layer, x_in)
            dlayer, dx_in = vjp((dx_out,
                                 (scale * aux_coef).astype(aux.dtype)))
            # cotangent chain stays scaled; the stored grad is unscaled
            dlayer = self._unscale_grads(dlayer, scale, gdt)
            return dx_in, dlayer, self._finite([dlayer], fp16)
        return self._jit("layer_bwd", f)

    def _embed_bwd(self):
        model = self.model
        gdt = jnp.dtype(self.store.grad_dtype.name)
        fp16 = self.config.fp16_enabled

        def f(resident, mb, rng, dx, scale):
            def fwd(res):
                return model.stream_embed(res, mb, rng=rng)[0]
            _, vjp = jax.vjp(fwd, resident)
            (dres,) = vjp(dx)
            dres = self._unscale_grads(dres, scale, gdt)
            return dres, self._finite([dres], fp16)
        return self._jit("embed_bwd", f)

    # -- grad D2H ------------------------------------------------------
    def _start_d2h(self, tree):
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()

    def _land(self, l: int, tree, layout: FlatLayout, first: bool):
        """Fetch a grad tree to host (transfer already in flight) and
        accumulate into unit ``l``'s buffer."""
        tel = self._tel if self._tel.enabled else None
        t0 = time.perf_counter() if tel else 0.0
        flat = layout.flatten(jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x), np.float32), tree))
        self.store.accumulate(l, flat, first)
        if tel:
            nbytes = _tree_bytes(tree)
            dt = time.perf_counter() - t0
            tel.count("param_stream/d2h_calls")
            tel.count("param_stream/d2h_bytes", nbytes)
            if dt > 0:
                # device_get blocks on the (already in-flight) transfer, so
                # this is an observed landing rate, not raw link bandwidth
                tel.registry.gauge("param_stream/d2h_mbps").set(
                    nbytes / dt / 1e6)

    # -- the step ------------------------------------------------------
    def train_step(self, batch, gas: int, lr: float, loss_scale,
                   fp16: bool, clip: Optional[float], rng) -> Tuple[
                       float, float, bool]:
        """One full optimizer step over ``gas`` microbatches.

        ``batch``: stacked [gas, ...] pytree (device or host) when gas>1,
        else a single microbatch.  Returns (mean unscaled loss, grad norm,
        overflow).
        """
        with self.mesh, self._tel.span("param_stream/train_step"):
            return self._train_step_in_mesh(batch, gas, lr, loss_scale,
                                            fp16, clip, rng)

    def _train_step_in_mesh(self, batch, gas, lr, loss_scale, fp16, clip,
                            rng):
        # runs under ``with self.mesh:`` so maybe_constrain inside the
        # model (activation layouts, stream_embed's batch/sp constraint)
        # fires like every other engine compute path
        L = self.n_layers
        win = self.windows
        scale = jnp.float32(loss_scale if fp16 else 1.0)
        embed_fwd = self._embed_fwd()
        layer_fwd = self._layer_fwd()
        head = self._head_fwd_bwd()
        layer_bwd = self._layer_bwd()
        embed_bwd = self._embed_bwd()

        loss_sum = jnp.float32(0.0)
        finite_all = jnp.asarray(True)
        # (unit, dev grad tree, appended-during-final-microbatch)
        pending: List[Tuple[int, Any, bool]] = []
        landed: set = set()

        def flush_pending(max_keep: int):
            while len(pending) > max_keep:
                ul, tree, fin = pending.pop(0)
                lay = (self.store.res_layout if ul < 0
                       else self.store.layouts[ul])
                self._land(ul, tree, lay, ul not in landed)
                landed.add(ul)
                if fin:
                    # this entry IS the unit's last accumulation — fold its
                    # norm contribution in now, under the D2H stream of
                    # later-landing units (entries carried over from the
                    # previous microbatch skip this: their value would only
                    # be recomputed when the final entry lands)
                    self.store.cache_unit_sq(ul)

        win_dev = (jnp.asarray(win) if win is not None else None)

        for m in range(gas):
            mb = (jax.tree_util.tree_map(lambda x: x[m], batch)
                  if gas > 1 else batch)
            mrng = jax.random.fold_in(rng, m) if rng is not None else None

            # ---- forward ----
            bc = self.buffer_count
            x, positions = embed_fwd(self.resident_dev, mb, mrng)
            stash = [None] * L
            aux = jnp.float32(0.0)
            self._ensure(0)
            for l in range(L):
                for k in range(1, bc):       # prefetch bc-1 ahead, under
                    self._ensure(l + k)      # compute (no-op once in flight)
                params_l = self._ensure(l, use=True)
                stash[l] = x
                lrng = (None if self.stacked else
                        (jax.random.fold_in(mrng, l)
                         if mrng is not None else None))
                w = (win_dev[l] if win_dev is not None else None)
                x, aux = layer_fwd(params_l, x, positions, aux, lrng, w)
                self._evict(list(range(l, l + bc)))

            # ---- head loss + bwd ----
            ce, dres_h, dx, fin = head(self.resident_dev, x, mb, scale)
            loss_sum = loss_sum + ce + self.aux_coef * aux
            finite_all = jnp.logical_and(finite_all, fin)

            # ---- backward over layers ----
            for l in range(L - 1, -1, -1):
                for k in range(1, bc):       # prefetch under compute
                    self._ensure(l - k)
                params_l = self._ensure(l, use=True)
                lrng = (None if self.stacked else
                        (jax.random.fold_in(mrng, l)
                         if mrng is not None else None))
                w = (win_dev[l] if win_dev is not None else None)
                dx, dlayer, fin = layer_bwd(params_l, stash[l], positions,
                                            dx, scale, lrng, w)
                stash[l] = None
                finite_all = jnp.logical_and(finite_all, fin)
                self._start_d2h(dlayer)
                pending.append((l, dlayer, m == gas - 1))
                flush_pending(self.buffer_count)
                self._evict(list(range(l - bc + 1, l + 1)))

            dres_e, fin = embed_bwd(self.resident_dev, mb, mrng, dx, scale)
            finite_all = jnp.logical_and(finite_all, fin)
            dres = jax.tree_util.tree_map(
                lambda a, b: (a.astype(jnp.float32) +
                              b.astype(jnp.float32)).astype(a.dtype),
                dres_h, dres_e)
            self._start_d2h(dres)
            pending.append((-1, dres, m == gas - 1))
            flush_pending(0 if m == gas - 1 else self.buffer_count)

        # ---- boundary: overflow check, norm/clip, host Adam ----
        overflow = bool(jax.device_get(jnp.logical_not(finite_all))) \
            if fp16 else False
        mean_loss = float(jax.device_get(loss_sum)) / gas
        grad_norm = 0.0
        if overflow:
            self.store.zero_grads()
        else:
            sq = self.store.grad_sq_norm()
            grad_norm = math.sqrt(sq) / gas
            clip_coef = None
            if clip and clip > 0 and grad_norm > clip:
                clip_coef = clip / (grad_norm + 1e-6)
            self._apply_boundary(lr, clip_coef, gas,
                                 pipelined=self.boundary_pipelined)
        if self._tel.enabled:
            # tier/* occupancy + hit-rate + bandwidth for this step
            self.store.tiered.publish_gauges()
        return mean_loss, grad_norm, overflow

    def _apply_boundary(self, lr: float, clip_coef: Optional[float],
                        gas: int, pipelined: bool = True):
        """GAS-boundary optimizer walk + H2D mirror refresh.

        ``pipelined`` (default): the fused C++ Adam runs unit-by-unit on
        the MAIN thread (full OpenMP width), and as each unit's update
        lands its H2D re-upload is handed to ONE worker thread — the
        memory-bound ``device_put`` of unit l rides under the Adam of unit
        l+1 (``offload.py step_streamed``'s pattern applied to the layer
        walk) without stealing compute cores from the update itself.
        ``pipelined=False`` is the serial reference walk, kept as the
        benchmark ablation (``benchmarks/param_stream_boundary``).
        """
        L = self.n_layers
        self.store.begin_step()
        # every cached working set is stale once updates start
        self._dev.clear()
        if not pipelined:
            self.store.apply_unit(-1, lr, clip_coef, gas)
            self.resident_dev = self._upload_resident()
            for l in range(L):
                self.store.apply_unit(l, lr, clip_coef, gas)
            self._upload_pinned()
            for l in range(self.resident_layers,
                           min(self.buffer_count, L)):
                self._ensure(l)   # warm next step's first window
            return
        ex = self._xfer_pool()
        store = self.store
        tel = self._tel if self._tel.enabled else None
        t0 = time.perf_counter() if tel else 0.0
        h2d_bytes = 0
        self.store.apply_unit(-1, lr, clip_coef, gas)
        res_host = store.resident_tree(dtype=store.compute_dtype)
        if tel:
            h2d_bytes += _tree_bytes(res_host)
        res_fut = ex.submit(device_put_global, res_host, self._res_shardings)
        up_futs = []
        for l in range(L):
            store.apply_unit(l, lr, clip_coef, gas)
            if l < self.resident_layers or l < self.buffer_count:
                mirror = store.mirror_tree(l)
                if tel:
                    h2d_bytes += _tree_bytes(mirror)
                up_futs.append((l, ex.submit(
                    device_put_global, mirror, self._layer_shardings[l])))
        self.resident_dev = res_fut.result()
        for l, fut in up_futs:
            if l < self.resident_layers:
                self._pinned[l] = fut.result()
            else:
                self._dev[l] = fut.result()   # warm next step's window
        if tel:
            dt = time.perf_counter() - t0
            tel.count("param_stream/boundary_h2d_bytes", h2d_bytes)
            if dt > 0:
                # uploads drain under the Adam walk; this is the boundary's
                # effective refresh rate, not raw link bandwidth
                tel.registry.gauge("param_stream/boundary_h2d_mbps").set(
                    h2d_bytes / dt / 1e6)
            tel.registry.histogram("span/param_stream/boundary").observe(
                dt * 1000.0)
            tel.emit("span", "param_stream/boundary",
                     dur_ms=round(dt * 1000.0, 3))

    # -- eval ----------------------------------------------------------
    def eval_loss(self, batch, rng=None) -> float:
        with self.mesh:
            return self._eval_loss_in_mesh(batch, rng)

    def _eval_loss_in_mesh(self, batch, rng) -> float:
        embed_fwd = self._embed_fwd()
        layer_fwd = self._layer_fwd()
        model = self.model
        x, positions = embed_fwd(self.resident_dev, batch, rng)
        aux = jnp.float32(0.0)
        win = self.windows
        bc = self.buffer_count
        for l in range(self.n_layers):
            for k in range(1, bc):
                self._ensure(l + k)
            # same per-layer rng convention as the train path / apply()
            lrng = (None if self.stacked else
                    (jax.random.fold_in(rng, l) if rng is not None
                     else None))
            w = (jnp.asarray(win[l]) if win is not None else None)
            x, aux = layer_fwd(self._ensure(l), x, positions, aux, lrng, w)
            self._evict(list(range(l, l + bc)))
        loss = self._jit(
            "eval_head",
            lambda res, xx, mb: model.stream_head_loss(res, xx, mb))(
                self.resident_dev, x, batch)
        return float(jax.device_get(loss)) + self.aux_coef * float(
            jax.device_get(aux))

    # -- state ---------------------------------------------------------
    def params_tree(self, dtype=None):
        """Full host params pytree (master precision unless ``dtype``)."""
        resident = self.store.resident_tree(dtype=dtype or np.float32)
        layer_trees = [
            self.store.layouts[l].unflatten(
                self.store.masters[l].astype(dtype or np.float32))
            for l in range(self.n_layers)]
        if self.stacked:
            layers = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *layer_trees)
        else:
            layers = layer_trees
        return self.model.stream_join(resident, layers)

    @staticmethod
    def _leaf_meta(tree) -> List[dict]:
        """Path/shape/dtype per leaf, in ``FlatLayout`` flatten order —
        enough for OFFLINE reconstruction of the nested tree from the flat
        master (``checkpoint/zero_to_fp32.py`` consumes this)."""
        out = []
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for path, leaf in flat:
            keys = []
            for p in path:
                if hasattr(p, "key"):
                    keys.append(p.key)
                elif hasattr(p, "idx"):
                    keys.append(int(p.idx))
                else:
                    keys.append(str(p))
            arr = np.asarray(leaf)
            is_float = bool(jnp.issubdtype(arr.dtype, jnp.floating))
            lm = {"path": keys, "shape": list(arr.shape),
                  "float": is_float, "dtype": str(arr.dtype)}
            if not is_float and arr.size <= 65536:
                # non-float leaves are not in the flat master; carry their
                # values so offline consolidation restores the full tree
                lm["value"] = arr.reshape(-1).tolist()
            out.append(lm)
        return out

    def save(self, save_dir: str, tag: str):
        import json
        path = os.path.join(save_dir, tag)
        os.makedirs(path, exist_ok=True)
        rank = jax.process_index()
        np.savez(os.path.join(
            path, f"zero_param_stream_rank{rank}.npz"),
            **self.store.state_dict())
        # structure sidecar: lets zero_to_fp32 consolidate WITHOUT the
        # model (the reference's per-rank shards carry param names the
        # same way)
        store = self.store
        meta = {"homogeneous": store.homogeneous,
                "n_layers": store.n_layers,
                "stacked": self.stacked,
                "layers_key": "layers",
                "resident": self._leaf_meta(store.resident_tree())}
        if store.homogeneous:
            meta["layer"] = self._leaf_meta(
                store.layouts[0].unflatten(store.masters[0]))
        else:
            meta["layer_list"] = [
                self._leaf_meta(store.layouts[l].unflatten(store.masters[l]))
                for l in range(store.n_layers)]
        with open(os.path.join(
                path, f"zero_param_stream_rank{rank}.meta.json"), "w") as f:
            json.dump(meta, f)

    def load(self, load_dir: str, tag: str,
             load_optimizer_states: bool = True) -> bool:
        """Restore host master (+ moments/step when
        ``load_optimizer_states`` — the reference flag gates optimizer
        state only; weights always load)."""
        f = os.path.join(load_dir, tag,
                         f"zero_param_stream_rank{jax.process_index()}.npz")
        if not os.path.exists(f):
            return False
        with np.load(f) as z:
            self.store.load_state_dict(
                {k: z[k] for k in z.files},
                load_optimizer_states=load_optimizer_states)
        self.resident_dev = self._upload_resident()
        self._upload_pinned()
        self._dev.clear()
        return True
