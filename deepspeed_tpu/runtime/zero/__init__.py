"""ZeRO (reference ``deepspeed/runtime/zero/``): sharding plans, offload,
param-partitioning surface, tiling, allocator."""

from deepspeed_tpu.runtime.zero.config import (DeepSpeedZeroConfig,
                                               OffloadDeviceEnum)
from deepspeed_tpu.runtime.zero.contiguous_memory_allocator import \
    ContiguousMemoryAllocator
from deepspeed_tpu.runtime.zero.offload import (FlatLayout,
                                                HostOffloadOptimizer,
                                                OptimizerStateSwapper,
                                                PartitionedParamSwapper)
from deepspeed_tpu.runtime.zero.partition_parameters import (
    GatheredParameters, Init, shutdown_init_context)
from deepspeed_tpu.runtime.zero.stage_plan import (ZeroShardingPlan,
                                                   constrain, maybe_constrain)
from deepspeed_tpu.runtime.zero.tiling import TiledLinear, tiled_linear

__all__ = [
    "DeepSpeedZeroConfig", "OffloadDeviceEnum", "ContiguousMemoryAllocator",
    "FlatLayout", "HostOffloadOptimizer", "OptimizerStateSwapper",
    "PartitionedParamSwapper", "GatheredParameters", "Init",
    "shutdown_init_context", "ZeroShardingPlan", "constrain",
    "maybe_constrain", "TiledLinear", "tiled_linear",
]
