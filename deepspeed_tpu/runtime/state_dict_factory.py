"""Sharded state-dict loaders with TP-degree merge/split.

Parity: reference ``deepspeed/runtime/state_dict_factory.py`` —
``SDLoaderFactory`` builds a loader over a list of per-MP-rank checkpoint
files; ``MegatronSDLoader.load(mp_world_size, mp_rank)`` returns this rank's
state dict, merging N→M (concat column/row-parallel weights, version-aware
QKV interleave) when the saved degree exceeds the serving degree and
splitting when it is smaller, with optional load-time int8 quantization via
:class:`~deepspeed_tpu.runtime.weight_quantizer.WeightQuantization`.

TPU notes: tensors are host numpy (the merge/split is pure host reshaping —
the result is then device_put against the serving mesh by the caller), and
the default checkpoint reader understands ``.npz`` (numpy), ``.pt``/``.bin``
(torch, when available) and pickle files, so both Megatron-style torch
shards and our own saved shards round-trip.  Categories:

* axis-0 (column-parallel): ``mlp.dense_h_to_4h.{weight,bias}``,
  ``word_embeddings.weight``, ``final_linear.weight``
* axis-1 (row-parallel): ``attention.dense.weight``,
  ``mlp.dense_4h_to_h.weight``
* QKV: ``attention.query_key_value.*`` — version 0 stores ``[3*np*hn, h]``
  (merge must interleave the three blocks per rank), versions 1.0/2.0 store
  per-rank-contiguous ``[np*3*hn, h]`` (plain concat)
* everything else: replicated (take rank 0's copy)
"""

import json
import os
import pickle
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization
from deepspeed_tpu.utils.logging import logger

AUTO_MODULE_KEY = "auto"

AXIS0_KEYS = ("mlp.dense_h_to_4h.weight", "word_embeddings.weight",
              "mlp.dense_h_to_4h.bias", "final_linear.weight")
AXIS1_KEYS = ("attention.dense.weight", "mlp.dense_4h_to_h.weight")
QKV_KEY = "attention.query_key_value"


def _default_load(path: str) -> Dict[str, Any]:
    """Read one checkpoint shard into a {key: ndarray} dict."""
    if path.endswith(".npz"):
        with np.load(path, allow_pickle=True) as z:
            out = {}
            for k in z.files:
                v = z[k]
                out[k] = v.item() if v.dtype == object and v.ndim == 0 else v
            return out
    if path.endswith((".pt", ".bin", ".pth")):
        try:
            import torch
            sd = torch.load(path, map_location="cpu")
            return sd
        except ImportError:
            pass
    with open(path, "rb") as f:
        return pickle.load(f)


class _FileCheckpointEngine:
    """Minimal load/save seam (reference plugs TorchCheckpointEngine here)."""

    def load(self, path, map_location=None):
        return _default_load(path)

    def save(self, obj, path):
        if path.endswith(".npz"):
            flat = {k: np.asarray(v) for k, v in obj.items()
                    if not isinstance(v, dict)}
            nested = {k: v for k, v in obj.items() if isinstance(v, dict)}
            if nested:
                raise ValueError(".npz shards must be flat; use .pkl")
            np.savez(path, **flat)
        else:
            with open(path, "wb") as f:
                pickle.dump(obj, f)


class SDLoaderFactory:
    """Reference surface ``state_dict_factory.py:20``."""

    @staticmethod
    def get_sd_loader_json(json_file, checkpoint_engine=None):
        if isinstance(json_file, str):
            with open(json_file) as f:
                data = json.load(f)
        else:
            assert isinstance(json_file, dict)
            data = json_file
        sd_type = data["type"]
        if sd_type.lower() in ("bloom", "ds_model"):
            # preshard-aware engines consume the raw descriptor
            return data
        return SDLoaderFactory.get_sd_loader(
            data["checkpoints"], checkpoint_engine, sd_type,
            data.get("version"))

    @staticmethod
    def get_sd_loader(ckpt_list, checkpoint_engine=None,
                      sd_type="Megatron", version=None):
        if sd_type == "Megatron":
            return MegatronSDLoader(ckpt_list, version, checkpoint_engine)
        raise ValueError(f"checkpoint type '{sd_type}' is not supported")


class SDLoaderBase(ABC):
    """Reference ``SDLoaderBase`` (``state_dict_factory.py:49``)."""

    def __init__(self, ckpt_list: List[str], version,
                 checkpoint_engine=None):
        self.module_key = None
        self.ckpt_list = list(ckpt_list)
        self.version = version
        self.checkpoint_engine = checkpoint_engine or _FileCheckpointEngine()
        self.check_ckpt_list()

    # -- the main entry -------------------------------------------------
    def load(self, mp_world_size: int, mp_rank: int,
             module_key: Optional[str] = AUTO_MODULE_KEY,
             is_pipe_parallel: bool = False, quantize: bool = False,
             quantize_bits: int = 8, quantize_groups: int = 64,
             mlp_extra_grouping: bool = True):
        """Returns ``(load_path, sd, (all_scales, merge_count))`` for this
        rank, merging/splitting when the saved MP degree differs from
        ``mp_world_size`` (cases documented at reference ``load:58``)."""
        self.module_key = module_key
        num_ckpt = len(self.ckpt_list)
        idx = mp_rank * num_ckpt // mp_world_size

        # pipeline layer files with an explicit module key are replicated
        # across mp ranks when degrees mismatch: read shard 0
        if is_pipe_parallel and module_key is not None \
                and mp_world_size != num_ckpt:
            mp_world_size = num_ckpt
            idx = 0

        load_path = self.ckpt_list[idx]
        merge_count = 1
        all_scales = None
        if num_ckpt == mp_world_size:
            assert os.path.exists(load_path), load_path
            sd = self.checkpoint_engine.load(load_path)
            if quantize:
                quantizer = WeightQuantization(
                    mlp_extra_grouping=mlp_extra_grouping,
                    mp_size=mp_world_size)
                module, all_scales = quantizer.sd_quantize_megatron(
                    self.get_module(sd), quantize_bits, quantize_groups)
                sd = self.set_module(sd, module)
        elif num_ckpt > mp_world_size:
            sd, all_scales, merge_count = self.merge_state_dict(
                mp_world_size, mp_rank, quantize, quantize_bits,
                quantize_groups, mlp_extra_grouping)
        else:
            sd, all_scales = self.split_state_dict(
                mp_world_size, mp_rank, quantize, quantize_bits,
                quantize_groups, mlp_extra_grouping)
        return load_path, sd, (all_scales, merge_count)

    def get_merge_state_dicts(self, mp_world_size, mp_rank):
        num_ckpt = len(self.ckpt_list)
        assert num_ckpt % mp_world_size == 0, \
            "Invalid checkpoints and world size for sd merge"
        num_to_merge = num_ckpt // mp_world_size
        ckpts = self.ckpt_list[num_to_merge * mp_rank:
                               num_to_merge * (mp_rank + 1)]
        logger.info(f"mp_rank: {mp_rank}, ckpt_list: {ckpts}")
        return [self.checkpoint_engine.load(c) for c in ckpts]

    def get_split_state_dict(self, mp_world_size, mp_rank):
        num_ckpt = len(self.ckpt_list)
        assert mp_world_size % num_ckpt == 0, \
            "Invalid checkpoints and world size for sd split"
        num_to_split = mp_world_size // num_ckpt
        ckpt_index = mp_rank // num_to_split
        ckpt_offset = mp_rank % num_to_split
        sd = self.checkpoint_engine.load(self.ckpt_list[ckpt_index])
        return sd, num_to_split, ckpt_offset

    # -- module-key plumbing (reference :152-:176) ----------------------
    def _choose_module_key(self, sd):
        assert not ("module" in sd and "model" in sd), \
            "checkpoint has both 'model' and 'module' keys"
        assert "module" in sd or "model" in sd, \
            "checkpoint contains neither 'model' nor 'module' keys"
        return "module" if "module" in sd else "model"

    def get_module(self, sd):
        if self.module_key is None:
            return sd
        if self.module_key == AUTO_MODULE_KEY:
            return sd[self._choose_module_key(sd)]
        return sd[self.module_key]

    def set_module(self, sd, module):
        if self.module_key is None:
            sd = module
        elif self.module_key == AUTO_MODULE_KEY:
            sd[self._choose_module_key(sd)] = module
        else:
            sd[self.module_key] = module
        return sd

    def check_ckpt_list(self):
        assert len(self.ckpt_list) > 0
        sd = self.checkpoint_engine.load(self.ckpt_list[0])
        if "mp_world_size" in sd:
            assert len(self.ckpt_list) == int(sd["mp_world_size"]), \
                (f"checkpoint count {len(self.ckpt_list)} != saved "
                 f"mp_world_size {sd['mp_world_size']}")

    @abstractmethod
    def merge_state_dict(self, mp_world_size, mp_rank, quantize,
                         quantize_bits, groups, mlp_extra_grouping):
        ...

    @abstractmethod
    def split_state_dict(self, mp_world_size, mp_rank, quantize,
                         quantize_bits, groups, mlp_extra_grouping):
        ...

    @abstractmethod
    def sanity_check(self, ckpt_file_name):
        ...


class MegatronSDLoader(SDLoaderBase):
    """Megatron-LM shard layout (reference ``state_dict_factory.py:214``)."""

    # -- QKV layout handling (reference :243, :281) ---------------------
    def merge_query_key_value(self, param_list, ckpt_ver):
        """version 0: each shard is ``[3*np*hn, h]`` (Q-block, K-block,
        V-block per rank) — merging must concat per-projection across ranks
        then re-stack Q|K|V.  1.0/2.0 store rank-contiguous rows: concat."""
        if ckpt_ver == 0:
            assert param_list[0].shape[0] % 3 == 0
            blocks = [np.split(np.asarray(p), 3, axis=0) for p in param_list]
            return np.concatenate(
                [np.concatenate([b[i] for b in blocks], axis=0)
                 for i in range(3)], axis=0)
        if ckpt_ver in (1.0, 2.0):
            return np.concatenate([np.asarray(p) for p in param_list],
                                  axis=0)
        raise AssertionError(
            f"checkpoint version: {ckpt_ver} is not supported")

    def split_query_key_value(self, param, num_to_split, offset, ckpt_ver):
        param = np.asarray(param)
        if ckpt_ver == 0:
            assert param.shape[0] % 3 == 0
            q, k, v = np.split(param, 3, axis=0)
            assert q.shape[0] % num_to_split == 0
            return np.concatenate(
                [np.split(t, num_to_split, axis=0)[offset]
                 for t in (q, k, v)], axis=0)
        if ckpt_ver in (1.0, 2.0):
            assert param.shape[0] % num_to_split == 0
            return np.split(param, num_to_split, axis=0)[offset]
        raise AssertionError(
            f"checkpoint version: {ckpt_ver} is not supported")

    # -- merge N ckpts → this rank's wider shard ------------------------
    def merge_state_dict(self, mp_world_size, mp_rank, quantize=False,
                         quantize_bits=8, groups=64,
                         mlp_extra_grouping=True):
        self.sanity_check(self.ckpt_list[0])
        sd_list = self.get_merge_state_dicts(mp_world_size, mp_rank)
        ds_sd = dict(sd_list[0])
        client_sds = [self.get_module(sd) for sd in sd_list]
        ckpt_ver = self.get_checkpoint_version(ds_sd)
        quantizer = WeightQuantization(
            mlp_extra_grouping=mlp_extra_grouping,
            mp_size=mp_world_size) if quantize else None

        new_sd = {}
        for key in client_sds[0]:
            values = [sd[key] for sd in client_sds]
            if any(p in key for p in AXIS1_KEYS):
                if quantize:
                    values = quantizer.Quantize(values, quantize_bits,
                                                groups, key=key, merge_dim=1)
                new_sd[key] = np.concatenate(
                    [np.asarray(v) for v in values], axis=1)
            elif QKV_KEY in key:
                if quantize:
                    # quantized path plain-cats BOTH weight and bias
                    # (reference merge_state_dict) so their row layouts
                    # stay aligned even for v0 checkpoints
                    if key.endswith("weight"):
                        values = quantizer.Quantize(values, quantize_bits,
                                                    groups, key=key)
                    new_sd[key] = np.concatenate(
                        [np.asarray(v) for v in values], axis=0)
                else:
                    new_sd[key] = self.merge_query_key_value(values, ckpt_ver)
            elif any(p in key for p in AXIS0_KEYS):
                if quantize and "mlp.dense_h_to_4h.weight" in key:
                    values = quantizer.Quantize(values, quantize_bits,
                                                groups, key=key)
                new_sd[key] = np.concatenate(
                    [np.asarray(v) for v in values], axis=0)
            else:
                new_sd[key] = np.asarray(values[0])

        all_scales = quantizer.merge_scales() if quantize else None
        ds_sd = self.set_module(ds_sd, new_sd)
        return ds_sd, all_scales, len(client_sds)

    # -- split one ckpt → this rank's narrower shard --------------------
    def split_state_dict(self, mp_world_size, mp_rank, quantize=False,
                         quantize_bits=8, groups=64,
                         mlp_extra_grouping=True):
        sd, num_to_split, offset = self.get_split_state_dict(
            mp_world_size, mp_rank)
        ds_sd = dict(sd)
        client_sd = self.get_module(sd)
        ckpt_ver = self.get_checkpoint_version(ds_sd)
        quantizer = WeightQuantization(
            mlp_extra_grouping=mlp_extra_grouping,
            mp_size=mp_world_size) if quantize else None

        new_sd = {}
        for key, value in client_sd.items():
            value = np.asarray(value)
            if any(p in key for p in AXIS1_KEYS):
                assert value.shape[1] % num_to_split == 0
                if quantize:
                    value = quantizer.Quantize([value], quantize_bits,
                                               groups, key)[0]
                new_sd[key] = np.split(value, num_to_split, axis=1)[offset]
            elif QKV_KEY in key:
                if quantize and key.endswith("weight"):
                    value = quantizer.Quantize([value], quantize_bits,
                                               groups, key)[0]
                new_sd[key] = self.split_query_key_value(
                    value, num_to_split, offset, ckpt_ver)
            elif any(p in key for p in AXIS0_KEYS):
                assert value.shape[0] % num_to_split == 0
                if quantize and "mlp.dense_h_to_4h.weight" in key:
                    value = quantizer.Quantize([value], quantize_bits,
                                               groups, key)[0]
                new_sd[key] = np.split(value, num_to_split, axis=0)[offset]
            else:
                new_sd[key] = value

        all_scales = (quantizer.merge_scales_split(num_to_split)
                      if quantize else None)
        ds_sd = self.set_module(ds_sd, new_sd)
        return ds_sd, all_scales

    def sanity_check(self, ckpt_file_name):
        keys_to_check = ["attention.dense.weight",
                         "mlp.dense_4h_to_h.weight",
                         "attention.query_key_value",
                         "mlp.dense_h_to_4h.weight",
                         "mlp.dense_h_to_4h.bias"]
        sd = self.checkpoint_engine.load(ckpt_file_name)
        module = self.get_module(sd)
        for key in keys_to_check:
            assert any(key in k for k in module), \
                f"key: {key} is not found in the checkpoint {ckpt_file_name}"

    def get_checkpoint_version(self, state_dict):
        if self.version is not None:
            return self.version
        return state_dict.get("checkpoint_version", 0)
