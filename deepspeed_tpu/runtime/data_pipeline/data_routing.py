"""Random-LTD (layer token drop) — data routing.

Parity: reference ``runtime/data_pipeline/data_routing/basic_layer.py:13``
(``RandomLayerTokenDrop``: per-layer random token subset during training,
full sequence in the reserved first/last layers) + ``scheduler.py``
(``RandomLTDScheduler``: linear ramp of kept-token count) + the CUDA
``random_ltd`` ops (token_sort/gather/scatter — ours: ``ops/random_ltd.py``
jnp gather/scatter).

TPU design: a functional wrapper — ``random_ltd_layer(layer_fn)`` gathers a
random token subset, runs the layer on the short sequence, scatters results
back; XLA sees static shapes because the kept count is scheduled on the host
(one recompile per schedule milestone, amortised over many steps).
"""

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.random_ltd import (sample_token_indices, token_gather,
                                          token_scatter)


class RandomLTDScheduler:
    """Linear seqlen ramp (reference RandomLTDScheduler).

    Config keys follow the reference ``random_ltd`` section:
    ``total_layer_num``, ``random_ltd_layer_num``, ``random_ltd_layer_id``,
    ``random_ltd_schedule``: {min_value, max_value, schedule_config:
    {seq_per_step, require_steps}}.
    """

    def __init__(self, config: Dict[str, Any]):
        sched = config.get("random_ltd_schedule", {})
        self.min_value = int(sched.get("min_value", 128))
        self.max_value = int(sched.get("max_value", 1024))
        sc = sched.get("schedule_config", {})
        self.seq_per_step = int(sc.get("seq_per_step", 16))
        self.require_steps = int(sc.get("require_steps", 100))
        self.layer_ids = config.get("random_ltd_layer_id", [])
        self.current_seq = self.min_value

    def get_current_seq(self, global_step: int) -> int:
        inc = (global_step // self.require_steps) * self.seq_per_step
        self.current_seq = min(self.max_value, self.min_value + inc)
        return self.current_seq

    def state_dict(self):
        return {"current_seq": self.current_seq}

    def load_state_dict(self, sd):
        self.current_seq = sd.get("current_seq", self.min_value)


def random_ltd_layer(layer_fn: Callable, x: jnp.ndarray, rng,
                     keep_tokens: int, *args, **kwargs):
    """Run ``layer_fn`` on a random ``keep_tokens`` subset of the sequence,
    scattering the outputs back into the full-resolution residual stream
    (dropped tokens pass through unchanged)."""
    B, S = x.shape[0], x.shape[1]
    if keep_tokens >= S:
        return layer_fn(x, *args, **kwargs)
    idx = sample_token_indices(rng, S, keep_tokens, batch=B)
    short = token_gather(x, idx)
    out_short = layer_fn(short, *args, **kwargs)
    return token_scatter(x, out_short, idx)
