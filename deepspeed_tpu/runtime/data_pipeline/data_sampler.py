"""Curriculum-aware data sampler.

Parity: reference ``runtime/data_pipeline/data_sampling/data_sampler.py:33``
(``DeepSpeedDataSampler``: consults per-metric difficulty indexes built by
the data analyzer, and at each step yields the global batch drawn from the
pool of samples whose difficulty ≤ the curriculum's current threshold).
"""

from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import \
    CurriculumScheduler
from deepspeed_tpu.utils.logging import logger


class DeepSpeedDataSampler:
    """Iterates global-batch index lists.

    ``difficulties``: per-sample difficulty values (one per dataset item) for
    one metric (reference supports several; pass the composed metric).  The
    eligible pool at step t is ``difficulty <= scheduler.difficulty(t)``;
    shuffling is deterministic per epoch.
    """

    def __init__(self, total_samples: int, batch_size: int,
                 difficulties: Optional[np.ndarray] = None,
                 curriculum: Optional[CurriculumScheduler] = None,
                 seed: int = 0, drop_last: bool = True):
        self.total_samples = int(total_samples)
        self.batch_size = int(batch_size)
        self.difficulties = (np.asarray(difficulties)
                             if difficulties is not None else None)
        if self.difficulties is not None:
            assert len(self.difficulties) == total_samples
        self.curriculum = curriculum
        self.seed = seed
        self.drop_last = drop_last
        self.global_step = 0
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def state_dict(self) -> Dict:
        return {"global_step": self.global_step, "epoch": self.epoch}

    def load_state_dict(self, sd: Dict):
        self.global_step = sd.get("global_step", 0)
        self.epoch = sd.get("epoch", 0)

    # ------------------------------------------------------------------
    def _eligible(self) -> np.ndarray:
        if self.curriculum is None or self.difficulties is None:
            return np.arange(self.total_samples)
        thresh = self.curriculum.update_difficulty(self.global_step)
        pool = np.nonzero(self.difficulties <= thresh)[0]
        if pool.size < self.batch_size:
            # reference pads the pool with the easiest samples
            order = np.argsort(self.difficulties)
            pool = order[:self.batch_size]
        return pool

    def __iter__(self):
        rng = np.random.default_rng(self.seed + self.epoch)
        while True:
            pool = self._eligible()
            batch = rng.choice(pool, size=self.batch_size,
                               replace=pool.size < self.batch_size)
            self.global_step += 1
            yield batch.tolist()

    def __len__(self):
        return self.total_samples // self.batch_size
