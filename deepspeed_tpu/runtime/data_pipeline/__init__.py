"""Data-efficiency pipeline (reference ``runtime/data_pipeline/``)."""

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import \
    CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import DataAnalyzer
from deepspeed_tpu.runtime.data_pipeline.data_routing import (
    RandomLTDScheduler, random_ltd_layer)
from deepspeed_tpu.runtime.data_pipeline.data_sampler import \
    DeepSpeedDataSampler
from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder, make_builder, make_dataset)

__all__ = ["CurriculumScheduler", "DataAnalyzer", "RandomLTDScheduler",
           "random_ltd_layer", "DeepSpeedDataSampler", "MMapIndexedDataset",
           "MMapIndexedDatasetBuilder", "make_builder", "make_dataset"]
