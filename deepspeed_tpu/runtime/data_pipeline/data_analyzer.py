"""Offline data analyzer — builds difficulty indexes for curriculum sampling.

Parity: reference ``runtime/data_pipeline/data_sampling/data_analyzer.py``
(``DataAnalyzer``: map metric functions over the dataset in parallel
workers, write per-metric ``sample_to_metric`` / ``metric_to_sample``
indexed files, then ``index_to_sample_percentile_merged``).

TPU design: host-side numpy + the mmap indexed dataset; the output feeds
``DeepSpeedDataSampler`` difficulties directly.
"""

import os
from typing import Callable, Dict, List, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)
from deepspeed_tpu.utils.logging import logger


class DataAnalyzer:

    def __init__(self, dataset: Sequence, metric_names: List[str],
                 metric_functions: List[Callable], save_path: str,
                 num_workers: int = 1, worker_id: int = 0,
                 metric_types: List[str] = None):
        assert len(metric_names) == len(metric_functions)
        self.dataset = dataset
        self.metric_names = metric_names
        self.metric_functions = metric_functions
        self.metric_types = metric_types or ["single_value_per_sample"] * \
            len(metric_names)
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id

    def _prefix(self, name: str) -> str:
        return os.path.join(self.save_path, f"{name}_sample_to_metric")

    def run_map(self) -> Dict[str, np.ndarray]:
        """Compute each metric over this worker's shard and persist."""
        os.makedirs(self.save_path, exist_ok=True)
        n = len(self.dataset)
        my = range(self.worker_id, n, self.num_workers)
        out: Dict[str, np.ndarray] = {}
        for name, fn in zip(self.metric_names, self.metric_functions):
            vals = np.zeros(n, np.int64)
            for i in my:
                vals[i] = int(fn(self.dataset[i]))
            out[name] = vals
            if self.num_workers == 1:
                b = MMapIndexedDatasetBuilder(self._prefix(name),
                                              dtype=np.int64)
                b.add_item(vals)
                b.finalize()
                logger.info(f"data_analyzer: wrote {self._prefix(name)}")
        return out

    def run_reduce(self, partials: List[Dict[str, np.ndarray]]
                   ) -> Dict[str, np.ndarray]:
        """Merge worker shards (element-wise max — shards are disjoint)."""
        merged = {}
        for name in self.metric_names:
            acc = partials[0][name].copy()
            for p in partials[1:]:
                acc = np.maximum(acc, p[name])
            merged[name] = acc
            b = MMapIndexedDatasetBuilder(self._prefix(name), dtype=np.int64)
            b.add_item(acc)
            b.finalize()
        return merged

    def load_metric(self, name: str) -> np.ndarray:
        return MMapIndexedDataset(self._prefix(name))[0]
