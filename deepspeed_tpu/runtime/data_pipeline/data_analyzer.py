"""Offline data analyzer — builds difficulty indexes for curriculum sampling.

Parity: reference ``runtime/data_pipeline/data_sampling/data_analyzer.py``
(``DataAnalyzer``: map metric functions over the dataset in parallel
workers, write per-metric ``sample_to_metric`` / ``metric_to_sample``
indexed files, then merge ``index_to_sample_percentile_merged`` so the
sampler can address samples by difficulty percentile).

TPU design: host-side numpy + the mmap indexed dataset; the outputs feed
``DeepSpeedDataSampler`` difficulties directly.  Per metric the full
reference index family is written:

* ``{metric}_sample_to_metric``            — item 0: value per sample
* ``{metric}_index_to_metric``             — item 0: sorted unique values
* ``{metric}_index_to_sample``             — item i: sample ids whose value
  is ``index_to_metric[i]`` (the reference's metric_to_sample inverse)
* ``{metric}_index_to_sample_percentile_merged`` — item p (p=0..99):
  sample ids in percentile bucket p of the metric distribution

Multi-metric curricula compose via :meth:`compose_metrics` — per-metric
percentile ranks, weighted-summed into ONE difficulty array (values
0..100), which is what ``DeepSpeedDataSampler(difficulties=...)`` and a
``CurriculumScheduler`` whose difficulty runs 0..100 consume.
"""

import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)
from deepspeed_tpu.utils.logging import logger

PERCENTILE_BUCKETS = 100


class DataAnalyzer:

    def __init__(self, dataset: Sequence, metric_names: List[str],
                 metric_functions: List[Callable], save_path: str,
                 num_workers: int = 1, worker_id: int = 0,
                 metric_types: List[str] = None):
        assert len(metric_names) == len(metric_functions)
        self.dataset = dataset
        self.metric_names = metric_names
        self.metric_functions = metric_functions
        self.metric_types = metric_types or ["single_value_per_sample"] * \
            len(metric_names)
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id

    def _prefix(self, name: str, kind: str = "sample_to_metric") -> str:
        return os.path.join(self.save_path, f"{name}_{kind}")

    # ------------------------------------------------------------------
    # map / reduce over workers
    # ------------------------------------------------------------------
    def run_map(self) -> Dict[str, np.ndarray]:
        """Compute each metric over this worker's shard and persist."""
        os.makedirs(self.save_path, exist_ok=True)
        n = len(self.dataset)
        my = range(self.worker_id, n, self.num_workers)
        out: Dict[str, np.ndarray] = {}
        for name, fn in zip(self.metric_names, self.metric_functions):
            vals = np.zeros(n, np.int64)
            for i in my:
                vals[i] = int(fn(self.dataset[i]))
            out[name] = vals
            if self.num_workers == 1:
                self._write_indexes(name, vals)
        return out

    def run_reduce(self, partials: List[Dict[str, np.ndarray]]
                   ) -> Dict[str, np.ndarray]:
        """Merge worker shards (element-wise max — shards are disjoint) and
        write the full index family per metric."""
        merged = {}
        for name in self.metric_names:
            acc = partials[0][name].copy()
            for p in partials[1:]:
                acc = np.maximum(acc, p[name])
            merged[name] = acc
            self._write_indexes(name, acc)
        return merged

    # ------------------------------------------------------------------
    # index family (reference: sample_to_metric + metric_to_sample +
    # index_to_sample_percentile_merged)
    # ------------------------------------------------------------------
    def _write_indexes(self, name: str, vals: np.ndarray) -> None:
        b = MMapIndexedDatasetBuilder(self._prefix(name), dtype=np.int64)
        b.add_item(vals)
        b.finalize()

        uniq, inverse = np.unique(vals, return_inverse=True)
        b = MMapIndexedDatasetBuilder(self._prefix(name, "index_to_metric"),
                                      dtype=np.int64)
        b.add_item(uniq)
        b.finalize()

        order = np.argsort(inverse, kind="stable")
        counts = np.bincount(inverse, minlength=len(uniq))
        bounds = np.concatenate([[0], np.cumsum(counts)])
        b = MMapIndexedDatasetBuilder(self._prefix(name, "index_to_sample"),
                                      dtype=np.int64)
        for i in range(len(uniq)):
            b.add_item(order[bounds[i]:bounds[i + 1]])
        b.finalize()

        by_value = np.argsort(vals, kind="stable")
        edges = np.linspace(0, len(vals), PERCENTILE_BUCKETS + 1)
        edges = np.round(edges).astype(np.int64)
        b = MMapIndexedDatasetBuilder(
            self._prefix(name, "index_to_sample_percentile_merged"),
            dtype=np.int64)
        for p in range(PERCENTILE_BUCKETS):
            b.add_item(by_value[edges[p]:edges[p + 1]])
        b.finalize()
        logger.info(f"data_analyzer: wrote {name} index family under "
                    f"{self.save_path}")

    # ------------------------------------------------------------------
    # loaders
    # ------------------------------------------------------------------
    def load_metric(self, name: str) -> np.ndarray:
        return MMapIndexedDataset(self._prefix(name))[0]

    def load_index_to_metric(self, name: str) -> np.ndarray:
        return MMapIndexedDataset(self._prefix(name, "index_to_metric"))[0]

    def load_index_to_sample(self, name: str) -> List[np.ndarray]:
        ds = MMapIndexedDataset(self._prefix(name, "index_to_sample"))
        return [ds[i] for i in range(len(ds))]

    def load_percentile_index(self, name: str) -> List[np.ndarray]:
        ds = MMapIndexedDataset(
            self._prefix(name, "index_to_sample_percentile_merged"))
        return [ds[i] for i in range(len(ds))]

    # ------------------------------------------------------------------
    # multi-metric composition
    # ------------------------------------------------------------------
    @staticmethod
    def compose_metrics(metrics: Dict[str, np.ndarray],
                        weights: Optional[Dict[str, float]] = None
                        ) -> np.ndarray:
        """Compose several per-sample metric arrays into ONE difficulty.

        Each metric is converted to its percentile rank (0..100) so
        incommensurable scales (sequence length vs vocab rarity) mix
        sanely, then weighted-averaged.  The result plugs straight into
        ``DeepSpeedDataSampler(difficulties=...)`` with a curriculum whose
        difficulty schedule runs 0..100 — the role of the reference's
        percentile-merged multi-metric index.
        """
        assert metrics, "need at least one metric"
        names = sorted(metrics)
        weights = weights or {}
        n = len(next(iter(metrics.values())))
        total_w = sum(float(weights.get(nm, 1.0)) for nm in names)
        out = np.zeros(n, np.float64)
        for nm in names:
            vals = np.asarray(metrics[nm])
            assert len(vals) == n, f"metric {nm} length {len(vals)} != {n}"
            # average rank over ties: equal metric values must compose to
            # equal difficulties (a curriculum threshold may not split
            # samples that are indistinguishable under the metric)
            sorted_vals = np.sort(vals, kind="stable")
            lo = np.searchsorted(sorted_vals, vals, side="left")
            hi = np.searchsorted(sorted_vals, vals, side="right")
            ranks = (lo + hi - 1) / 2.0
            pct = ranks * (PERCENTILE_BUCKETS / max(1, n - 1))
            out += float(weights.get(nm, 1.0)) * pct
        return np.rint(out / total_w).astype(np.int64)
