"""Memory-mapped indexed dataset (Megatron format).

Parity: reference ``runtime/data_pipeline/data_sampling/indexed_dataset.py``
(``MMapIndexedDataset`` + builder: a ``.bin`` of concatenated sample arrays
and a ``.idx`` with dtype/sizes/pointers), used by the data analyzer and
sampler for out-of-core metric/index storage.

TPU note: host-side numpy mmap — identical on any platform; the arrays feed
``device_put`` directly.
"""

import os
import struct
from typing import List, Optional

import numpy as np

_MAGIC = b"DSTPUIDX"
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
           9: np.uint32, 10: np.uint64}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Append numpy arrays; ``finalize`` writes the index."""

    def __init__(self, out_file: str, dtype=np.int32):
        self._path = out_file
        self._dtype = np.dtype(dtype)
        self._bin = open(data_file_path(out_file), "wb")
        self._sizes: List[int] = []

    def add_item(self, array) -> None:
        arr = np.asarray(array, self._dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def add_batch(self, arrays) -> None:
        for a in arrays:
            self.add_item(a)

    def finalize(self) -> None:
        self._bin.close()
        sizes = np.asarray(self._sizes, np.int64)
        pointers = np.concatenate([[0], np.cumsum(sizes[:-1])]) * \
            self._dtype.itemsize
        with open(index_file_path(self._path), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<B", _CODES[self._dtype]))
            f.write(struct.pack("<q", len(sizes)))
            f.write(sizes.tobytes())
            f.write(pointers.astype(np.int64).tobytes())


class MMapIndexedDataset:
    """Random access over the builder's output without loading the .bin."""

    def __init__(self, prefix: str):
        with open(index_file_path(prefix), "rb") as f:
            assert f.read(8) == _MAGIC, f"bad index file {prefix}.idx"
            (code,) = struct.unpack("<B", f.read(1))
            (n,) = struct.unpack("<q", f.read(8))
            self.dtype = np.dtype(_DTYPES[code])
            self.sizes = np.frombuffer(f.read(8 * n), np.int64)
            self.pointers = np.frombuffer(f.read(8 * n), np.int64)
        self._data = np.memmap(data_file_path(prefix), mode="r",
                               dtype=self.dtype)

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, idx: int) -> np.ndarray:
        off = self.pointers[idx] // self.dtype.itemsize
        return np.asarray(self._data[off:off + self.sizes[idx]])

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None):
        full = self[idx]
        return full[offset:offset + length if length else None]

    @property
    def supports_prefetch(self) -> bool:
        return False


def make_builder(out_file, impl="mmap", dtype=np.int32):
    assert impl in ("mmap", "cached", "lazy"), impl
    return MMapIndexedDatasetBuilder(out_file, dtype=dtype)


def make_dataset(prefix, impl="mmap", skip_warmup=True):
    assert os.path.exists(index_file_path(prefix)), \
        f"no index at {prefix}.idx"
    return MMapIndexedDataset(prefix)
