"""Curriculum learning scheduler.

Parity: reference ``runtime/data_pipeline/curriculum_scheduler.py``
(``CurriculumScheduler``: difficulty schedules ``fixed_linear``,
``fixed_root``, ``fixed_discrete``, ``custom``) used for seqlen curriculum
(legacy ``curriculum_learning`` config) and by the data sampler for
difficulty-based example selection.
"""

import math
from typing import Any, Callable, Dict, Optional

CURRICULUM_LEARNING_MIN_DIFFICULTY = "min_difficulty"
CURRICULUM_LEARNING_MAX_DIFFICULTY = "max_difficulty"
CURRICULUM_LEARNING_SCHEDULE_TYPE = "schedule_type"
CURRICULUM_LEARNING_SCHEDULE_CONFIG = "schedule_config"

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:

    def __init__(self, config: Dict[str, Any]):
        self.state = dict(config)
        self.schedule_type = config.get(CURRICULUM_LEARNING_SCHEDULE_TYPE,
                                        FIXED_LINEAR)
        self.min_difficulty = int(config.get(
            CURRICULUM_LEARNING_MIN_DIFFICULTY, 8))
        self.max_difficulty = int(config.get(
            CURRICULUM_LEARNING_MAX_DIFFICULTY, 1024))
        self.sc = dict(config.get(CURRICULUM_LEARNING_SCHEDULE_CONFIG, {}))
        self.custom_fn: Optional[Callable[[int], int]] = None
        self.current_difficulty = self.min_difficulty
        self.first_step = True
        if self.schedule_type == FIXED_DISCRETE:
            assert "difficulty" in self.sc and "max_step" in self.sc, \
                "fixed_discrete needs schedule_config.difficulty + max_step"
            assert len(self.sc["difficulty"]) == len(self.sc["max_step"]) + 1
        elif self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            assert "total_curriculum_step" in self.sc, \
                f"{self.schedule_type} needs schedule_config.total_curriculum_step"
            self.sc.setdefault("difficulty_step", 8)
            if self.schedule_type == FIXED_ROOT:
                self.sc.setdefault("root_degree", 2)

    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        self.custom_fn = fn
        self.schedule_type = CUSTOM

    # ------------------------------------------------------------------
    def get_difficulty(self, global_steps: int) -> int:
        if self.schedule_type == CUSTOM:
            assert self.custom_fn is not None
            return int(self.custom_fn(global_steps))
        if self.schedule_type == FIXED_DISCRETE:
            for diff, until in zip(self.sc["difficulty"], self.sc["max_step"]):
                if global_steps <= until:
                    return int(diff)
            return int(self.sc["difficulty"][-1])
        total = self.sc["total_curriculum_step"]
        frac = min(1.0, max(0.0, global_steps / total))
        if self.schedule_type == FIXED_ROOT:
            frac = frac ** (1.0 / self.sc["root_degree"])
        diff = self.min_difficulty + frac * (self.max_difficulty -
                                             self.min_difficulty)
        step = self.sc["difficulty_step"]
        diff = int(diff // step * step)
        return max(self.min_difficulty, min(self.max_difficulty, diff))

    def update_difficulty(self, global_steps: int) -> int:
        self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty
