"""Typed config base + helpers.

Parity: reference ``runtime/config_utils.py`` (``DeepSpeedConfigModel``
pydantic base + ``get_scalar_param``).  We use plain dataclass-style classes
with dict ingestion, unknown-key warnings, and deprecated-key aliasing —
the same ergonomics without a pydantic dependency.
"""

import copy
from typing import Any, Dict

from deepspeed_tpu.utils.logging import logger


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


class DeepSpeedConfigModel:
    """Declarative config: subclasses define class attributes as defaults
    (optionally with ``_fields_`` metadata for deprecated aliases); instances
    are built from a dict, warning on unknown keys."""

    # map of deprecated key -> new key
    _deprecated_ = {}

    def __init__(self, param_dict: Dict[str, Any] = None, strict: bool = False):
        param_dict = copy.copy(param_dict) or {}
        # resolve deprecated aliases
        for old, new in self._deprecated_.items():
            if old in param_dict:
                logger.warning(f"Config key '{old}' is deprecated; use '{new}'")
                param_dict.setdefault(new, param_dict.pop(old))

        cls = type(self)
        known = {k for k in dir(cls)
                 if not k.startswith("_")
                 and not isinstance(getattr(cls, k, None), property)
                 and not callable(getattr(cls, k))}
        for k in known:
            default = getattr(cls, k)
            setattr(self, k, copy.deepcopy(default))
        for k, v in param_dict.items():
            if k in known:
                setattr(self, k, v)
            else:
                msg = f"Unknown config key '{k}' for {cls.__name__}"
                if strict:
                    raise ValueError(msg)
                logger.warning(msg)
        self._validate()

    def _validate(self):
        pass

    def to_dict(self):
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}

    def __repr__(self):
        return f"{type(self).__name__}({self.to_dict()})"


class ScientificNotationEncoder:
    pass
