"""Runtime utility surface.

Parity: reference ``deepspeed/runtime/utils.py`` — the grab-bag of helpers
user code and subsystems import from ``deepspeed.runtime.utils``: norms and
clipping, overflow checks, partitioning helpers, ``PartitionedTensor``
(flat 1-D partitioning with CSR-style metadata, used by the pipeline's
partition-activations path), seeds/paths, and memory reports.

TPU notes: norms/clipping are pure jnp over pytrees or tensor lists (inside
jit they fuse; the reference's multi-pass ``torch.norm`` loops dissolve);
``CheckOverflow`` wraps the engine's jit-friendly ``has_inf_or_nan``;
``PartitionedTensor`` keeps the reference's rowptr metadata encoding so
serialized partitions interop, but reassembly is host-side concatenation
(under SPMD the full array already exists as one ``jax.Array``; this class
serves explicit per-rank protocols like pipeline activation shipping).
"""

import os
from typing import Any, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.pipe.module import (partition_balanced,
                                               partition_uniform)
from deepspeed_tpu.utils.memory import memory_status, see_memory_usage
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "DummyOptim", "noop_decorator", "ensure_directory_exists",
    "set_random_seed", "CheckOverflow", "get_global_norm",
    "clip_grad_norm_", "get_grad_norm", "get_weight_norm",
    "partition_uniform", "partition_balanced", "PartitionedTensor",
    "memory_status", "see_memory_usage", "call_to_str",
    "get_only_unique_item", "clip_gradients",
    "get_global_norm_of_tensors", "clip_tensors_by_global_norm",
    "align_dense_tensors", "empty_cache",
]


class DummyOptim:
    """Placeholder when only grad accumulation/clipping is wanted
    (reference ``utils.py:35``)."""

    def __init__(self, params):
        self.param_groups = [{"params": params}]


def noop_decorator(func):
    return func


def ensure_directory_exists(filename: str):
    """mkdir -p the parent directory of ``filename`` (reference :49)."""
    os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)


def set_random_seed(seed: int):
    """Seed python/numpy; returns a jax PRNG key (JAX has no global seed —
    the key is the TPU-native analogue of the reference's torch.manual_seed)."""
    import random
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.key(seed)


def empty_cache():
    """Reference :815 empties the CUDA caching allocator; XLA's allocator
    has no user-visible cache — provided for API compatibility."""


# ---------------------------------------------------------------------------
# norms / clipping / overflow
# ---------------------------------------------------------------------------

def _leaves(parameters) -> List[jnp.ndarray]:
    if isinstance(parameters, (list, tuple)):
        out = []
        for p in parameters:
            out.extend(jax.tree_util.tree_leaves(p))
        return out
    return jax.tree_util.tree_leaves(parameters)


def get_global_norm(norm_list: Sequence[float]):
    """sqrt(sum of squared norms) (reference :316)."""
    return float(np.sqrt(sum(float(n) ** 2 for n in norm_list)))


def get_global_norm_of_tensors(input_tensors, norm_type=2, mpu=None):
    """Global norm over a tensor list / pytree (reference :895).  Inside
    jit this is one fused reduction."""
    leaves = _leaves(input_tensors)
    if norm_type == float("inf") or norm_type == "inf":
        return jnp.max(jnp.stack(
            [jnp.max(jnp.abs(t.astype(jnp.float32))) for t in leaves]))
    norms = jnp.stack([jnp.sum(jnp.abs(t.astype(jnp.float32)) ** norm_type)
                       for t in leaves])
    return jnp.sum(norms) ** (1.0 / norm_type)


def get_grad_norm(parameters, norm_type=2, mpu=None):
    """Reference :395 — identical math over a grads tree/list."""
    return get_global_norm_of_tensors(parameters, norm_type=norm_type)


def get_weight_norm(parameters, norm_type=2, mpu=None):
    """Reference :499."""
    return get_global_norm_of_tensors(parameters, norm_type=norm_type)


def clip_tensors_by_global_norm(input_tensors, max_norm=1.0,
                                global_norm=None, mpu=None, eps=1e-6):
    """Scale the whole tree so its global norm is <= max_norm
    (reference :939).  Returns (clipped, global_norm)."""
    if global_norm is None:
        global_norm = get_global_norm_of_tensors(input_tensors)
    coef = jnp.minimum(1.0, max_norm / (global_norm + eps))

    def scale(t):
        return (t.astype(jnp.float32) * coef).astype(t.dtype)
    return jax.tree_util.tree_map(scale, input_tensors), global_norm


def clip_gradients(parameters, max_norm=1.0, global_grad_norm=None,
                   mpu=None, eps=1e-6):
    """Reference :876 — clip a grads tree by its global norm; returns
    (clipped_grads, global_norm)."""
    return clip_tensors_by_global_norm(parameters, max_norm=max_norm,
                                       global_norm=global_grad_norm, eps=eps)


def clip_grad_norm_(parameters, max_norm, norm_type=2, mpu=None):
    """Reference :325.  Functional (no in-place mutation in JAX): returns
    (clipped_parameters, total_norm)."""
    total_norm = get_global_norm_of_tensors(parameters, norm_type=norm_type)
    clipped, _ = clip_tensors_by_global_norm(parameters, max_norm=max_norm,
                                             global_norm=total_norm)
    return clipped, total_norm


class CheckOverflow:
    """Inf/NaN scan over grad trees (reference ``utils.py:170``).  The
    reference's per-rank CPU-sum + allreduce protocol dissolves: under SPMD
    every process computes the same global reduction inside jit."""

    def __init__(self, param_groups=None, mpu=None, zero_reduce_scatter=False,
                 deepspeed=None):
        self.mpu = mpu
        self.params = param_groups

    @staticmethod
    def has_overflow_serial(grads) -> jnp.ndarray:
        from deepspeed_tpu.runtime.loss_scaler import has_inf_or_nan
        return has_inf_or_nan(grads)

    def has_overflow(self, grads) -> bool:
        return bool(jax.device_get(self.has_overflow_serial(grads)))

    check = has_overflow


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

def call_to_str(base, *args, **kwargs) -> str:
    """'base(arg1, key=value)' (reference :845 — pipeline instruction repr)."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(a) for a in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v!r}" for k, v in kwargs.items())
    return name + ")"


def get_only_unique_item(items):
    """Reference :867."""
    item_set = set(items)
    if len(item_set) != 1:
        raise RuntimeError(f"expected there to be only one unique element "
                           f"in {items}")
    return next(iter(item_set))


def align_dense_tensors(tensor_list, alignment):
    """Pad the last tensor so the flat total is a multiple of ``alignment``
    (reference :965 — flat-buffer alignment for comm efficiency)."""
    total = sum(int(np.size(t)) for t in tensor_list)
    remainder = total % alignment
    if remainder == 0:
        return list(tensor_list)
    pad = alignment - remainder
    dtype = jnp.asarray(tensor_list[-1]).dtype
    # reference appends a standalone pad tensor, leaving the originals'
    # shapes untouched (callers unflatten per-tensor after comm)
    return list(tensor_list) + [jnp.zeros((pad,), dtype)]


class PartitionedTensor:
    """Flat 1-D partition of a tensor over ``num_parts`` ranks with the
    reference's CSR-rowptr metadata (reference ``utils.py:657``; used by
    the pipeline's partition-activations protocol).

    ``group`` is ``(num_parts, rank)`` — explicit instead of a torch
    process group; under SPMD the caller knows its coordinates from the
    mesh."""

    def __init__(self, tensor=None, group=(1, 0), partition_meta=None):
        self.num_parts, self.rank = int(group[0]), int(group[1])
        if tensor is not None:
            self.orig_size = list(np.shape(tensor))
            self.local_data, self.partition = self._partition_tensor(tensor)

    @classmethod
    def from_meta(cls, meta, local_part, group):
        meta = [int(m) for m in np.asarray(meta).tolist()]
        obj = cls(tensor=None, group=group)
        ndims = meta[0]
        obj.orig_size = meta[1:1 + ndims]
        rest = meta[1 + ndims:]
        assert obj.num_parts == rest[0], "partition count mismatch"
        assert obj.rank == rest[1], "rank mismatch"
        obj.partition = rest[2:]
        obj.local_data = jnp.ravel(jnp.asarray(local_part))
        return obj

    def _partition_tensor(self, tensor):
        flat = jnp.ravel(jnp.asarray(tensor))
        partition = partition_uniform(num_items=flat.size,
                                      num_parts=self.num_parts)
        start = partition[self.rank]
        length = partition[self.rank + 1] - start
        return flat[start:start + length], list(partition)

    def full(self, parts: Optional[List[Any]] = None):
        """Reassemble from every rank's shard.  ``parts``: all ranks'
        ``data()`` in rank order (the reference all-gathers over its torch
        group; the caller supplies the gathered shards here — or nothing
        for num_parts == 1)."""
        if parts is None:
            assert self.num_parts == 1, \
                "full() needs every rank's shard (pass parts=[...])"
            parts = [self.local_data]
        flat = jnp.concatenate([jnp.ravel(jnp.asarray(p)) for p in parts])
        assert flat.size == int(np.prod(self.orig_size)), \
            f"shards total {flat.size} != {self.orig_size}"
        return flat.reshape(self.orig_size)

    def to_meta(self):
        meta = [len(self.orig_size)] + list(self.orig_size)
        meta += [self.num_parts, self.rank] + list(self.partition)
        return np.asarray(meta, np.int64)

    def data(self):
        return self.local_data

    def local_size(self):
        return self.local_data.shape

    def full_size(self):
        return self.orig_size
