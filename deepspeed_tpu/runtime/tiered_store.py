"""Tiered-memory engine: one HBM ⇄ pinned-host ⇄ NVMe store.

Parity: reference ZeRO-Infinity (arXiv:2104.07857) keeps params,
gradients and optimizer states on whichever tier fits — HBM for the
working set, host memory behind pinned buffers, NVMe behind the aio
swapper — with an "infinity offload engine" moving tensors along the
tier chain ahead of use.  The reference grew three disjoint
implementations of that idea (``partitioned_param_coordinator``,
``partitioned_optimizer_swapper``, ZeRO-Inference weight streaming);
this module is the single store the TPU port's three beyond-HBM
mechanisms share:

* ``runtime/zero/offload.py`` — ``OptimizerStateSwapper`` swaps
  per-sub-group moments through the store's NVMe tier,
* ``runtime/zero/param_stream.py`` — ``HostParamStore`` allocates its
  host/NVMe planes through the store,
* ``inference/engine.py`` — int8/bf16 weight streaming is a read-only
  placement over the store (closing the old int8+NVMe hole: quantized
  weights live on NVMe with their scale sidecars listed in the
  manifest).

Three design points:

1. **Placement** is a per-tensor :class:`PlacementPolicy` (resident /
   host / nvme) with persistence-threshold pinning à la
   ``param_persistence_threshold``: tensors at or below the threshold
   stay device-resident no matter the default tier.
2. **Quantized tiers are first class**: a host or NVMe entry may store
   its payload as the PR 15 blockwise codec
   (:class:`deepspeed_tpu.comm.quantize.QuantizedPayload` — int8 blocks
   + fp32 per-block scales).  On NVMe the codes and the scales are
   separate files (the scale *sidecar*), both listed in the manifest.
3. **NVMe durability** follows the checkpoint protocol
   (``runtime/resilience.py``): every payload file is written
   tmp → fsync → atomic rename, and :meth:`TieredStore.commit` seals
   the directory with the self-digested ``ds_manifest.json`` +
   ``.ds_commit`` marker, so ``resilience.validate_tag`` /
   ``scripts/ds_ckpt_fsck.py`` classify a tier directory exactly like a
   checkpoint tag (torn file → ``partial``, missing marker →
   ``no_marker``).

Accounting rides the telemetry plane as the FROZEN ``tier/*`` gauge
vocabulary below (mirrored byte-for-byte in
``scripts/check_telemetry_schema.py`` with a lockstep tier-1 test).
"""

import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle
from deepspeed_tpu.runtime import resilience
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "TIERS", "TIER_GAUGES", "PlacementPolicy", "TieredStore",
    "PrefetchEngine", "STORE_SUBDIR",
]

#: The tier chain, fastest first.
TIERS = ("hbm", "host", "nvme")

#: Subdirectory under ``nvme_dir`` holding one tag dir per store.
STORE_SUBDIR = "ds_tiered"

# FROZEN gauge vocabulary of the tiered-memory plane — mirrored
# byte-for-byte in scripts/check_telemetry_schema.py (TIER_GAUGES) with
# a lockstep tier-1 test.  Bytes per tier, prefetch hit/miss counters,
# eviction/writeback counts, and achieved bandwidth per transfer path.
TIER_GAUGES = (
    "tier/hbm_bytes",
    "tier/host_bytes",
    "tier/nvme_bytes",
    "tier/prefetch_hits",
    "tier/prefetch_misses",
    "tier/evictions",
    "tier/writebacks",
    "tier/h2d_gbps",
    "tier/d2h_gbps",
    "tier/nvme_read_gbps",
    "tier/nvme_write_gbps",
    "tier/quant_bytes_saved",
)

_TMP_SUFFIX = ".tmp"


def _np(x) -> np.ndarray:
    return x if isinstance(x, np.ndarray) else np.asarray(x)


def _sanitize(key: str) -> str:
    """File-name-safe entry key (mirrors PartitionedParamSwapper)."""
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in key)


# ----------------------------------------------------------------------
# blockwise int8 payload codec (host-side twin of comm/quantize's
# jnp codec — identical math: symmetric per-block absmax, zero blocks
# get scale 1.0 so dequantize is exact)
# ----------------------------------------------------------------------

_INT8_MAX = 127.0


def _quantize_np(x: np.ndarray, block_size: int):
    """Flat fp32 → (codes int8 [nblocks, block], scales fp32
    [nblocks, 1]); numel padded to the block size with zeros."""
    flat = np.asarray(x, np.float32).reshape(-1)
    pad = (-flat.size) % block_size
    if pad:
        flat = np.pad(flat, (0, pad))
    g = flat.reshape(-1, block_size)
    scale = np.max(np.abs(g), axis=1, keepdims=True) / _INT8_MAX
    scale = np.where(scale == 0, 1.0, scale).astype(np.float32)
    codes = np.clip(np.rint(g / scale), -128, 127).astype(np.int8)
    return codes, scale


def _dequantize_np(codes: np.ndarray, scales: np.ndarray, shape, dtype,
                   numel: int) -> np.ndarray:
    out = (codes.astype(np.float32) * scales).reshape(-1)[:numel]
    return out.reshape(shape).astype(dtype)


def _make_payload(x: np.ndarray, block_size: int):
    """Wrap one host tensor as the PR 15 :class:`QuantizedPayload`
    (single-leaf).  Import deferred: the fp32-only store never pulls the
    comm codec in."""
    from deepspeed_tpu.comm.quantize import QuantizedLeaf, QuantizedPayload
    codes, scales = _quantize_np(x, block_size)
    leaf = QuantizedLeaf(codes=codes, scales=scales, shape=tuple(x.shape),
                         dtype=np.dtype(x.dtype), numel=int(x.size))
    return QuantizedPayload(
        leaves=[leaf], block_size=block_size,
        wire_bytes=codes.nbytes + scales.nbytes,
        raw_bytes=int(x.size) * np.dtype(x.dtype).itemsize)


# ----------------------------------------------------------------------
# placement policy
# ----------------------------------------------------------------------


@dataclass
class PlacementPolicy:
    """Per-tensor tier choice (reference: ``offload_param`` /
    ``offload_optimizer`` device knobs + ``param_persistence_threshold``
    pinning, unified).

    ``default_tier`` is where a tensor goes unless (a) its numel is at
    or below ``persistence_threshold`` — then it stays ``hbm``-resident
    (persistence pinning), or (b) an entry in ``overrides`` matches a
    prefix of its name.  ``quantize`` stores float payloads of host /
    nvme entries as the PR 15 blockwise-int8 codec with fp32 scale
    sidecars; ``read_only`` marks an inference-style placement — the
    store rejects writebacks so a served model can never dirty its
    weights."""

    default_tier: str = "host"
    persistence_threshold: int = 0
    overrides: Dict[str, str] = field(default_factory=dict)
    quantize: bool = False
    quant_block: int = 256
    read_only: bool = False

    def __post_init__(self):
        if self.default_tier not in TIERS:
            raise ValueError(
                f"placement_policy: unknown tier {self.default_tier!r} "
                f"(choose from {TIERS})")
        for k, t in self.overrides.items():
            if t not in TIERS:
                raise ValueError(
                    f"placement_policy override {k!r}: unknown tier {t!r}")

    @staticmethod
    def from_config(mc) -> "PlacementPolicy":
        """Build from a parsed ``memory`` config block (or a raw dict)."""
        get = (mc.get if isinstance(mc, dict)
               else lambda k, d=None: getattr(mc, k, d))
        return PlacementPolicy(
            default_tier=get("placement_policy", "host") or "host",
            persistence_threshold=int(
                get("persistence_threshold", 0) or 0),
            overrides=dict(get("overrides", None) or {}),
            quantize=bool(get("quantize_tiers", False)),
            quant_block=int(get("quant_block", 256) or 256),
            read_only=bool(get("read_only", False)))

    def place(self, name: str, numel: int) -> str:
        for prefix, tier in self.overrides.items():
            if name.startswith(prefix):
                return tier
        if numel <= self.persistence_threshold:
            return "hbm"
        return self.default_tier

    def wants_quant(self, value: np.ndarray, tier: str) -> bool:
        return (self.quantize and tier in ("host", "nvme")
                and np.issubdtype(_np(value).dtype, np.floating))


# ----------------------------------------------------------------------
# entries
# ----------------------------------------------------------------------


@dataclass
class _Leaf:
    """One array inside an entry (entries are shallow pytrees: a bare
    array, or a dict of arrays — e.g. the inference engine's
    ``{"qv","qs","qz"}`` groupwise-int8 triple)."""
    sub: str                      # "" for a bare array
    shape: Tuple[int, ...]
    dtype: np.dtype
    nbytes: int
    host: Optional[np.ndarray] = None      # host-tier payload / cache
    payload: Any = None                    # QuantizedPayload (int8 tier)
    files: Tuple[str, ...] = ()            # nvme file names (rel)
    block: int = 0                         # codec block size (quantized)


@dataclass
class _Entry:
    key: str
    tier: str
    quantized: bool
    leaves: List[_Leaf]
    mapped: bool = False          # nvme plane handed out as np.memmap
    pinned_slot: bool = False     # currently staged on device
    device: Any = None

    @property
    def nbytes(self) -> int:
        return sum(lf.nbytes for lf in self.leaves)


# ----------------------------------------------------------------------
# the store
# ----------------------------------------------------------------------


class TieredStore:
    """Named tensor groups across HBM ⇄ pinned host ⇄ NVMe files.

    ``put``/``get`` move whole entries; ``prefetch``/``fetch`` are the
    async path clients drive from their layer schedule (see
    :class:`PrefetchEngine` for the schedule-driven wrapper);
    ``read_into``/``write_from`` are the zero-copy seam the optimizer
    swapper's ring buffers use; ``alloc_plane`` hands param-stream its
    host or NVMe-mapped planes.  All movement lands in the frozen
    ``tier/*`` gauges."""

    def __init__(self, name: str = "store", nvme_dir: Optional[str] = None,
                 policy: Optional[PlacementPolicy] = None,
                 host_budget_bytes: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 aio_config: Optional[dict] = None, fsync: bool = False,
                 nvme_subdir: Optional[str] = STORE_SUBDIR):
        self.name = str(name)
        self.policy = policy or PlacementPolicy()
        self.host_budget_bytes = host_budget_bytes
        self.hbm_budget_bytes = hbm_budget_bytes
        self.fsync = fsync
        self._dir = None
        if nvme_dir is not None:
            # default layout: <nvme_dir>/ds_tiered/<name>/ — one tag dir
            # per store, fsck-scannable at the ds_tiered root.  Clients
            # with a pre-existing flat layout (the optimizer swap dir)
            # pass nvme_subdir=None to use nvme_dir as the tag dir itself.
            self._dir = (os.path.join(str(nvme_dir), nvme_subdir, self.name)
                         if nvme_subdir else str(nvme_dir))
            os.makedirs(self._dir, exist_ok=True)
        self._entries: Dict[str, _Entry] = {}
        self._reader = AsyncIOHandle(**(aio_config or {}))
        self._writer = AsyncIOHandle(**(aio_config or {}))
        self._pending: Dict[str, bool] = {}   # key -> reads in flight
        self._lru: List[str] = []             # hbm staging order
        self._sealed = False                  # manifest current?
        # cumulative transfer accounting (bandwidth gauges)
        self._xfer = {k: [0, 0.0] for k in
                      ("h2d", "d2h", "nvme_read", "nvme_write")}
        self._counts = {"prefetch_hits": 0, "prefetch_misses": 0,
                        "evictions": 0, "writebacks": 0,
                        "quant_bytes_saved": 0}

    # -- construction from the ``memory`` config block -----------------
    @staticmethod
    def from_config(mc, name: str = "store",
                    aio_config: Optional[dict] = None) -> "TieredStore":
        get = (mc.get if isinstance(mc, dict)
               else lambda k, d=None: getattr(mc, k, d))
        nvme_dir = get("nvme_dir", None)
        hb = get("host_budget_bytes", None)
        db = get("hbm_budget_bytes", None)
        return TieredStore(
            name=name, nvme_dir=nvme_dir,
            policy=PlacementPolicy.from_config(mc),
            host_budget_bytes=int(hb) if hb else None,
            hbm_budget_bytes=int(db) if db else None,
            aio_config=aio_config)

    # -- paths ---------------------------------------------------------
    @property
    def nvme_path(self) -> Optional[str]:
        return self._dir

    def _require_dir(self) -> str:
        if self._dir is None:
            raise ValueError(
                f"tiered store {self.name!r}: an NVMe-tier entry needs "
                f"memory.nvme_dir (no directory configured)")
        return self._dir

    def path_for(self, key: str, sub: str = "") -> str:
        fn = _sanitize(key if not sub else f"{key}.{sub}")
        return os.path.join(self._require_dir(), f"{fn}.bin")

    # -- durable file write (tmp → fsync → atomic rename) --------------
    def _write_file(self, path: str, arr: np.ndarray):
        tmp = f"{path}{_TMP_SUFFIX}.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(np.ascontiguousarray(arr).tobytes())
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        self._sealed = False

    def _read_file(self, path: str, shape, dtype) -> np.ndarray:
        buf = np.empty(int(np.prod(shape, dtype=np.int64)), np.dtype(dtype))
        self._reader.sync_pread(buf, path)
        return buf.reshape(shape)

    # -- transfer accounting -------------------------------------------
    def _account(self, path_kind: str, nbytes: int, dur_s: float):
        rec = self._xfer[path_kind]
        rec[0] += int(nbytes)
        rec[1] += max(dur_s, 1e-9)

    def _tel(self):
        from deepspeed_tpu.monitor.telemetry import get_telemetry
        return get_telemetry()

    def publish_gauges(self):
        """Emit the frozen ``tier/*`` gauge set from current occupancy
        and cumulative transfer counters (telemetry-disabled = no-op)."""
        tel = self._tel()
        if not tel.enabled:
            return
        occ = self.tier_bytes()
        for tier in TIERS:
            tel.gauge(f"tier/{tier}_bytes", occ[tier])
        for k, v in self._counts.items():
            tel.gauge(f"tier/{k}", v)
        for kind, gauge in (("h2d", "tier/h2d_gbps"),
                            ("d2h", "tier/d2h_gbps"),
                            ("nvme_read", "tier/nvme_read_gbps"),
                            ("nvme_write", "tier/nvme_write_gbps")):
            nbytes, secs = self._xfer[kind]
            if nbytes:
                tel.gauge(gauge, round(nbytes / secs / 1e9, 6))

    # -- client accounting seam ----------------------------------------
    def note_prefetch(self, hit: bool, n: int = 1):
        """Book ``n`` prefetch hits/misses observed by a client that runs
        its own staging (param-stream's ``_ensure`` window)."""
        key = "prefetch_hits" if hit else "prefetch_misses"
        self._counts[key] += int(n)

    def note_transfer(self, kind: str, nbytes: int, dur_s: float):
        """Book a transfer a client performed itself: ``kind`` is one of
        h2d / d2h / nvme_read / nvme_write."""
        self._account(kind, nbytes, dur_s)

    def note_eviction(self, n: int = 1):
        self._counts["evictions"] += int(n)

    def note_writeback(self, n: int = 1):
        self._counts["writebacks"] += int(n)

    def tier_bytes(self) -> Dict[str, int]:
        """Current occupancy per tier.  A staged (device-resident) copy
        of a host/nvme entry counts toward ``hbm`` as well — that is the
        working set the budget bounds."""
        occ = {t: 0 for t in TIERS}
        for e in self._entries.values():
            occ[e.tier] += e.nbytes
            if e.tier != "hbm" and e.device is not None:
                occ["hbm"] += e.nbytes
        return occ

    def stats(self) -> Dict[str, Any]:
        out = {f"{t}_bytes": b for t, b in self.tier_bytes().items()}
        out.update(self._counts)
        for kind in self._xfer:
            nbytes, secs = self._xfer[kind]
            out[f"{kind}_gbps"] = (round(nbytes / secs / 1e9, 6)
                                   if nbytes else 0.0)
        hits = self._counts["prefetch_hits"]
        misses = self._counts["prefetch_misses"]
        out["prefetch_hit_rate"] = (round(hits / (hits + misses), 4)
                                    if hits + misses else None)
        out["entries"] = len(self._entries)
        return out

    # -- registration / placement --------------------------------------
    def _leaves_of(self, key: str, value) -> List[Tuple[str, np.ndarray]]:
        if isinstance(value, dict):
            return [(str(k), _np(v)) for k, v in sorted(value.items())]
        return [("", _np(value))]

    def put(self, key: str, value, tier: Optional[str] = None) -> "_Entry":
        """Place ``value`` (array or flat dict of arrays) under ``key``.
        Tier comes from the policy unless forced; host/nvme float
        payloads quantize to the PR 15 codec when the policy says so.
        NVMe files are written durably (tmp + atomic rename)."""
        pairs = self._leaves_of(key, value)
        numel = sum(int(a.size) for _, a in pairs)
        tier = tier or self.policy.place(key, numel)
        if tier not in TIERS:
            raise ValueError(f"unknown tier {tier!r}")
        leaves: List[_Leaf] = []
        quantized = False
        for sub, arr in pairs:
            lf = _Leaf(sub=sub, shape=tuple(arr.shape),
                       dtype=np.dtype(arr.dtype), nbytes=arr.nbytes)
            if tier == "hbm":
                lf.host = arr        # device staging happens on fetch
            elif self.policy.wants_quant(arr, tier):
                quantized = True
                payload = _make_payload(arr, self.policy.quant_block)
                lf.payload = payload
                lf.block = self.policy.quant_block
                lf.nbytes = payload.wire_bytes
                self._counts["quant_bytes_saved"] += payload.bytes_saved
                if tier == "nvme":
                    leaf0 = payload.leaves[0]
                    files = []
                    for tag, part in (("q", leaf0.codes),
                                      ("scales", leaf0.scales)):
                        p = self.path_for(key, f"{sub}.{tag}" if sub
                                          else tag)
                        t0 = time.perf_counter()
                        self._write_file(p, part)
                        self._account("nvme_write", part.nbytes,
                                      time.perf_counter() - t0)
                        files.append(os.path.basename(p))
                    lf.files = tuple(files)
                    lf.payload = None      # codes live on disk only
                    # keep codec geometry for the read path
                    lf.host = None
                else:
                    lf.host = None
            elif tier == "host":
                lf.host = arr.copy() if arr.base is not None else arr
            else:  # nvme, raw
                p = self.path_for(key, sub)
                t0 = time.perf_counter()
                self._write_file(p, arr)
                self._account("nvme_write", arr.nbytes,
                              time.perf_counter() - t0)
                lf.files = (os.path.basename(p),)
            leaves.append(lf)
        entry = _Entry(key=key, tier=tier, quantized=quantized,
                       leaves=leaves)
        self._entries[key] = entry
        self._enforce_host_budget()
        return entry

    def put_group(self, prefix: str, tree: Dict[str, Any],
                  tier: Optional[str] = None) -> List[str]:
        """Place every item of ``tree`` as ``{prefix}.{name}``; returns
        the keys (the group's schedule order)."""
        keys = []
        for k in sorted(tree):
            keys.append(f"{prefix}.{k}")
            self.put(keys[-1], tree[k], tier=tier)
        return keys

    def register_plane(self, key: str, shape, dtype,
                       nvme_dir: Optional[str] = None) -> np.ndarray:
        """Allocate a mutable backing plane (param-stream masters /
        mirrors / grad accumulators): plain host RAM, or an NVMe-backed
        ``np.memmap`` when ``nvme_dir`` is given (the OS page cache
        plays the pinned-buffer role).  The plane is catalogued so the
        tier gauges see its footprint, but the caller owns the memory —
        identical semantics to the old ``param_stream._alloc``."""
        dtype = np.dtype(dtype)
        if nvme_dir is None:
            arr = np.zeros(shape, dtype)
            tier, mapped = "host", False
        else:
            os.makedirs(nvme_dir, exist_ok=True)
            path = os.path.join(nvme_dir, f"{_sanitize(key)}.mm")
            arr = np.lib.format.open_memmap(path, mode="w+", dtype=dtype,
                                            shape=shape)
            tier, mapped = "nvme", True
        lf = _Leaf(sub="", shape=tuple(arr.shape), dtype=dtype,
                   nbytes=arr.nbytes, host=arr)
        self._entries[key] = _Entry(key=key, tier=tier, quantized=False,
                                    leaves=[lf], mapped=mapped)
        return arr

    def register_swap(self, key: str, numel: int,
                      dtype=np.float32) -> str:
        """Catalog an NVMe swap slot the optimizer swapper streams
        through its own pinned ring buffers (``read_into`` /
        ``write_from``).  Returns the file path."""
        dtype = np.dtype(dtype)
        lf = _Leaf(sub="", shape=(int(numel),), dtype=dtype,
                   nbytes=int(numel) * dtype.itemsize,
                   files=(os.path.basename(self.path_for(key)),))
        self._entries[key] = _Entry(key=key, tier="nvme", quantized=False,
                                    leaves=[lf])
        return self.path_for(key)

    # -- swapper seam: zero-copy reads/writes on caller buffers --------
    def read_into(self, key: str, view: np.ndarray,
                  async_op: bool = False):
        """NVMe → caller's (pinned) host buffer.  Async reads complete
        at :meth:`reader_wait`."""
        path = self.path_for(key)
        t0 = time.perf_counter()
        if async_op:
            self._reader.async_pread(view, path)
        else:
            self._reader.sync_pread(view, path)
        self._account("nvme_read", view.nbytes, time.perf_counter() - t0)

    def write_from(self, key: str, view: np.ndarray, sync: bool = True):
        """Caller's host buffer → NVMe swap file (hot path: in-place
        rewrite of a same-size slot, no tmp+rename — durability is
        restored by the next :meth:`commit`)."""
        if self.policy.read_only:
            raise ValueError(
                f"tiered store {self.name!r} is read-only "
                f"(inference placement); writeback of {key!r} rejected")
        path = self.path_for(key)
        t0 = time.perf_counter()
        if sync:
            self._writer.sync_pwrite(view, path)
        else:
            self._writer.async_pwrite(view, path)
        self._account("nvme_write", view.nbytes, time.perf_counter() - t0)
        self._counts["writebacks"] += 1
        self._sealed = False

    def reader_wait(self):
        return self._reader.wait()

    def writer_wait(self):
        return self._writer.wait()

    def alloc_pinned(self, numel: int, dtype=np.float32) -> np.ndarray:
        return self._reader.new_cpu_locked_tensor(int(numel), dtype)

    # -- prefetch / fetch ----------------------------------------------
    def prefetch(self, keys):
        """Queue async NVMe reads for ``keys`` (str or list) so the
        transfer overlaps upstream compute.  Host/hbm entries need no
        staging read; they count as prefetched so a later fetch books a
        hit either way."""
        if isinstance(keys, str):
            keys = [keys]
        for key in keys:
            e = self._entries[key]
            if key in self._pending or e.pinned_slot:
                continue
            if e.tier == "nvme" and not e.mapped:
                for lf in e.leaves:
                    if lf.host is not None or lf.payload is not None:
                        continue
                    self._issue_leaf_read(key, lf)
            self._pending[key] = True

    def _issue_leaf_read(self, key: str, lf: _Leaf):
        d = self._require_dir()
        t0 = time.perf_counter()
        if len(lf.files) == 2:       # quantized: codes + scale sidecar
            numel = int(np.prod(lf.shape, dtype=np.int64))
            block = lf.block or self.policy.quant_block
            nblocks = -(-numel // block)
            codes = np.empty((nblocks, block), np.int8)
            scales = np.empty((nblocks, 1), np.float32)
            self._reader.async_pread(codes, os.path.join(d, lf.files[0]))
            self._reader.async_pread(scales, os.path.join(d, lf.files[1]))
            lf.host = None
            lf._inflight = (codes, scales)     # type: ignore[attr-defined]
            nbytes = codes.nbytes + scales.nbytes
        else:
            buf = np.empty(int(np.prod(lf.shape, dtype=np.int64)),
                           lf.dtype)
            self._reader.async_pread(buf, os.path.join(d, lf.files[0]))
            lf._inflight = (buf,)              # type: ignore[attr-defined]
            nbytes = buf.nbytes
        self._account("nvme_read", nbytes, time.perf_counter() - t0)

    def _land_leaf(self, lf: _Leaf):
        """Turn a completed read (or resident payload) into the host
        array for one leaf."""
        inflight = getattr(lf, "_inflight", None)
        if inflight is not None:
            if len(inflight) == 2:
                codes, scales = inflight
                lf.host = _dequantize_np(
                    codes, scales, lf.shape, lf.dtype,
                    int(np.prod(lf.shape, dtype=np.int64)))
            else:
                lf.host = inflight[0].reshape(lf.shape)
            lf._inflight = None                # type: ignore[attr-defined]
        elif lf.host is None and lf.payload is not None:
            leaf0 = lf.payload.leaves[0]
            lf.host = _dequantize_np(
                leaf0.codes, leaf0.scales, lf.shape, lf.dtype,
                int(np.prod(lf.shape, dtype=np.int64)))
        return lf.host

    def fetch(self, key: str, device: bool = False):
        """Entry payload as host array(s) (or staged to device with an
        async ``device_put``).  A fetch that was not prefetched is a
        demand miss: the read happens synchronously, on the critical
        path."""
        e = self._entries[key]
        if key in self._pending or e.tier in ("hbm", "host") or e.mapped \
                or all(lf.host is not None or lf.payload is not None
                       for lf in e.leaves):
            self._counts["prefetch_hits"] += 1
            if self._pending.pop(key, None) and e.tier == "nvme" \
                    and not e.mapped:
                self._reader.wait()
        else:
            self._counts["prefetch_misses"] += 1
            if e.tier == "nvme" and not e.mapped:
                for lf in e.leaves:
                    if lf.host is None and lf.payload is None:
                        self._issue_leaf_read(key, lf)
                self._reader.wait()
        for lf in e.leaves:
            self._land_leaf(lf)
        value = self._value_of(e)
        if device:
            return self._stage(e, value)
        return value

    def fetch_group(self, keys: List[str], device: bool = False):
        """Fetch several entries as one dict keyed by the suffix after
        the last '.' (the layer-working-set shape clients dispatch)."""
        out = {}
        for key in keys:
            out[key.rsplit(".", 1)[-1]] = self.fetch(key, device=device)
        return out

    def _value_of(self, e: _Entry):
        if len(e.leaves) == 1 and e.leaves[0].sub == "":
            return e.leaves[0].host
        return {lf.sub: lf.host for lf in e.leaves}

    def _stage(self, e: _Entry, value):
        import jax
        t0 = time.perf_counter()
        e.device = jax.device_put(value)
        self._account("h2d", e.nbytes, time.perf_counter() - t0)
        e.pinned_slot = True
        if e.key in self._lru:
            self._lru.remove(e.key)
        self._lru.append(e.key)
        self._enforce_hbm_budget()
        return e.device

    # -- eviction / writeback ------------------------------------------
    def evict(self, key: str, writeback: Optional[np.ndarray] = None):
        """Drop the staged/host copy of ``key``.  ``writeback`` (host
        array) persists mutated data down-tier first; NVMe staging
        caches are discarded (the files stay authoritative)."""
        e = self._entries.get(key)
        if e is None:
            return
        if writeback is not None:
            if self.policy.read_only:
                raise ValueError(
                    f"tiered store {self.name!r} is read-only; "
                    f"writeback of {key!r} rejected")
            arr = _np(writeback)
            if e.device is not None:
                # the mutated data came down from the device copy
                self._account("d2h", arr.nbytes, 1e-9)
            if e.tier == "nvme" and not e.mapped:
                t0 = time.perf_counter()
                self._write_file(self.path_for(key, e.leaves[0].sub),
                                 arr)
                self._account("nvme_write", arr.nbytes,
                              time.perf_counter() - t0)
            else:
                e.leaves[0].host = arr
            self._counts["writebacks"] += 1
        if e.device is not None:
            e.device = None
            e.pinned_slot = False
        if e.tier == "nvme" and not e.mapped and not e.quantized:
            for lf in e.leaves:
                lf.host = None         # files stay authoritative
        if e.tier == "nvme" and e.quantized:
            for lf in e.leaves:
                if lf.files:
                    lf.host = None
        if key in self._lru:
            self._lru.remove(key)
        self._pending.pop(key, None)
        self._counts["evictions"] += 1

    def _enforce_hbm_budget(self):
        if not self.hbm_budget_bytes:
            return
        while self.tier_bytes()["hbm"] > self.hbm_budget_bytes and \
                len(self._lru) > 1:
            self.evict(self._lru[0])

    def _enforce_host_budget(self):
        """Spill oldest host-tier entries to NVMe when the pinned-host
        budget is exceeded (requires ``nvme_dir``; without one the
        budget is advisory and only the gauges show the overshoot)."""
        if not self.host_budget_bytes or self._dir is None or \
                getattr(self, "_spilling", False):
            return
        over = self.tier_bytes()["host"] - self.host_budget_bytes
        if over <= 0:
            return
        self._spilling = True
        for key in list(self._entries):
            e = self._entries[key]
            if e.tier != "host" or e.mapped:
                continue
            value = self._value_of(e)
            self._entries.pop(key)
            self.put(key, value, tier="nvme")
            self._counts["evictions"] += 1
            over -= e.nbytes
            if over <= 0:
                break
        self._spilling = False

    # -- durability: manifest + marker over the NVMe tier ---------------
    def commit(self, global_step: int = 0) -> Optional[str]:
        """Seal the store's NVMe directory with the checkpoint
        protocol's self-digested manifest + commit marker, in place:
        after this, ``resilience.validate_tag(store.nvme_path)`` (and
        ``ds_ckpt_fsck`` pointed at the parent) classify the tier like a
        checkpoint tag — a truncated payload file is ``partial``, a torn
        manifest ``bad_manifest``.  Returns the directory (None when no
        NVMe tier is configured)."""
        if self._dir is None:
            return None
        self._writer.wait()
        entries = []
        for e in self._entries.values():
            if e.tier != "nvme":
                continue
            entries.append({
                "key": e.key, "quantized": bool(e.quantized),
                "mapped": bool(e.mapped),
                "leaves": [{"sub": lf.sub, "shape": list(lf.shape),
                            "dtype": str(lf.dtype),
                            "files": list(lf.files)}
                           for lf in e.leaves]})
        manifest = resilience.build_manifest(
            {}, tag=self.name, global_step=global_step,
            extra={"tiered_store": {
                "name": self.name,
                "policy": {"default_tier": self.policy.default_tier,
                           "quantize": self.policy.quantize,
                           "quant_block": self.policy.quant_block,
                           "read_only": self.policy.read_only},
                "entries": entries}})
        manifest["files"] = resilience._payload_files(self._dir)
        manifest["digest"] = resilience._manifest_digest(manifest)
        import json
        resilience.atomic_write_text(
            os.path.join(self._dir, resilience.MANIFEST_NAME),
            json.dumps(manifest), fsync=self.fsync)
        resilience.atomic_write_text(
            os.path.join(self._dir, resilience.COMMIT_MARKER),
            manifest["digest"], fsync=self.fsync)
        if self.fsync:
            resilience.fsync_tree(self._dir)
        self._sealed = True
        return self._dir

    def validate(self) -> Tuple[str, Optional[dict]]:
        """fsck the NVMe tier: ``(status, manifest)`` straight from
        ``resilience.validate_tag``."""
        if self._dir is None:
            return resilience.MISSING, None
        return resilience.validate_tag(self._dir)

    # -- teardown ------------------------------------------------------
    def wait_all(self):
        self._reader.wait()
        self._writer.wait()

    def release(self):
        """Drain I/O and drop staged device/host caches; NVMe files (and
        the manifest, once committed) stay — the durable tier survives
        the process."""
        self.wait_all()
        self._pending.clear()
        for e in self._entries.values():
            e.device = None
            e.pinned_slot = False
            if e.tier == "nvme" and not e.mapped:
                for lf in e.leaves:
                    if lf.files:
                        lf.host = None
        self._lru.clear()

    def destroy(self):
        """Release + delete every NVMe file this store owns (including
        manifest/marker and any stray tmp files)."""
        self.release()
        if self._dir is None:
            return
        import shutil
        shutil.rmtree(self._dir, ignore_errors=True)
        self._entries = {k: e for k, e in self._entries.items()
                         if e.tier != "nvme"}

    def keys(self) -> List[str]:
        return list(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)


# ----------------------------------------------------------------------
# schedule-driven prefetch
# ----------------------------------------------------------------------


class PrefetchEngine:
    """Double-buffered prefetch over a layer schedule (the overlap idiom
    ``param_stream._ensure`` and ``OptimizerStateSwapper`` already use):
    accessing schedule position *i* issues async reads for the next
    ``depth`` positions, so NVMe/host → device transfers for layer
    *i+1* run while layer *i* computes.  An access off the schedule (or
    before its prefetch was issued) falls back to a demand read and
    books a ``tier/prefetch_misses``."""

    def __init__(self, store: TieredStore, schedule: List[List[str]],
                 depth: int = 1):
        self.store = store
        self.schedule = [list(g) for g in schedule]
        self.depth = max(1, int(depth))
        self._issued = set()

    def reset(self):
        self._issued.clear()

    def access(self, idx: int, device: bool = False):
        """Working set for schedule position ``idx``; prefetches the
        window behind it before returning."""
        group = self.schedule[idx]
        for ahead in range(1, self.depth + 1):
            j = idx + ahead
            if j < len(self.schedule) and j not in self._issued:
                self.store.prefetch(self.schedule[j])
                self._issued.add(j)
        out = self.store.fetch_group(group, device=device)
        self._issued.discard(idx)
        for j in list(self._issued):
            if j <= idx:
                self._issued.discard(j)
        return out
