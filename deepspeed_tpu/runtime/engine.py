"""DeepSpeedEngine — the training engine.

Parity: reference ``runtime/engine.py:189`` (``DeepSpeedEngine``:
``forward:1780``, ``backward:1931``, ``step:2142``, ``_take_model_step:2074``,
``_configure_optimizer:1260``, ``save_checkpoint:3084``, ``load_checkpoint:2724``).

TPU-first redesign
------------------
The reference engine is an imperative coordinator: it wraps ``nn.Module``,
installs gradient hooks, manages buckets/streams, and mutates optimizer state
in place.  Here the whole training step — forward, backward, gradient
accumulation (``lax.scan``), ZeRO collectives, loss-scale automaton, optimizer
update — is ONE jitted SPMD program over the device mesh.  ZeRO placement is
declared by ``ZeroShardingPlan`` and the XLA partitioner materialises the
same all-gather/reduce-scatter schedule the reference hand-codes.

The user-visible API keeps DeepSpeed shape:

    engine, tx, dataloader, lr_sched = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params, config=cfg)
    loss = engine(batch)          # forward (computes grads too — functional)
    engine.backward(loss)         # accumulates
    engine.step()                 # applies at gradient-accumulation boundary

or the fused fast path:  ``loss = engine.train_batch(data_iter)``.

The model contract is functional: ``model`` is a callable
``loss_fn(params, batch, rng) -> scalar loss`` (or an object with a
``.loss`` method of the same signature, e.g. our model zoo classes).
"""

import contextlib
import os
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm as dist
from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.comm.quantize import CommQuantizer
from deepspeed_tpu.monitor.monitor import MonitorMaster
from deepspeed_tpu.monitor.telemetry import (MetricsDrain, StepStallWatchdog,
                                             get_telemetry)
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import FSDP_AXIS, build_mesh
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.loss_scaler import (HostLossScale, LossScaleState,
                                               dynamic_loss_scale_state,
                                               has_inf_or_nan,
                                               static_loss_scale_state,
                                               update_scale)
from deepspeed_tpu.runtime.lr_schedules import (LRScheduler, build_schedule,
                                                one_cycle_mom)
from deepspeed_tpu.runtime.optimizers import build_optimizer
from deepspeed_tpu.runtime.resilience import (CheckpointTransaction,
                                              CheckpointCorruptError,
                                              DivergenceError,
                                              DivergenceSentinel,
                                              FaultInjector,
                                              PreemptionHandler, RetryPolicy,
                                              TrainingPreempted, COMMITTED,
                                              LEGACY, atomic_write_text,
                                              build_manifest, gc_tags,
                                              poison_tree, retry_io,
                                              scan_tags, validate_tag,
                                              verify_restored)
from deepspeed_tpu.runtime.zero.stage_plan import (OverlapContext,
                                                   ZeroShardingPlan,
                                                   constrain,
                                                   device_put_global,
                                                   overlap_scope,
                                                   plan_reduce_buckets)
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (BACKWARD_GLOBAL_TIMER,
                                       FORWARD_GLOBAL_TIMER,
                                       STEP_GLOBAL_TIMER,
                                       SynchronizedWallClockTimer,
                                       ThroughputTimer)

MEMORY_OPT_ALLREDUCE_SIZE = 500_000_000


@struct.dataclass
class TrainState:
    """The entire mutable training state as one pytree, so a step is a pure
    ``state -> state`` function (the reference spreads this across engine,
    optimizer and scaler objects)."""
    params: Any              # fp32 master params (sharded per plan)
    opt_state: Any           # optax state (sharded per plan)
    loss_scale: LossScaleState
    global_step: jnp.ndarray     # i32
    skipped_steps: jnp.ndarray   # i32
    rng: jax.Array


@struct.dataclass
class StepMetrics:
    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    lr: jnp.ndarray
    loss_scale: jnp.ndarray
    overflow: jnp.ndarray


def _global_norm_f32(grads) -> jnp.ndarray:
    """``optax.global_norm`` with the square-sum accumulated in fp32 —
    bf16 grad trees (data_types.grad_accum_dtype) would otherwise sum
    millions of squares at 8 mantissa bits.  XLA fuses the cast into the
    reduction; nothing materializes."""
    return optax.global_norm(jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32), grads))


def moq_anneal_step(state: "TrainState") -> jnp.ndarray:
    """The MoQ anneal clock: the *successful*-step counter.  The reference
    Quantizer only advances qsteps/ratio on non-overflow steps; every
    quantizer.transform call site (train, eval, pipeline) must use this one
    definition or their quantization bits desynchronize."""
    return state.global_step - state.skipped_steps


def _batch_token_count(batch):
    """Tokens per global batch: the size of the first integer leaf (token
    ids).  Dense/regression batches have no integer leaf — returns None and
    throughput telemetry falls back to samples/s."""
    for leaf in jax.tree_util.tree_leaves(batch):
        if jnp.issubdtype(leaf.dtype, jnp.integer):
            return int(np.prod(leaf.shape))
    return None


class DeepSpeedEngine:

    def __init__(self,
                 model: Callable,
                 config: DeepSpeedConfig,
                 params: Any = None,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 lr_scheduler=None,
                 mesh=None,
                 tp_rules=None,
                 dont_change_device=False,
                 collate_fn=None,
                 training_data=None):
        self.module = model
        self.loss_fn = self._resolve_loss_fn(model)
        self._config = config
        self.accelerator = get_accelerator()

        dist.init_distributed()
        dist.configure(config)

        # ---- mesh / topology -----------------------------------------
        if mesh is None:
            mesh = groups.initialize_mesh(config.mesh_config)
        else:
            groups.initialize_mesh(mesh=mesh)
        self.mesh = mesh

        # ---- precision ----------------------------------------------
        if config.bfloat16_enabled:
            self.compute_dtype = jnp.bfloat16
        elif config.fp16_enabled:
            self.compute_dtype = jnp.float16
        else:
            self.compute_dtype = jnp.float32
        # grad tree / GAS-carry dtype (reference data_types.grad_accum_dtype,
        # runtime/config.py:943).  bf16 halves grad HBM; norms and the Adam
        # math still run fp32 (optimizers._scale_by_adam_dtyped upcasts).
        self.grad_accum_dtype = jnp.dtype(
            config.grad_accum_dtype or "float32")

        # ---- ZeRO plan ----------------------------------------------
        # auto-TP: a model that ships its own sharding rules (the whole
        # model zoo does) gets them applied without the caller plumbing
        # them through — the reference's module_inject auto-TP behaviour
        if tp_rules is None and hasattr(model, "tp_rules"):
            tp_rules = model.tp_rules()
        zc = config.zero_config
        self.zero_stage = zc.stage
        self.plan = ZeroShardingPlan(
            mesh, stage=zc.stage, tp_rules=tp_rules,
            param_persistence_threshold=(zc.param_persistence_threshold
                                         if zc.stage >= 3 else 0),
            offload_optimizer=zc.offload_optimizer_device != "none",
            offload_param=zc.offload_param_device != "none")

        # explicit comm/compute overlap (zero_optimization.overlap):
        # stage-3 forward gather pipeline (layer_scan, installed around
        # step tracing by _overlap_scope) + bucketed grad reduce-scatter
        # (_reduce_grads).  Disabled configs route through the exact
        # serial code — bit-for-bit the seed step.
        ov = getattr(zc, "overlap", None)
        self._overlap_cfg = ov
        self._overlap_enabled = bool(ov is not None and ov.enabled)
        self._overlap_ctx = None
        if self._overlap_enabled and zc.stage >= 3:
            self._overlap_ctx = OverlapContext(
                gather_prefetch_depth=ov.gather_prefetch_depth,
                param_persistence_threshold=(
                    self.plan.param_persistence_threshold),
                spec_fn=self.plan._tp_spec_for,
                on_gather=self._census_param_gather)
        self._rs_buckets = 0

        # ---- optimizer ----------------------------------------------
        self.client_optimizer = optimizer
        self.optimizer_name_ = (config.optimizer_config.type.lower()
                                if config.optimizer_config and config.optimizer_config.type
                                else None)
        self.tx, self._base_lr, self._schedule_fn = self._configure_optimizer(
            optimizer, lr_scheduler)
        self.lr_scheduler = (lr_scheduler if not callable(self._schedule_fn) or
                             isinstance(lr_scheduler, LRScheduler) else None)
        if self.lr_scheduler is None and self._schedule_fn is not None:
            self.lr_scheduler = LRScheduler(self._schedule_fn)

        # ---- state init / placement ---------------------------------
        if params is None:
            raise ValueError("model_parameters (a params pytree) is required")
        self.state = self._init_state(params)

        # ---- host-side bookkeeping ----------------------------------
        self.micro_steps = 0
        self.global_steps = int(self.state.global_step)
        self.skipped_steps = 0
        self.gradient_accumulation_steps_ = config.gradient_accumulation_steps
        self._cached = None  # (loss, grads, overflow) from forward
        self._accum_grads = None
        self._accum_count = 0
        self._step_applied = False
        self._global_grad_norm = 0.0

        # activation checkpointing knobs (reference _configure_checkpointing)
        from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
        checkpointing.configure(deepspeed_config=config)

        # curriculum seqlen (reference engine.py:1820-1826) + PLD (:1646)
        self.curriculum_scheduler_ = None
        cl_cfg = config.curriculum_learning_config
        if cl_cfg.get("enabled", False):
            from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler \
                import CurriculumScheduler
            self.curriculum_scheduler_ = CurriculumScheduler(cl_cfg)
        self.progressive_layer_drop = None
        pld_cfg = config.progressive_layer_drop_config
        if pld_cfg.get("enabled", False):
            from deepspeed_tpu.runtime.progressive_layer_drop import \
                ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=pld_cfg.get("theta", 0.5),
                gamma=pld_cfg.get("gamma", 0.001))

        # compression (reference engine.py:1401 compression_scheduler hookup)
        self._compression = None
        self.compression_scheduler = None
        # MoQ: step-time annealed weight quantization (reference
        # engine.py:1319 _configure_quantization + :1799 quantize call)
        self.quantizer = None
        if config.compression_config:
            from deepspeed_tpu.compression import (CompressionScheduler,
                                                   init_compression)
            from deepspeed_tpu.runtime.quantize import \
                build_quantizer_from_config
            self.quantizer = build_quantizer_from_config(
                config.compression_config)
            if self.quantizer is not None:
                self.quantizer.attach(self.state.params,
                                      self.quantizer.groups_cfg or None)
            spec = init_compression(model, config,
                                    tp_rules=self.plan.tp_rules,
                                    mesh=self.mesh)
            if self.quantizer is not None:
                # MoQ owns weight quantization: drop it from the in-forward
                # compression path so weights aren't quantized twice
                from deepspeed_tpu.compression.config import \
                    WEIGHT_QUANTIZATION
                spec.groups = [g for g in spec.groups
                               if g.method != WEIGHT_QUANTIZATION]
            if spec.config.enabled and spec.groups:
                self._compression = spec
                self.compression_scheduler = CompressionScheduler(spec)

        # async step pipeline (config "async_pipeline"): prefetched input
        # feed + deferred metric readback.  When on, nothing in the steady
        # hot loop may block on the device — the throughput timer trusts
        # host wall-clock instead of issuing a per-step barrier.
        ap = config.async_pipeline_config
        self._async_enabled = bool(ap.enabled)
        self._prefetcher = None       # engine-owned DevicePrefetchIterator
        self._prefetch_source = None  # the caller iterator it wraps
        self._default_iter = None     # persistent no-arg train_batch iter
        self._host_lr_cache = None    # (step, float lr)
        fc = config.fp16_config
        if config.fp16_enabled and config.dynamic_loss_scale:
            self._host_ls = HostLossScale(
                config.initial_dynamic_scale, dynamic=True,
                scale_window=fc.loss_scale_window,
                min_scale=fc.min_loss_scale, hysteresis=fc.hysteresis)
        else:
            self._host_ls = HostLossScale(
                config.loss_scale if config.fp16_enabled else 1.0,
                dynamic=False)

        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=config.train_batch_size,
            steps_per_output=config.steps_per_print,
            sync=not self._async_enabled)
        # unified telemetry spine (monitor/telemetry.py): configure the
        # process-global sink BEFORE MonitorMaster so its JSONL fourth
        # writer attaches to the same stream
        tc = config.telemetry_config
        self.telemetry = get_telemetry().configure(tc)
        self._tel_enabled = self.telemetry.enabled
        # quantized-collective wire codec (comm/quantize.py, the
        # "comm.quantization" block): policy for the ZeRO grad reduction;
        # world-size and per-leaf gating happen at trace time
        self.comm_quant = CommQuantizer.from_config(
            getattr(config, "comm_quantization", None))
        # deferred metric readback: device scalars queue here; readback is
        # one batched device_get per sync_interval (or a drainer thread)
        self._metrics_drain = None
        if self._tel_enabled:
            self._metrics_drain = MetricsDrain(
                self._drain_emit,
                sync_interval=ap.sync_interval if self._async_enabled else 1,
                use_thread=self._async_enabled and ap.drain_thread)
        # profiling plane (monitor/profiling.py): compile tracing + HBM
        # attribution + live roofline; None unless telemetry.profiling.enabled
        self._profiling = self.telemetry.profiling
        self._watchdog = None
        if self._tel_enabled and tc.stall_watchdog:
            # distributed telemetry: the watchdog also runs the cross-rank
            # straggler sweep over the shard aggregator (rank 0 owns one)
            self._watchdog = StepStallWatchdog(
                self.telemetry, stall_factor=tc.stall_factor,
                poll_interval_secs=tc.stall_poll_secs,
                min_stall_secs=tc.stall_min_secs,
                cluster=self.telemetry.cluster,
                compile_watcher=(self._profiling.compiles
                                 if self._profiling is not None else None),
            ).start()
        self._last_batch_tokens = None
        # live MFU: analytic per-step model flops (set once the flops
        # profiler has run) / measured step time / device-peak ceiling
        self._analytic_step_flops = None
        self._analytic_step_bytes = None
        self._mfu_peak_flops = None
        # fault-tolerance layer (config "resilience", runtime/resilience.py):
        # durable checkpoint transactions + retry policy are always wired
        # (rc.enabled gates the durable protocol); preemption handler and
        # divergence sentinel are opt-in.  The fault injector is explicit
        # plumbing — engine-owned, handed to the prefetch worker and the
        # checkpoint paths — never process-global, so tests stay isolated.
        rc = config.resilience_config
        self._resilience = rc
        self._injector = FaultInjector.from_config(rc.fault_injection)
        self._retry_policy = RetryPolicy.from_config(rc)
        self._last_good_ckpt = None   # (dir, tag) of last committed/loaded
        self._preempt = None
        if rc.preemption_handler:
            self._preempt = PreemptionHandler(
                telemetry=self.telemetry).install()
        self._sentinel = None
        if rc.divergence_sentinel:
            self._sentinel = DivergenceSentinel(
                max_consecutive_skips=rc.max_consecutive_skips,
                interval=rc.sentinel_interval,
                action=rc.on_divergence,
                telemetry=self.telemetry)
        # resolve the process checkpoint engine from config (sync orbax vs
        # async Nebula-style) — save/load then use whatever is current, so
        # set_checkpoint_engine() overrides still stick
        from deepspeed_tpu.runtime.checkpoint_engine import \
            get_checkpoint_engine
        get_checkpoint_engine(config)
        self.monitor = MonitorMaster(config.monitor_config)
        if self._tel_enabled:
            self.telemetry.emit(
                "meta", "engine/init",
                attrs={"zero_stage": self.zero_stage,
                       "dtype": self.compute_dtype.__name__,
                       "mesh": {k: int(v) for k, v in self.mesh.shape.items()},
                       "micro_batch": config.train_micro_batch_size_per_gpu,
                       "gas": config.gradient_accumulation_steps,
                       "train_batch": config.train_batch_size})

        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(
                training_data, collate_fn=collate_fn)

        # compiled-step caches are keyed on gas: a later call with a
        # different gas must not silently reuse a closure over a stale one
        self._compiled_train_step = {}
        self._compiled_offload_grad = {}
        self._compiled_fwd_bwd = None
        self._compiled_apply = None
        self._batch_ndim = None

        log_dist(
            f"DeepSpeedEngine ready: zero_stage={self.zero_stage} "
            f"dtype={self.compute_dtype.__name__} mesh={dict(self.mesh.shape)} "
            f"micro_batch={config.train_micro_batch_size_per_gpu} "
            f"gas={config.gradient_accumulation_steps} "
            f"train_batch={config.train_batch_size}", ranks=[0])

    # ------------------------------------------------------------------
    # setup helpers
    # ------------------------------------------------------------------
    @staticmethod
    def _resolve_loss_fn(model):
        if hasattr(model, "loss") and callable(model.loss):
            return model.loss
        if callable(model):
            return model
        raise TypeError(
            "model must be callable loss_fn(params, batch, rng) or expose "
            "a .loss method")

    def _configure_optimizer(self, client_optimizer, client_scheduler):
        """Parity: reference ``_configure_optimizer:1260`` /
        ``_configure_basic_optimizer:1321`` — config-named optimizer takes
        precedence; a client optax transform is used as-is."""
        cfg = self._config
        schedule_fn = None
        base_lr = 0.0
        if cfg.scheduler_config and cfg.scheduler_config.type:
            schedule_fn = build_schedule(cfg.scheduler_config.type,
                                         cfg.scheduler_config.params)
        elif isinstance(client_scheduler, LRScheduler):
            schedule_fn = client_scheduler.schedule_fn
        elif callable(client_scheduler):
            schedule_fn = client_scheduler

        config_opt_name = (cfg.optimizer_config.type
                           if cfg.optimizer_config else None)
        if config_opt_name:
            opt_params = dict(cfg.optimizer_config.params)
            base_lr = opt_params.get("lr", 1e-3)
            if schedule_fn is not None:
                opt_params["lr"] = schedule_fn
            # 1Cycle momentum cycling (reference OneCycle cycles optimizer
            # momentum inversely to lr) — adam-family only
            if (cfg.scheduler_config and cfg.scheduler_config.type ==
                    "OneCycle" and config_opt_name.lower() in
                    ("adam", "adamw", "fusedadam", "cpuadam")):
                mom_fn = one_cycle_mom(cfg.scheduler_config.params)
                if mom_fn is not None:
                    opt_params["_b1_schedule"] = mom_fn
            try:
                tx = build_optimizer(config_opt_name, opt_params)
            except ValueError:
                if client_optimizer is None:
                    raise
                logger.warning(
                    f"optimizer '{config_opt_name}' is not built in; using "
                    "the client-supplied optax transform instead")
                tx = client_optimizer
        elif client_optimizer is not None:
            tx = client_optimizer
            if schedule_fn is not None and cfg.scheduler_config:
                logger.warning("scheduler config ignored: client optimizer "
                               "owns its learning rate")
        else:
            # reference requires an optimizer for training; default AdamW so
            # inference-ish uses of the engine still construct
            tx = optax.adamw(1e-3)
            base_lr = 1e-3

        if self._config.gradient_clipping and self._config.gradient_clipping > 0:
            clip = float(self._config.gradient_clipping)

            def clip_f32(updates, state, params=None):
                del params
                norm = _global_norm_f32(updates)   # fp32 even for bf16 grads
                coef = jnp.minimum(1.0, clip / (norm + 1e-6))
                return jax.tree_util.tree_map(
                    lambda g: (g * coef.astype(g.dtype)), updates), state
            tx = optax.chain(
                optax.GradientTransformation(
                    lambda _: optax.EmptyState(), clip_f32), tx)
        if schedule_fn is None:
            schedule_fn = lambda step: jnp.asarray(base_lr, jnp.float32)  # noqa: E731
        return tx, base_lr, schedule_fn

    def _init_state(self, params) -> TrainState:
        cfg = self._config
        zc = cfg.zero_config
        # ZeRO-Offload / ZeRO-Infinity: optimizer lives on the host (and
        # optionally NVMe); device keeps compute-dtype params only.
        # With offload_param the PARAMS live on the host too and stream
        # per-layer (runtime/zero/param_stream.py) — the full model never
        # resides in HBM.
        self._offload = None
        self._param_stream = None
        if zc.offload_param_device != "none":
            return self._init_param_stream_state(params)
        if zc.offload_optimizer_device != "none":
            return self._init_offload_state(params)
        # master params in fp32 (reference: fp16/bf16 optimizers keep fp32
        # master copies; we ONLY store the master and cast per-step).
        # jnp.array (copy) rather than asarray: the train step donates the
        # state, and an aliased no-copy view would delete the caller's arrays.
        params = jax.tree_util.tree_map(
            lambda x: jnp.array(x, jnp.float32), params)

        if cfg.fp16_enabled:
            if cfg.dynamic_loss_scale:
                ls = dynamic_loss_scale_state(
                    cfg.fp16_config.initial_scale_power,
                    hysteresis=cfg.fp16_config.hysteresis)
            else:
                ls = static_loss_scale_state(cfg.loss_scale)
        else:
            ls = static_loss_scale_state(1.0)

        param_sh = self.plan._to_sharding(self.plan.master_param_specs(params))
        with self.mesh:
            params = device_put_global(params, param_sh)
            opt_state = jax.jit(
                self.tx.init,
                out_shardings=self.plan.opt_state_shardings(self.tx, params),
            )(params)
        repl = self.plan.replicated_sharding()
        seed = cfg.seed
        with self.mesh:
            # jit (not device_put): builds replicated state on multi-host
            # meshes where device_put can't target non-addressable devices
            rng, step0, skip0 = jax.jit(
                lambda: (jax.random.key(seed), jnp.asarray(0, jnp.int32),
                         jnp.asarray(0, jnp.int32)),
                out_shardings=repl)()
        ls = device_put_global(
            ls, jax.tree_util.tree_map(lambda _: repl, ls))
        return TrainState(
            params=params, opt_state=opt_state, loss_scale=ls,
            global_step=step0, skipped_steps=skip0, rng=rng)

    def _init_param_stream_state(self, params) -> TrainState:
        """ZeRO-Infinity parameter offload: host master params + moments,
        double-buffered per-layer device streaming
        (``runtime/zero/param_stream.py``).  Max trainable params/chip is
        bounded by HOST memory, not HBM — the reference's
        ``zero.Init(remote_device="cpu"/"nvme")`` capability
        (``partition_parameters.py:539``)."""
        from deepspeed_tpu.runtime.zero.param_stream import ParamStreamRunner
        cfg = self._config
        if jax.process_count() > 1:
            # multi-host: the host store is REPLICATED per process (grads
            # come back fully-replicated from the layer programs — XLA
            # all-reduces over ICI — so every process lands identical
            # grads and applies the identical deterministic update).
            # Host RAM cost is the full model per host; the reference
            # shards its CPU partitions instead, a documented trade.
            log_dist("param-stream multi-host: host master/moments are "
                     "replicated per process (full model per host)",
                     ranks=[0])
        if cfg.compression_config:
            raise NotImplementedError(
                "compression/MoQ does not compose with offload_param "
                "streaming yet")
        opt_name = self.optimizer_name_ or "adamw"
        supported = {"adam", "adamw", "fusedadam", "cpuadam", "adagrad"}
        if opt_name not in supported:
            raise ValueError(
                f"offload_param supports {sorted(supported)}; got "
                f"'{opt_name}' (reference: ZeRO-Offload requires "
                "DeepSpeedCPUAdam/Adagrad)")
        opt_params = (dict(cfg.optimizer_config.params)
                      if cfg.optimizer_config else {})
        self._param_stream = ParamStreamRunner(
            self.module, params, cfg, self.mesh, self.plan,
            compute_dtype=self.compute_dtype,
            grad_accum_dtype=self.grad_accum_dtype,
            opt_name=opt_name, opt_params=opt_params)
        log_dist(
            f"param-stream offload: {self._param_stream.store.num_params():,}"
            f" params host-resident, {self._param_stream.n_layers} layers "
            f"streamed ({self._param_stream.resident_layers} pinned), "
            f"device={cfg.zero_config.offload_param_device}", ranks=[0])
        if cfg.fp16_enabled and cfg.dynamic_loss_scale:
            ls = dynamic_loss_scale_state(
                cfg.fp16_config.initial_scale_power,
                hysteresis=cfg.fp16_config.hysteresis)
        elif cfg.fp16_enabled:
            ls = static_loss_scale_state(cfg.loss_scale)
        else:
            ls = static_loss_scale_state(1.0)
        repl = self.plan.replicated_sharding()
        seed = cfg.seed
        with self.mesh:
            rng, step0, skip0 = jax.jit(
                lambda: (jax.random.key(seed), jnp.asarray(0, jnp.int32),
                         jnp.asarray(0, jnp.int32)),
                out_shardings=repl)()
        return TrainState(
            params=(), opt_state=(),
            loss_scale=device_put_global(
                ls, jax.tree_util.tree_map(lambda _: repl, ls)),
            global_step=step0, skipped_steps=skip0, rng=rng)

    def _init_offload_state(self, params) -> TrainState:
        """ZeRO-Offload mode state: host master + moments (see
        ``runtime/zero/offload.py``), device params in compute dtype."""
        from deepspeed_tpu.runtime.zero.offload import (HostOffloadOptimizer,
                                                        ShardedFlatLayout)
        cfg = self._config
        multihost = jax.process_count() > 1
        if multihost and cfg.zero_config.stage < 3:
            raise NotImplementedError(
                "multi-host offload_optimizer needs ZeRO stage 3: each "
                "process updates only the fsdp shards it can address, which "
                "requires params and grads to share the fsdp partition")
        opt_name = self.optimizer_name_ or "adamw"
        supported = {"adam", "adamw", "fusedadam", "cpuadam", "adagrad"}
        if opt_name not in supported:
            raise ValueError(
                f"offload_optimizer supports {sorted(supported)}; got "
                f"'{opt_name}' (reference: ZeRO-Offload requires "
                "DeepSpeedCPUAdam/Adagrad)")
        opt_params = (dict(cfg.optimizer_config.params)
                      if cfg.optimizer_config else {})
        if multihost:
            # per-host partition: fp32 copy placed with the GRAD sharding
            # (== param sharding at stage 3); each process's master covers
            # exactly its addressable shards (reference: per-DP-rank fp32
            # flat partitions, stage3.py).  The fp32 tree stays on HOST —
            # device_put_global's callback hands each device its slice, so
            # the full unsharded fp32 model never lands on one chip.
            def _host_fp32(x):
                h = np.asarray(jax.device_get(x))
                return h.astype(np.float32) \
                    if jnp.issubdtype(h.dtype, jnp.floating) else h
            fp32 = jax.tree_util.tree_map(_host_fp32, params)
            grad_sh = self.plan._to_sharding(self.plan.grad_specs(fp32))
            with self.mesh:
                fp32 = device_put_global(fp32, grad_sh)
            self._offload = HostOffloadOptimizer(
                fp32, cfg.zero_config, opt_name=opt_name,
                opt_params=opt_params, layout=ShardedFlatLayout(fp32),
                rank=jax.process_index(), world_size=jax.process_count())
            self._offload_sharded = True
            del fp32
        else:
            host_params = jax.tree_util.tree_map(
                lambda x: (np.asarray(x, np.float32)
                           if jnp.issubdtype(np.asarray(x).dtype,
                                             jnp.floating)
                           else np.asarray(x)), params)
            self._offload = HostOffloadOptimizer(
                host_params, cfg.zero_config, opt_name=opt_name,
                opt_params=opt_params,
                rank=jax.process_index(), world_size=jax.process_count())
            self._offload_sharded = False

        if cfg.fp16_enabled and cfg.dynamic_loss_scale:
            ls = dynamic_loss_scale_state(
                cfg.fp16_config.initial_scale_power,
                hysteresis=cfg.fp16_config.hysteresis)
        elif cfg.fp16_enabled:
            ls = static_loss_scale_state(cfg.loss_scale)
        else:
            ls = static_loss_scale_state(1.0)

        dev_params = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, self.compute_dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            else jnp.asarray(x), params)
        param_sh = self.plan._to_sharding(self.plan.param_specs(dev_params))
        with self.mesh:
            dev_params = device_put_global(dev_params, param_sh)
        self._offload_param_sh = param_sh
        repl = self.plan.replicated_sharding()
        seed = cfg.seed
        with self.mesh:
            rng, step0, skip0 = jax.jit(
                lambda: (jax.random.key(seed), jnp.asarray(0, jnp.int32),
                         jnp.asarray(0, jnp.int32)),
                out_shardings=repl)()
        return TrainState(
            params=dev_params, opt_state=(),
            loss_scale=device_put_global(
                ls, jax.tree_util.tree_map(lambda _: repl, ls)),
            global_step=step0, skipped_steps=skip0, rng=rng)

    # ------------------------------------------------------------------
    # the compiled step
    # ------------------------------------------------------------------
    def _loss_and_grads(self, params, loss_scale, batch, rng, step=None,
                        qstep=None):
        """value_and_grad of the (possibly loss-scaled) compute-dtype loss.

        ``qstep`` is the MoQ anneal clock — the *successful*-step counter
        (global_step - skipped_steps), because the reference Quantizer skips
        qsteps/ratio advancement on fp16 overflow steps (its quantize() is
        only called from a non-overflow step path).  Compression scheduling
        stays on the raw global step like the reference scheduler."""
        if qstep is None:
            qstep = step

        def scaled_loss(p):
            p_c = self._transformed_compute_params(p, rng, step, qstep)
            return self._model_scaled_loss(p_c, batch, rng, loss_scale)

        (_, loss), grads = jax.value_and_grad(scaled_loss, has_aux=True)(params)
        # unscale in fp32, then store at grad_accum_dtype (XLA fuses the
        # round-trip; bf16 storage halves the grad tree / GAS carry)
        grads = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) / loss_scale).astype(
                self.grad_accum_dtype), grads)
        return loss, grads

    def _transformed_compute_params(self, p, rng, step, qstep):
        """Compute-dtype view of the params with the cast-site transforms
        (compression STE, MoQ straight-through) applied."""
        p_c = jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, p)
        if self._compression is not None and step is not None:
            p_c = self._compression.transform(p_c, step)
        if self.quantizer is not None and step is not None:
            # MoQ: forward sees Q(w) from the schedule_offset step on —
            # the cast-site equivalent of the reference's post-step
            # quantization of the fp16 weight copy (engine.py:1799).
            # Straight-through: the reference evaluates grads at Q(w) but
            # applies them to the unquantized master, i.e. identity
            # backward — without this, d(round)/dx = 0 kills training.
            q_c = self.quantizer.transform(
                p_c, qstep, rng=jax.random.fold_in(rng, 0x4D6F51),
                schedule_offset=self.quantizer.schedule_offset)
            p_c = jax.tree_util.tree_map(
                lambda x, q: x + jax.lax.stop_gradient(q - x), p_c, q_c)
        return p_c

    def _model_scaled_loss(self, p_c, batch, rng, loss_scale):
        """Hook: (scaled fp32 loss, unscaled loss).  PipelineEngine
        overrides this to scale AT THE SOURCE inside the interleaved 1F1B
        backward — fp16 cotangents must ride the pipe pre-amplified, like
        the reference scales the loss before backward."""
        loss = self.loss_fn(p_c, batch, rng)
        return (loss * loss_scale).astype(jnp.float32), loss

    def _apply_update(self, state: TrainState, grads, overflow):
        """Shared optimizer-update tail: clip (inside tx), skip-on-overflow,
        re-constrain placements, loss-scale automaton.  Used by both the fused
        train step and the 3-call ``step()`` so the semantics cannot diverge.
        (Reference analogue: ``_take_model_step:2074`` +
        ``_overflow_check_and_loss_scale_update:1840``.)"""
        cfg = self._config
        grad_norm = _global_norm_f32(grads)
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        def pick(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(overflow, o, n), new, old)
        new_params = pick(new_params, state.params)
        new_opt = pick(new_opt, state.opt_state)
        new_params = constrain(new_params,
                               self.plan.master_param_specs(state.params),
                               self.mesh)
        new_ls = update_scale(
            state.loss_scale, overflow,
            dynamic=cfg.fp16_enabled and cfg.dynamic_loss_scale,
            scale_window=cfg.fp16_config.loss_scale_window,
            min_scale=cfg.fp16_config.min_loss_scale,
            hysteresis=cfg.fp16_config.hysteresis)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, loss_scale=new_ls,
            global_step=state.global_step + 1,
            skipped_steps=state.skipped_steps + overflow.astype(jnp.int32),
            rng=state.rng)
        return new_state, grad_norm

    def _census_grad_reduce(self, grads, bytes_saved=0):
        """Trace-time comm census for the ZeRO gradient reduction.

        The engine never calls a ``dist.*`` verb for grad sync — the
        grad-spec constraint makes the XLA partitioner insert the
        cross-device reduction — so without this record the single
        largest communicator in training is invisible to the comm plane
        (ROADMAP item 3's bytes-saved gauges hook in exactly here).
        Payload bytes are dtype-TRUE: ``size * itemsize`` at the grad
        tree's actual dtypes (works on tracers — aval shape/dtype), never
        an element count.  Stage >= 2 shards the reduction
        (reduce-scatter semantics); stages 0/1 land replicated grads
        (all-reduce).  Runs at trace time like every comm census.

        Quantized runs (``comm.quantization``) pass ``bytes_saved`` so
        the record books the reduced WIRE payload (int8 codes + fp32
        scales) with ``wire_dtype="int8"`` — the busbw tables then show
        the saved traffic instead of misreporting full-precision bytes."""
        if not self._tel_enabled:
            return
        world = groups.get_data_parallel_world_size()
        if world <= 1:
            return
        leaves = jax.tree_util.tree_leaves(grads)
        nbytes = sum(int(g.size) * np.dtype(g.dtype).itemsize for g in leaves)
        op = "reduce_scatter" if self.zero_stage >= 2 else "all_reduce"
        saved = int(bytes_saved)
        dist.comms_logger.append(op, nbytes - saved if saved else nbytes,
                                 "fsdp",
                                 dtype=str(leaves[0].dtype) if leaves else None,
                                 world=world,
                                 wire_dtype="int8" if saved else None,
                                 bytes_saved=saved if saved else None)

    def _quantize_grad_wire(self, grads):
        """Apply the ``comm.quantization`` wire codec to the ZeRO grad
        reduction at trace level.  The engine never calls a ``dist.*``
        verb here — XLA inserts the physical collective from the grad
        spec — so the codec is modelled as a blockwise int8 QDQ of the
        reduced gradient (exactly the phase-2 re-quantization of the
        two-phase EQuARX collective in comm/quantize.py; the phase-1
        per-rank error averages down by 1/world).  Returns
        ``(grads, bytes_saved)``; disabled configs return the tree
        untouched (bit-for-bit the unquantized path)."""
        q = self.comm_quant
        if not q.active():
            return grads, 0
        if groups.get_data_parallel_world_size() <= 1:
            return grads, 0
        op = "reduce_scatter" if self.zero_stage >= 2 else "all_reduce"
        return q.qdq_tree(grads, op)

    def _census_param_gather(self, nbytes, n_layers):
        """Trace-time comm census for the layer_scan gather pipeline: the
        explicit per-layer all-gathers of the stage-3 forward, booked once
        per traced scan (``n_layers`` layer working sets, ``nbytes``
        total) like every comm census.  Without this the overlap layer's
        dominant forward collective would be invisible to the busbw
        tables that the exposed-comm win is booked through."""
        if not self._tel_enabled:
            return
        world = int(self.mesh.shape.get(FSDP_AXIS, 1))
        if world <= 1:
            return
        dist.comms_logger.append("all_gather", int(nbytes), "fsdp",
                                 world=world)

    def _overlap_scope(self):
        """Context installing the gather-pipeline OverlapContext for the
        duration of a step-builder call.  The with-block runs at TRACE
        time inside jit, so wrapping the step body covers every trace and
        retrace; serial configs get a null context and the models'
        ``layer_scan`` collapses to the seed ``jax.lax.scan``."""
        if self._overlap_ctx is None:
            return contextlib.nullcontext()
        return overlap_scope(self._overlap_ctx)

    def _reduce_grads(self, grads, params):
        """The ZeRO gradient reduction: placement constraint (XLA lowers
        it to reduce-scatter / all-reduce), optional wire quantization,
        comm census.  One site for all three step builders so the
        semantics cannot diverge.

        Serial (``overlap.enabled=false``): whole-tree constrain + QDQ +
        one census record — exactly the seed lines, bit-for-bit.

        Overlapped: the tree is flushed in ``rs_bucket_bytes`` buckets in
        REVERSE flatten order (last layers' grads are final first during
        backward), an ``optimization_barrier`` chain pinning each
        bucket's reduction after the previous one, so the reductions
        issue under the backward tail instead of piling up after it.
        Constraint and codec are per-leaf in both paths, so bucketing
        changes collective ISSUE ORDER and census granularity only —
        values are bit-identical to the serial reduction.  Composes with
        ``comm.quantization``: each bucket rides the int8 wire, so the
        quantized window is the one being overlapped."""
        ov = self._overlap_cfg
        if not self._overlap_enabled:
            grads = constrain(grads, self.plan.grad_specs(params), self.mesh)
            grads, wire_saved = self._quantize_grad_wire(grads)
            self._census_grad_reduce(grads, bytes_saved=wire_saved)
            return grads
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        spec_leaves = treedef.flatten_up_to(self.plan.grad_specs(params))
        buckets = plan_reduce_buckets(leaves, ov.rs_bucket_bytes)
        self._rs_buckets = len(buckets)
        q = self.comm_quant
        quantize = (q.active()
                    and groups.get_data_parallel_world_size() > 1)
        op = "reduce_scatter" if self.zero_stage >= 2 else "all_reduce"
        out = list(leaves)
        prev = None
        for bucket in buckets:
            sub = [jax.lax.with_sharding_constraint(
                out[i], NamedSharding(self.mesh, spec_leaves[i]))
                for i in bucket]
            if prev is not None:
                # data-dependence chain: this bucket's reduction may not
                # be hoisted ahead of the previous (later-layer) bucket's
                tied = jax.lax.optimization_barrier(tuple(sub) + prev)
                sub = list(tied[:len(sub)])
            saved = 0
            if quantize:
                sub, saved = q.qdq_tree(sub, op)
                sub = list(sub)
            self._census_grad_reduce(sub, bytes_saved=saved)
            for j, i in enumerate(bucket):
                out[i] = sub[j]
            prev = tuple(sub)
        return jax.tree_util.tree_unflatten(treedef, out)

    def _finish_step(self, state: TrainState, loss, grads, rng):
        """Shared train-step tail: grad placement constraint, overflow
        check, optimizer update, metrics.  Used by both the dense and the
        pipeline engines so their semantics cannot diverge."""
        grads = self._reduce_grads(grads, state.params)
        fp16 = self._config.fp16_enabled
        overflow = has_inf_or_nan(grads) if fp16 else jnp.asarray(False)
        new_state, grad_norm = self._apply_update(
            state.replace(rng=rng), grads, overflow)
        metrics = StepMetrics(
            loss=loss.astype(jnp.float32),
            grad_norm=grad_norm.astype(jnp.float32),
            lr=jnp.asarray(self._schedule_fn(state.global_step), jnp.float32),
            loss_scale=new_state.loss_scale.cur_scale,
            overflow=overflow)
        return new_state, metrics

    def _forward_grads(self, params, scale, step_rng, batch, gas: int,
                       step=None, qstep=None):
        """GAS microbatch accumulation (``lax.scan``) shared by the fused and
        the offload step builders (reference: one grad-accumulation semantic,
        ``backward:1931`` scaling by 1/GAS)."""
        if gas > 1:
            def micro(carry, inp):
                idx, mb = inp
                acc, rloss = carry
                mb_rng = jax.random.fold_in(step_rng, idx)
                loss, grads = self._loss_and_grads(params, scale, mb, mb_rng,
                                                   step=step, qstep=qstep)
                acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                return (acc, rloss + loss), None

            zeros = jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, self.grad_accum_dtype), params)
            (gsum, lsum), _ = jax.lax.scan(
                micro, (zeros, jnp.float32(0.0)),
                (jnp.arange(gas), batch))
            grads = jax.tree_util.tree_map(lambda g: g / gas, gsum)
            return lsum / gas, grads
        return self._loss_and_grads(params, scale, batch, step_rng, step=step,
                                    qstep=qstep)

    def _build_train_step(self, gas: int):
        cfg = self._config
        fp16 = cfg.fp16_enabled

        def train_step(state: TrainState, batch):
            # the with-block runs at trace time, so the gather pipeline
            # is live for exactly this trace (and every retrace)
            with self._overlap_scope():
                scale = (state.loss_scale.cur_scale if fp16
                         else jnp.float32(1.0))
                rng, step_rng = jax.random.split(state.rng)
                loss, grads = self._forward_grads(
                    state.params, scale, step_rng, batch, gas,
                    step=state.global_step,
                    qstep=moq_anneal_step(state))
                # ZeRO grad placement: stage>=2 spec is fsdp-sharded → XLA
                # lowers the DP reduction as reduce-scatter (reference
                # average_tensor / __reduce_and_partition_ipg_grads)
                return self._finish_step(state, loss, grads, rng)

        return train_step

    def _wrap_compiled(self, fn, site):
        """Route a jitted entry point through the CompileWatcher so cache
        misses (recompiles) are timed and emitted as ``compile/*`` events."""
        if self._profiling is None:
            return fn
        return self._profiling.wrap(fn, site,
                                    step_fn=lambda: self.global_steps)

    def _prof_track(self, span):
        """HBM attribution context for a top-level span; no-op without the
        profiling plane (or off-TPU, where memory_stats() is unavailable)."""
        if self._profiling is None:
            return contextlib.nullcontext()
        return self._profiling.track(span)

    def _get_compiled_train_step(self, gas: int):
        if gas not in self._compiled_train_step:
            step = self._build_train_step(gas)
            self._compiled_train_step[gas] = self._wrap_compiled(
                jax.jit(step, donate_argnums=(0,)), f"engine/train_step:{gas}")
        return self._compiled_train_step[gas]

    # ------------------------------------------------------------------
    # ZeRO-Offload step path: device computes grads, host applies Adam
    # ------------------------------------------------------------------
    def _get_compiled_offload_grad_step(self, gas: int):
        if gas not in self._compiled_offload_grad:
            fp16 = self._config.fp16_enabled

            def grad_step(state: TrainState, batch):
                with self._overlap_scope():
                    scale = (state.loss_scale.cur_scale if fp16
                             else jnp.float32(1.0))
                    rng, step_rng = jax.random.split(state.rng)
                    loss, grads = self._forward_grads(
                        state.params, scale, step_rng, batch, gas,
                        step=state.global_step,
                        qstep=moq_anneal_step(state))
                    grads = self._reduce_grads(grads, state.params)
                    overflow = (has_inf_or_nan(grads) if fp16
                                else jnp.asarray(False))
                    grad_norm = _global_norm_f32(grads)
                    return loss, grads, overflow, grad_norm, rng
            self._compiled_offload_grad[gas] = self._wrap_compiled(
                jax.jit(grad_step), f"engine/offload_grad:{gas}")
        return self._compiled_offload_grad[gas]

    def _offload_host_apply(self, grads, overflow, grad_norm):
        """Host tail of the offload step: stream grads D2H, fused C++ Adam on
        the flat master (NVMe-swapped moments under ZeRO-Infinity), stream
        updated params H2D, run the loss-scale automaton."""
        cfg = self._config
        # bf16/fp32 runs never overflow-skip: the flag is a traced constant
        # False, and fetching it would serialize the host on the whole
        # device step before the grad D2H stream even starts
        overflow_b = (bool(jax.device_get(overflow))
                      if cfg.fp16_enabled else False)
        if not overflow_b:
            # schedule evaluated on the HOST step counter: no sync against
            # the in-flight device step
            lr = float(jax.device_get(
                jnp.asarray(self._schedule_fn(self.global_steps))))
            coef = None
            if cfg.gradient_clipping and cfg.gradient_clipping > 0:
                gn = float(jax.device_get(grad_norm))
                clip = cfg.gradient_clipping
                if gn > clip:
                    coef = clip / (gn + 1e-6)
            if self._offload_sharded:
                # multi-host: streamed D2H/Adam, then assemble the global
                # device tree from each process's local master shards
                self._offload.step_streamed(grads, lr=lr, clip_coef=coef)
                with self.mesh:
                    new_params = self._offload.device_params(
                        self._offload_param_sh, dtype=self.compute_dtype)
            else:
                # fully pipelined: per-leaf D2H / per-subgroup C++ Adam /
                # per-leaf H2D of the updated master all overlap (no
                # whole-tree host cast + serial upload tail)
                with self.mesh:
                    new_params = self._offload.step_streamed(
                        grads, lr=lr, clip_coef=coef,
                        upload_shardings=self._offload_param_sh,
                        upload_dtype=np.dtype(
                            jnp.dtype(self.compute_dtype).name))
            self.state = self.state.replace(params=new_params)
        new_ls = update_scale(
            self.state.loss_scale, jnp.asarray(overflow_b),
            dynamic=cfg.fp16_enabled and cfg.dynamic_loss_scale,
            scale_window=cfg.fp16_config.loss_scale_window,
            min_scale=cfg.fp16_config.min_loss_scale,
            hysteresis=cfg.fp16_config.hysteresis)
        self.state = self.state.replace(
            loss_scale=new_ls,
            global_step=self.state.global_step + 1,
            skipped_steps=self.state.skipped_steps + int(overflow_b))
        return overflow_b

    # ------------------------------------------------------------------
    # DeepSpeed-parity 3-call API
    # ------------------------------------------------------------------
    def forward(self, batch, rng=None):
        """Computes loss (and, functionally, gradients — cached for
        ``backward``).  Returns the unscaled loss."""
        if not self._tel_enabled:
            return self._forward_inner(batch, rng)
        with self.telemetry.span("engine/forward", step=self.global_steps), \
                self._prof_track("fwd"):
            return self._forward_inner(batch, rng)

    def _forward_inner(self, batch, rng=None):
        if self._param_stream is not None:
            raise NotImplementedError(
                "offload_param streaming runs whole optimizer steps; use "
                "train_batch() (the 3-call API would re-stream the model "
                "per call)")
        self.timers(FORWARD_GLOBAL_TIMER).start()
        if self._compiled_fwd_bwd is None:
            def fwd_bwd(state, batch):
                with self._overlap_scope():
                    scale = (state.loss_scale.cur_scale
                             if self._config.fp16_enabled
                             else jnp.float32(1.0))
                    rng, step_rng = jax.random.split(state.rng)
                    loss, grads = self._loss_and_grads(
                        state.params, scale, batch, step_rng,
                        step=state.global_step,
                        qstep=moq_anneal_step(state))
                    grads = self._reduce_grads(grads, state.params)
                    overflow = (has_inf_or_nan(grads)
                                if self._config.fp16_enabled
                                else jnp.asarray(False))
                    return loss, grads, overflow, rng
            self._compiled_fwd_bwd = self._wrap_compiled(
                jax.jit(fwd_bwd), "engine/fwd_bwd")
        batch = self._shard_batch(batch)
        with self.mesh:
            loss, grads, overflow, rng = self._compiled_fwd_bwd(self.state, batch)
        self.state = self.state.replace(rng=rng)
        self._cached = (loss, grads, overflow)
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        return loss

    __call__ = forward

    def backward(self, loss=None, allreduce_gradients=True, release_loss=False):
        """Accumulates the gradients computed by the latest ``forward``.
        Parity: reference ``backward:1931`` (scaling by 1/GAS happens here)."""
        if not self._tel_enabled:
            return self._backward_inner(loss)
        with self.telemetry.span("engine/backward", step=self.global_steps), \
                self._prof_track("bwd"):
            return self._backward_inner(loss)

    def _backward_inner(self, loss=None):
        assert self._cached is not None, "backward() called before forward()"
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        _, grads, overflow = self._cached
        gas = self.gradient_accumulation_steps_
        scaled = jax.tree_util.tree_map(lambda g: g / gas, grads)
        if self._accum_grads is None:
            self._accum_grads = scaled
            self._accum_overflow = overflow
        else:
            self._accum_grads = jax.tree_util.tree_map(
                jnp.add, self._accum_grads, scaled)
            self._accum_overflow = jnp.logical_or(self._accum_overflow, overflow)
        self._accum_count += 1
        self.micro_steps += 1
        self._cached = None
        self.timers(BACKWARD_GLOBAL_TIMER).stop()
        return loss

    def is_gradient_accumulation_boundary(self):
        return self._accum_count >= self.gradient_accumulation_steps_

    def step(self):
        """Applies the optimizer update at the GAS boundary.
        Parity: reference ``step:2142`` → ``_take_model_step:2074``."""
        if not self._tel_enabled:
            return self._step_inner()
        with self.telemetry.span("engine/step", step=self.global_steps), \
                self._prof_track("step"):
            self._step_inner()
        if self._step_applied:
            self._emit_step_telemetry()

    def _step_inner(self):
        self._step_applied = False
        if not self.is_gradient_accumulation_boundary():
            return
        self.timers(STEP_GLOBAL_TIMER).start()
        if self._offload is not None:
            grad_norm = optax.global_norm(self._accum_grads)
            self._offload_host_apply(self._accum_grads,
                                     self._accum_overflow, grad_norm)
        else:
            if self._compiled_apply is None:
                self._compiled_apply = self._wrap_compiled(
                    jax.jit(self._apply_update, donate_argnums=(0, 1)),
                    "engine/apply")
            with self.mesh:
                self.state, grad_norm = self._compiled_apply(
                    self.state, self._accum_grads, self._accum_overflow)
        # kept as a device scalar: get_global_grad_norm() floats on demand,
        # so the 3-call API doesn't serialize dispatch every step either
        self._global_grad_norm = grad_norm
        self._accum_grads = None
        self._accum_count = 0
        self._step_applied = True
        self.global_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._write_monitor()
        self.timers(STEP_GLOBAL_TIMER).stop()
        if self._config.wall_clock_breakdown and \
                self.global_steps % self._config.steps_per_print == 0:
            self.timers.log([FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                             STEP_GLOBAL_TIMER])

    # ------------------------------------------------------------------
    # fused fast path
    # ------------------------------------------------------------------
    def train_batch(self, data_iter=None, batch=None):
        """One full training step (GAS microbatches) as a single compiled
        program.  Parity with ``PipelineEngine.train_batch`` semantics: returns
        the mean loss over the global batch."""
        if self._preempt is not None and self._preempt.requested:
            self._handle_preemption()
        if not self._tel_enabled:
            loss = self._train_batch_inner(data_iter, batch)
        else:
            t0 = time.perf_counter()
            with self.telemetry.span("engine/train_batch",
                                     step=self.global_steps), \
                    self._prof_track("train_batch"):
                loss = self._train_batch_inner(data_iter, batch)
            self._emit_step_telemetry(step_secs=time.perf_counter() - t0,
                                      metrics=self._last_metrics)
        # step-boundary fault-tolerance hooks: divergence sentinel first
        # (its restore path clears state a preemption save would persist),
        # then preemption — a signal delivered mid-step is honored here
        # rather than a full step later
        if self._sentinel is not None:
            self._handle_sentinel()
        if self._preempt is not None and self._preempt.requested:
            self._handle_preemption()
        return loss

    def _train_batch_inner(self, data_iter=None, batch=None):
        gas = self.gradient_accumulation_steps_
        presharded = False
        if batch is None:
            owns_iter = data_iter is None
            if owns_iter:
                assert self.training_dataloader is not None, \
                    "train_batch needs data_iter, batch=, or training_data"
                data_iter = self._default_data_iter()
            if self._async_enabled:
                data_iter = self._wrap_prefetch(data_iter)
            from deepspeed_tpu.runtime.dataloader import DevicePrefetchIterator
            if isinstance(data_iter, DevicePrefetchIterator):
                # the worker already collated, gas-stacked, curriculum-
                # transformed and sharded this batch — just pop it
                try:
                    if self._tel_enabled:
                        with self.telemetry.span(
                                "engine/input_wait", step=self.global_steps,
                                attrs={"queued": data_iter.qsize()}):
                            batch = next(data_iter)
                    else:
                        batch = next(data_iter)
                except StopIteration:
                    if owns_iter:
                        self._default_iter = None
                    self._release_prefetcher(data_iter)
                    raise
                presharded = True
            else:
                micro_batches = [next(data_iter) for _ in range(gas)]
                if gas > 1:
                    batch = jax.tree_util.tree_map(
                        lambda *xs: np.stack(xs), *micro_batches)
                else:
                    batch = micro_batches[0]
        self.tput_timer.start()
        if self.compression_scheduler is not None:
            self.compression_scheduler.check(self.global_steps)
        if self.curriculum_scheduler_ is not None and not presharded:
            batch = self._apply_curriculum(batch, leading_gas_dim=gas > 1)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        if not presharded:
            batch = self._shard_batch(batch, leading_gas_dim=gas > 1)
        if self._tel_enabled:
            self._last_batch_tokens = _batch_token_count(batch)
        if self._injector is not None and \
                self._injector.poison_grads(self.global_steps):
            # deterministic divergence trigger: NaN the float batch inputs
            # (falling back to params when the batch is all-integer, e.g.
            # token ids) so this step's gradients go non-finite
            batch, n_poisoned = poison_tree(batch)
            if n_poisoned == 0:
                self.state = self.state.replace(
                    params=poison_tree(self.state.params)[0])
            logger.warning(f"fault injector: poisoned gradients at step "
                           f"{self.global_steps}")
        self._maybe_profile_flops(batch, gas)
        if self._param_stream is not None:
            cfg = self._config
            fp16 = cfg.fp16_enabled
            rng, step_rng = jax.random.split(self.state.rng)
            # lr from the HOST step counter and scale from the host
            # loss-scale mirror: neither reads the in-flight device state,
            # so this host-orchestrated path stops paying two device
            # round-trips per step just to learn values it already knows
            lr_now = self._host_schedule_value(self.global_steps)
            scale = self._host_ls.cur_scale if fp16 else 1.0
            loss_f, gnorm, overflow_b = self._param_stream.train_step(
                batch, gas, lr_now, scale, fp16,
                cfg.gradient_clipping, step_rng)
            # device automaton stays updated in lockstep (checkpoint parity)
            new_ls = update_scale(
                self.state.loss_scale, jnp.asarray(overflow_b),
                dynamic=fp16 and cfg.dynamic_loss_scale,
                scale_window=cfg.fp16_config.loss_scale_window,
                min_scale=cfg.fp16_config.min_loss_scale,
                hysteresis=cfg.fp16_config.hysteresis)
            self._host_ls.update(bool(overflow_b))
            self.state = self.state.replace(
                rng=rng, loss_scale=new_ls,
                global_step=self.state.global_step + 1,
                skipped_steps=(self.state.skipped_steps +
                               int(overflow_b)))
            metrics = StepMetrics(
                loss=jnp.float32(loss_f), grad_norm=jnp.float32(gnorm),
                lr=jnp.asarray(lr_now, jnp.float32),
                loss_scale=self.state.loss_scale.cur_scale,
                overflow=jnp.asarray(overflow_b))
        elif self._offload is not None:
            grad_fn = self._get_compiled_offload_grad_step(gas)
            with self.mesh:
                loss, grads, overflow, grad_norm, rng = grad_fn(
                    self.state, batch)
            self.state = self.state.replace(rng=rng)
            lr_now = self._schedule_fn(self.state.global_step)
            self._offload_host_apply(grads, overflow, grad_norm)
            metrics = StepMetrics(
                loss=loss.astype(jnp.float32),
                grad_norm=grad_norm.astype(jnp.float32),
                lr=jnp.asarray(lr_now, jnp.float32),
                loss_scale=self.state.loss_scale.cur_scale,
                overflow=overflow)
        else:
            step_fn = self._get_compiled_train_step(gas)
            with self.mesh:
                self.state, metrics = step_fn(self.state, batch)
        self.global_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        if self._sentinel is not None:
            # device references only — the sentinel batches its own
            # device_get every `interval` pushes, keeping the hot loop
            # sync-free
            self._sentinel.push(self.global_steps, loss=metrics.loss,
                                overflow=metrics.overflow)
        self._last_metrics = metrics
        self._global_grad_norm = metrics.grad_norm
        self.tput_timer.stop(global_step=True)
        self._write_monitor(metrics)
        return metrics.loss

    def _apply_curriculum(self, batch, leading_gas_dim=False, step=None):
        """Truncate sequences to the curriculum difficulty (reference
        ``engine.py:1820-1826`` curriculum_seqlen slicing).  Each difficulty
        milestone is a new static shape → one recompile, amortised over the
        steps at that difficulty.  ``step`` overrides the difficulty clock
        for the prefetch worker, which transforms batches ahead of time."""
        seqlen = self.curriculum_scheduler_.update_difficulty(
            self.global_steps if step is None else step)
        dim = 2 if leading_gas_dim else 1

        def trunc(x):
            if np.ndim(x) > dim and x.shape[dim] > seqlen:
                slicer = [slice(None)] * np.ndim(x)
                slicer[dim] = slice(0, seqlen)
                return x[tuple(slicer)]
            return x
        return jax.tree_util.tree_map(trunc, batch)

    def pld_enabled(self):
        return self.progressive_layer_drop is not None

    def pld_theta(self):
        return (self.progressive_layer_drop.get_theta()
                if self.progressive_layer_drop else 1.0)

    # subclass hooks: PipelineEngine preps (stacks) the batch and runs with
    # a leading microbatch dim — everything else is shared here.
    _eval_leading_gas_dim = False

    def _prep_eval_batch(self, batch):
        return batch

    def eval_batch(self, batch, rng=None):
        if self._param_stream is not None:
            batch = self._prep_eval_batch(batch)
            batch = self._shard_batch(
                batch, leading_gas_dim=self._eval_leading_gas_dim)
            return jnp.float32(
                self._param_stream.eval_loss(batch, rng=self.state.rng))
        if not hasattr(self, "_compiled_eval"):
            def ev(state, batch):
                p_c = jax.tree_util.tree_map(
                    lambda x: x.astype(self.compute_dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x,
                    state.params)
                # eval must see the same weights the training forward sees
                # (reference quantizes the fp16 copies in place, so its eval
                # path is quantized/compressed too)
                if self._compression is not None:
                    p_c = self._compression.transform(p_c, state.global_step)
                if self.quantizer is not None:
                    # same successful-step anneal clock as the training
                    # forward, or eval sees further-annealed bits after
                    # any overflow step
                    p_c = self.quantizer.transform(
                        p_c, moq_anneal_step(state),
                        schedule_offset=self.quantizer.schedule_offset)
                return self.loss_fn(p_c, batch, state.rng)
            self._compiled_eval = self._wrap_compiled(
                jax.jit(ev), "engine/eval")
        batch = self._prep_eval_batch(batch)
        batch = self._shard_batch(batch,
                                  leading_gas_dim=self._eval_leading_gas_dim)
        with self.mesh:
            return self._compiled_eval(self.state, batch)

    # ------------------------------------------------------------------
    def _shard_batch(self, batch, leading_gas_dim=False):
        multihost = jax.process_count() > 1

        def put(x):
            x = np.asarray(x) if not isinstance(x, jax.Array) else x
            ndim = x.ndim
            if leading_gas_dim:
                spec = self.plan.batch_spec(ndim - 1)
                spec = P(*([None] + list(spec)))
            else:
                spec = self.plan.batch_spec(ndim)
            sharding = NamedSharding(self.mesh, spec)
            if multihost:
                # each process holds its local slice of the global batch
                # (dataloader is process-strided); assemble the global array
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)
        return jax.tree_util.tree_map(put, batch)

    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None,
                     num_local_io_workers=None, data_sampler=None,
                     route=None):
        """Parity: reference ``deepspeed_io:1678`` — builds the distributed
        dataloader (global batches; sharding happens at device_put).
        ``num_local_io_workers`` sizes the host-side sample-fetch pool
        (falls back to ``async_pipeline.io_workers``); with the async
        pipeline enabled the loader is wrapped so iteration yields
        pre-sharded device batches from a background prefetcher."""
        from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                                      PrefetchingDataLoader)
        ap = self._config.async_pipeline_config
        if batch_size is None:
            batch_size = (self.train_micro_batch_size_per_gpu() *
                          groups.get_data_parallel_world_size())
        io_workers = (num_local_io_workers if num_local_io_workers is not None
                      else ap.io_workers)
        loader = DeepSpeedDataLoader(dataset, batch_size=batch_size,
                                     collate_fn=collate_fn,
                                     seed=self._config.seed,
                                     num_workers=io_workers)
        if self._async_enabled:
            return PrefetchingDataLoader(loader, self._make_prefetcher)
        return loader

    # -- async input feed ----------------------------------------------
    def _default_data_iter(self):
        """The iterator behind no-arg ``train_batch()``.  Sync path keeps
        the historical fresh-``iter()``-per-call behavior; async keeps ONE
        persistent iterator so a single prefetch worker spans steps (a
        fresh prefetcher per call could never run ahead)."""
        if not self._async_enabled:
            return iter(self.training_dataloader)
        if self._default_iter is None:
            self._default_iter = iter(self.training_dataloader)
        return self._default_iter

    def _wrap_prefetch(self, data_iter):
        """Wrap a host-batch iterator in the engine-owned prefetcher
        (identity-cached: repeated calls with the same iterator reuse the
        running worker; a new iterator retires the old prefetcher)."""
        from deepspeed_tpu.runtime.dataloader import DevicePrefetchIterator
        if isinstance(data_iter, DevicePrefetchIterator):
            return data_iter
        if self._prefetch_source is not data_iter:
            if self._prefetcher is not None:
                self._prefetcher.close()
            self._prefetcher = self._make_prefetcher(data_iter)
            self._prefetch_source = data_iter
        return self._prefetcher

    def _make_prefetcher(self, source):
        from deepspeed_tpu.runtime.dataloader import DevicePrefetchIterator
        ap = self._config.async_pipeline_config
        rc = self._resilience
        return DevicePrefetchIterator(
            source, gas=self.gradient_accumulation_steps_,
            shard_fn=self._shard_batch,
            transform=(self._prefetch_transform
                       if self.curriculum_scheduler_ is not None else None),
            depth=ap.prefetch_depth,
            start_index=self.global_steps,
            max_retries=rc.dataloader_max_retries,
            retry_backoff_secs=rc.dataloader_retry_backoff_secs,
            injector=self._injector,
            telemetry=self.telemetry)

    def _prefetch_transform(self, batch, index, leading_gas_dim):
        # runs on the prefetch worker: curriculum difficulty is keyed to
        # the step the batch will be CONSUMED at, not the current step
        return self._apply_curriculum(batch, leading_gas_dim=leading_gas_dim,
                                      step=index)

    def _release_prefetcher(self, prefetcher):
        prefetcher.close()
        if self._prefetcher is prefetcher:
            self._prefetcher = None
            self._prefetch_source = None

    def _host_schedule_value(self, step):
        """lr at host ``step`` as a python float, cached per step.  The
        schedule runs on a concrete int, so any device work is a tiny
        fresh computation — never a sync against the in-flight train step."""
        if self._host_lr_cache is None or self._host_lr_cache[0] != step:
            val = self._schedule_fn(step)
            self._host_lr_cache = (step, float(jax.device_get(val)))
        return self._host_lr_cache[1]

    # ------------------------------------------------------------------
    # monitor / introspection parity accessors
    # ------------------------------------------------------------------
    def _emit_step_telemetry(self, step_secs=None, metrics=None):
        """Per-step telemetry tail (telemetry-enabled runs only): heartbeat
        for the stall watchdog, loss/grad-norm/loss-scale + throughput
        gauges, and device-memory gauges with peak tracking.

        Sync-free by construction: the heartbeat and throughput gauges are
        host-clock, HBM gauges read allocator stats, and the device metric
        scalars go through :class:`MetricsDrain` — readback happens on the
        ``sync_interval`` boundary (or a drainer thread), not here."""
        tel = self.telemetry
        step = self.global_steps
        if self._watchdog is not None:
            self._watchdog.beat(step)
        elif getattr(tel, "attribution", None) is not None:
            # no watchdog heartbeat to close the attribution window —
            # beat the plane directly (same beat-to-beat step_ms contract)
            tel.attribution.beat(step)
        if self._overlap_enabled:
            # overlap effectiveness gauges (the frozen comm/overlap/*
            # vocabulary): exposure split from the attribution plane's
            # latest window, bucket counts from the trace-time planners
            plane = getattr(tel, "attribution", None)
            if plane is not None and plane.history:
                rec = plane.history[-1]
                comm_ms = float(rec.get("comm_ms", 0.0))
                exposed = float(rec.get("exposed_comm_ms", 0.0))
                tel.gauge("comm/overlap/exposed_ms", exposed, step=step)
                tel.gauge("comm/overlap/overlapped_ms",
                          max(0.0, comm_ms - exposed), step=step)
            if self._rs_buckets:
                tel.gauge("comm/overlap/rs_buckets",
                          float(self._rs_buckets), step=step)
            ctx = self._overlap_ctx
            if ctx is not None and ctx.layers:
                # one gather "bucket" per pipelined layer working set
                tel.gauge("comm/overlap/gather_buckets",
                          float(ctx.layers), step=step)
                tel.gauge("comm/overlap/prefetch_depth",
                          float(ctx.gather_prefetch_depth), step=step)
        if metrics is not None:
            vals = {"engine/loss": metrics.loss,
                    "engine/grad_norm": metrics.grad_norm}
            if self._config.fp16_enabled:
                vals["engine/loss_scale"] = metrics.loss_scale
            self._metrics_drain.push(step, vals)
        elif self._global_grad_norm is not None:
            self._metrics_drain.push(
                step, {"engine/grad_norm": self._global_grad_norm})
        if step_secs is not None and step_secs > 0:
            tel.gauge("engine/samples_per_sec",
                      self._config.train_batch_size / step_secs, step=step)
            if self._last_batch_tokens:
                tel.gauge("engine/tokens_per_sec",
                          self._last_batch_tokens / step_secs, step=step)
            if self._analytic_step_flops:
                flops_per_sec = self._analytic_step_flops / step_secs
                tel.gauge("train/model_flops_per_sec", flops_per_sec,
                          step=step)
                if self._mfu_peak_flops:
                    tel.gauge("train/mfu",
                              flops_per_sec / self._mfu_peak_flops,
                              step=step)
        if self._profiling is not None:
            self._profiling.on_step(step)
            if step_secs is not None and step_secs > 0:
                # live roofline: achieved fraction of peak compute and HBM
                # bandwidth for the whole train_batch span (analytic
                # numerators from the flops profiler, table denominators)
                self._profiling.roofline(
                    "train_batch", step_secs,
                    flops=self._analytic_step_flops,
                    bytes_moved=self._analytic_step_bytes,
                    peak_flops=self._mfu_peak_flops, step=step)
        if self._config.telemetry_config.hbm_gauges:
            self._emit_hbm_gauges(step)

    def _drain_emit(self, step, host_vals):
        """MetricsDrain callback: host floats for one step, in step order."""
        for name, value in host_vals.items():
            self.telemetry.gauge(name, value, step=step)

    def flush_telemetry(self):
        """Force readback + emit of any metrics still queued in the drain
        (checkpoint boundaries, end of training, tests)."""
        if self._metrics_drain is not None:
            self._metrics_drain.flush()

    def _emit_hbm_gauges(self, step):
        """HBM pressure gauges from ``jax.Device.memory_stats()`` (None on
        backends without allocator stats — skip quietly)."""
        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:
            stats = None
        if not stats:
            return
        for key in ("bytes_in_use", "peak_bytes_in_use",
                    "largest_alloc_size", "bytes_limit"):
            if key in stats:
                self.telemetry.gauge(f"hbm/{key}", float(stats[key]),
                                     step=step)

    def _write_monitor(self, metrics=None):
        if not self.monitor.enabled:
            return
        events = []
        if metrics is not None:
            events = [
                ("Train/Samples/train_loss", float(metrics.loss),
                 self.global_samples()),
                ("Train/Samples/lr", float(metrics.lr), self.global_samples()),
            ]
            if self._config.fp16_enabled:
                events.append(("Train/Samples/loss_scale",
                               float(metrics.loss_scale), self.global_samples()))
        self.monitor.write_events(events)

    def _maybe_profile_flops(self, batch, gas):
        """Parity: reference ``engine.py:1792,1810`` — run the flops profiler
        at ``flops_profiler.profile_step`` and print the model profile.

        Profiles the *forward* loss function on one microbatch (reference
        counts forward MACs via module hooks), inside the mesh context so
        sharding constraints trace the same as the executed program.  No XLA
        recompile — analytic jaxpr counting only."""
        fpc = self._config.flops_profiler_config
        if not fpc.enabled or self.global_steps != fpc.profile_step:
            return
        if self._param_stream is not None:
            return   # params live on host; no device tree to trace
        from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
        micro = batch
        if gas > 1:
            micro = jax.tree_util.tree_map(lambda x: x[0], batch)
        rng = self.state.rng

        def fwd(params, mb):
            p_c = jax.tree_util.tree_map(
                lambda x: x.astype(self.compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
            return self.loss_fn(p_c, mb, rng)

        prof = FlopsProfiler()
        prof.start_profile()
        with self.mesh:
            prof.profile(fwd, self.state.params, micro,
                         measure_time=False, xla_analysis=False)
        if dist.get_rank() == 0:
            prof.print_model_profile(profile_step=fpc.profile_step,
                                     module_depth=fpc.module_depth,
                                     top_modules=fpc.top_modules,
                                     detailed=fpc.detailed,
                                     output_file=fpc.output_file)
        prof.end_profile()
        self.flops_profiler = prof
        # wire the analytic count into live telemetry: a train step is
        # fwd+bwd (~3x forward flops) over `gas` microbatches; every step
        # from here on emits train/model_flops_per_sec, and train/mfu when
        # a per-device peak is known (config peak_tflops, else chip table)
        if prof.total_flops:
            self._analytic_step_flops = 3.0 * float(prof.total_flops) * gas
            # analytic HBM traffic for the bandwidth roofline: same 3x
            # fwd+bwd approximation over the jaxpr's operand/result bytes
            try:
                from deepspeed_tpu.profiling.flops_profiler import \
                    jaxpr_hbm_bytes
                with self.mesh:
                    fwd_bytes = jaxpr_hbm_bytes(fwd, self.state.params, micro)
                self._analytic_step_bytes = (3.0 * float(fwd_bytes) * gas
                                             if fwd_bytes else None)
            except Exception:
                self._analytic_step_bytes = None
            peak = (float(fpc.peak_tflops) * 1e12
                    if float(getattr(fpc, "peak_tflops", 0.0) or 0.0) > 0
                    else None)
            if peak is None:
                from deepspeed_tpu.comm.topology_model import \
                    device_peak_flops
                peak = device_peak_flops()
            self._mfu_peak_flops = (peak * jax.device_count()
                                    if peak else None)

    def global_samples(self):
        return self.global_steps * self._config.train_batch_size

    def get_global_grad_norm(self):
        return float(self._global_grad_norm)

    def get_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_lr()
        return [self._base_lr]

    def get_loss_scale(self):
        return float(self.state.loss_scale.cur_scale)

    @property
    def cur_scale(self):
        return self.get_loss_scale()

    def was_step_applied(self):
        return self._step_applied

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def train_batch_size(self):
        return self._config.train_batch_size

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def quantize_training(self):
        """MoQ config tuple (reference ``engine.py:698`` — in_forward,
        enabled, groups, fp16_mixed, change_ratio, type, rounding, verbose,
        kernel).  Reads the live Quantizer so the report can't drift from
        what actually runs."""
        from deepspeed_tpu.runtime.quantize import quantizer_from_shared
        wq = (self._config.compression_config or {}).get(
            "weight_quantization", {})
        shared = wq.get("shared_parameters", {})
        in_forward = shared.get("quantize_weight_in_forward", False)
        enabled = bool(shared.get("enabled", False)
                       or shared.get("quantize_enabled", False))
        q = self.quantizer or quantizer_from_shared(shared)
        return (in_forward, enabled, q.q_groups, q.q_mixed_fp16,
                q.q_change_ratio, q.q_type, q.q_rounding, q.q_verbose,
                q.use_quantizer_kernel)

    def zero_optimization_stage(self):
        return self.zero_stage

    def zero_optimization(self):
        return self.zero_stage > 0

    def fp16_enabled(self):
        return self._config.fp16_enabled

    def bfloat16_enabled(self):
        return self._config.bfloat16_enabled

    def get_params(self):
        return self.state.params

    def module_state_dict(self):
        """Full (un-sharded, host) params — reference ``module_state_dict`` /
        ``_zero3_consolidated_16bit_state_dict:3432`` rolled into one: orbax
        handles gather-on-save, so consolidation is just a replicated
        device_get."""
        if self._param_stream is not None:
            return self._param_stream.params_tree()
        if self._offload is not None:
            if self._offload_sharded:
                # multi-host: the host master is shard-local; consolidate
                # from the (compute-dtype) device params instead
                return jax.device_get(self._replicate_gather(
                    self.state.params))
            return self._offload.params_tree()
        return jax.device_get(self._replicate_gather(self.state.params))

    def _replicate_gather(self, tree):
        """All-gather a sharded tree to replicated via jit (works on
        multi-host meshes where a plain device_put cannot re-target
        non-addressable devices)."""
        repl = self.plan.replicated_sharding()
        with self.mesh:
            return jax.jit(lambda x: x, out_shardings=repl)(tree)

    # ------------------------------------------------------------------
    # fault tolerance (runtime/resilience.py)
    # ------------------------------------------------------------------
    def _shutdown_workers(self):
        """Drain the engine's worker threads cleanly: close the prefetcher
        (its daemon worker exits on the queue sentinel) and flush any
        device metrics still queued in the drain."""
        if self._prefetcher is not None:
            self._release_prefetcher(self._prefetcher)
        self._default_iter = None
        self.flush_telemetry()

    def _handle_preemption(self):
        """Step-boundary response to SIGTERM/SIGINT: emergency checkpoint
        (when ``resilience.ckpt_dir`` is set), clean worker drain, then
        :class:`TrainingPreempted` so the caller unwinds instead of being
        killed mid-write."""
        rc = self._resilience
        tag = f"emergency_step{self.global_steps}" if rc.ckpt_dir else None
        if tag is not None:
            try:
                self.save_checkpoint(rc.ckpt_dir, tag=tag)
            except Exception as exc:
                logger.error(f"emergency checkpoint failed: {exc!r}")
                tag = None
        self._shutdown_workers()
        self.telemetry.fault("fault/preempted", step=self.global_steps,
                             attrs={"tag": tag, "dir": rc.ckpt_dir or None})
        self._preempt.uninstall()
        self._preempt.clear()
        where = f"; emergency checkpoint {rc.ckpt_dir}/{tag}" if tag else ""
        raise TrainingPreempted(
            f"training preempted at step {self.global_steps}{where}")

    def _handle_sentinel(self):
        """Act on a tripped divergence sentinel: auto-restore from the last
        good checkpoint when configured (and one exists), else drain and
        halt with :class:`DivergenceError`."""
        action = self._sentinel.poll()
        if action is None:
            return
        if action == "restore" and self._last_good_ckpt is not None:
            load_dir, tag = self._last_good_ckpt
            logger.warning(
                f"divergence ({self._sentinel.reason} at step "
                f"{self._sentinel.trip_step}): auto-restoring {load_dir}/{tag}")
            self.load_checkpoint(load_dir, tag=tag)
            self.telemetry.fault("fault/auto_restore", step=self.global_steps,
                                 attrs={"dir": load_dir, "tag": tag,
                                        "reason": self._sentinel.reason})
            self._sentinel.reset()
            return
        reason, step = self._sentinel.reason, self._sentinel.trip_step
        self._shutdown_workers()
        raise DivergenceError(
            f"training diverged at step {step}: {reason} "
            f"(no checkpoint to restore)" if action == "restore" else
            f"training diverged at step {step}: {reason}")

    # ------------------------------------------------------------------
    # checkpointing (parity: save_checkpoint:3084 / load_checkpoint:2724)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        from deepspeed_tpu.runtime.checkpoint_engine import get_checkpoint_engine
        eng = get_checkpoint_engine()
        tag = tag or f"global_step{self.global_steps}"
        client_state = dict(client_state or {})
        client_state.update({
            "global_steps": self.global_steps,
            "skipped_steps": int(self.state.skipped_steps),
            "micro_steps": self.micro_steps,
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler else None),
        })
        rc = self._resilience
        if not rc.enabled:
            # legacy in-place path: no tmp dir, no marker, no retries
            eng.save(self.state, save_dir, tag, client_state=client_state)
            if self._param_stream is not None:
                self._param_stream.save(save_dir, tag)
            if self._offload is not None:
                self._offload.save(save_dir, tag)
            if save_latest and jax.process_index() == 0:
                with open(os.path.join(save_dir, "latest"), "w") as f:
                    f.write(tag)
            dist.barrier()
            return True
        # durable protocol: every writer (orbax engine, param-stream host
        # store, offload host shards) targets the dot-prefixed tmp tag —
        # invisible to tag scans — then commit() fsyncs and atomically
        # renames it into place with a manifest + marker.  The whole
        # attempt (including the rename) sits under the retry policy; the
        # injector's "ckpt_save" site is consumed by the same retries.
        txn = CheckpointTransaction(
            save_dir, tag,
            is_coordinator=jax.process_index() == 0,
            barrier_fn=dist.barrier if jax.process_count() > 1 else None)

        def _attempt():
            txn.begin()
            eng.save(self.state, save_dir, txn.tmp_tag,
                     client_state=client_state)
            if self._param_stream is not None:
                self._param_stream.save(save_dir, txn.tmp_tag)
            if self._offload is not None:
                self._offload.save(save_dir, txn.tmp_tag)
            # async (Nebula-style) engines flush their background write
            # here — the commit marker must never precede the payload
            eng.commit(txn.tmp_tag)
            return txn.commit(build_manifest(self.state, tag,
                                             self.global_steps,
                                             checksum=rc.checksum))

        retry_io(_attempt, self._retry_policy, telemetry=self.telemetry,
                 op=f"ckpt_save[{tag}]", injector=self._injector,
                 site="ckpt_save", cleanup=txn.abort)
        self._last_good_ckpt = (save_dir, tag)
        if save_latest and jax.process_index() == 0:
            retry_io(
                lambda: atomic_write_text(
                    os.path.join(save_dir, "latest"), tag),
                self._retry_policy, telemetry=self.telemetry,
                op=f"latest[{tag}]", injector=self._injector, site="fs")
        if rc.keep_last > 0 and jax.process_index() == 0:
            gc_tags(save_dir, rc.keep_last, protect=(tag,),
                    telemetry=self.telemetry)
        self.telemetry.emit("meta", "ckpt/committed",
                            attrs={"dir": os.path.abspath(save_dir),
                                   "tag": tag, "step": self.global_steps})
        dist.barrier()
        return True

    def _load_candidates(self, load_dir, tag):
        """Ordered list of loadable tags ``[(tag, status, manifest,
        is_fallback)]``.  An explicit ``tag`` is honored or rejected — no
        silent substitution; ``tag=None`` resolves the ``latest`` pointer
        and falls back to the newest COMMITTED tag when the pointed-to
        checkpoint is missing, torn, or corrupt."""
        if tag is not None:
            status, manifest = validate_tag(os.path.join(load_dir, tag))
            if status == LEGACY:
                logger.warning(f"checkpoint {load_dir}/{tag} predates the "
                               "durable-commit protocol; loading unvalidated")
            elif status != COMMITTED:
                raise CheckpointCorruptError(
                    f"checkpoint {load_dir}/{tag} failed validation: "
                    f"{status}")
            return [(tag, status, manifest, False)]
        latest_tag = None
        latest = os.path.join(load_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                latest_tag = f.read().strip()
        tags = scan_tags(load_dir)
        by_tag = {t: (s, m) for t, s, m in tags}
        out = []
        if latest_tag:
            status, manifest = by_tag.get(latest_tag, (None, None))
            if status is None:
                status, manifest = validate_tag(
                    os.path.join(load_dir, latest_tag))
            if status in (COMMITTED, LEGACY):
                out.append((latest_tag, status, manifest, False))
            else:
                logger.error(f"latest checkpoint {load_dir}/{latest_tag} is "
                             f"{status}; scanning for newest valid tag")
        for t, s, m in tags:
            if s == COMMITTED and t != latest_tag:
                out.append((t, s, m, True))
        return out

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                        load_lr_scheduler_states=True,
                        load_module_strict=True, load_module_only=False):
        from deepspeed_tpu.runtime.checkpoint_engine import get_checkpoint_engine
        eng = get_checkpoint_engine()
        rc = self._resilience
        if not rc.enabled:
            if tag is None:
                latest = os.path.join(load_dir, "latest")
                if not os.path.exists(latest):
                    logger.warning(f"no 'latest' file at {load_dir}")
                    return None, {}
                with open(latest) as f:
                    tag = f.read().strip()
            candidates = [(tag, LEGACY, None, False)]
        else:
            candidates = self._load_candidates(load_dir, tag)
            if not candidates:
                logger.warning(f"no loadable checkpoint under {load_dir}")
                return None, {}
        state = client_state = None
        chosen = None
        last_exc = None
        for cand_tag, status, manifest, is_fallback in candidates:
            if is_fallback:
                self.telemetry.fault(
                    "fault/ckpt_fallback",
                    attrs={"dir": os.path.abspath(load_dir),
                           "to": cand_tag,
                           "step": (manifest or {}).get("global_step")})
                logger.warning(f"falling back to checkpoint {cand_tag}")
            try:
                def _attempt():
                    return eng.load(
                        self.state, load_dir, cand_tag, self.mesh,
                        load_optimizer_states=load_optimizer_states,
                        load_module_only=load_module_only)
                if rc.enabled:
                    state, client_state = retry_io(
                        _attempt, self._retry_policy,
                        telemetry=self.telemetry,
                        op=f"ckpt_load[{cand_tag}]",
                        injector=self._injector, site="ckpt_load")
                else:
                    state, client_state = _attempt()
                verify_restored(state, manifest)
                chosen = cand_tag
                break
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as exc:
                last_exc = exc
                logger.error(f"loading checkpoint {load_dir}/{cand_tag} "
                             f"failed: {exc!r}")
                state = client_state = None
        if chosen is None:
            if last_exc is not None:
                raise last_exc
            logger.warning(f"no loadable checkpoint under {load_dir}")
            return None, {}
        tag = chosen
        self.state = state
        if self._param_stream is not None:
            if not self._param_stream.load(
                    load_dir, tag,
                    load_optimizer_states=load_optimizer_states):
                logger.warning(
                    "no param-stream host state in checkpoint "
                    f"{load_dir}/{tag}; host params unchanged")
        if self._offload is not None:
            restored = load_optimizer_states and self._offload.load(load_dir,
                                                                    tag)
            if restored:
                with self.mesh:
                    if self._offload_sharded:
                        new_params = self._offload.device_params(
                            self._offload_param_sh,
                            dtype=self.compute_dtype)
                    else:
                        new_params = device_put_global(
                            jax.tree_util.tree_map(
                                lambda x: jnp.asarray(
                                    x.astype(self.compute_dtype)
                                    if jnp.issubdtype(x.dtype, jnp.floating)
                                    else x),
                                self._offload.params_tree()),
                            self._offload_param_sh)
                    self.state = self.state.replace(params=new_params)
            else:
                # no host shard restored (fresh fp32 weights or
                # load_optimizer_states=False): resync the host master from
                # the just-loaded device params so the next step doesn't
                # revert them to construction-time weights
                if self._offload_sharded:
                    # loaded device params share the grad/fsdp sharding at
                    # stage 3: flatten local shards directly (fp32 cast in
                    # the shard fetch)
                    self._offload.layout.flatten(
                        self.state.params, out=self._offload.master)
                else:
                    loaded = jax.device_get(
                        self._replicate_gather(self.state.params))
                    self._offload.layout.flatten(loaded,
                                                 out=self._offload.master)
        self.global_steps = client_state.get("global_steps", 0)
        self.micro_steps = client_state.get("micro_steps", 0)
        # resync the host loss-scale mirror from the restored device
        # automaton (one-time device_get at a checkpoint boundary)
        ls = jax.device_get(self.state.loss_scale)
        self._host_ls.load(ls.cur_scale, ls.cur_hysteresis,
                           ls.last_overflow_iter, ls.iteration)
        self._host_lr_cache = None
        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                client_state.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(client_state["lr_scheduler"])
        if rc.enabled:
            self._last_good_ckpt = (load_dir, tag)
        return load_dir, client_state
