"""1-bit (sign-compressed, error-feedback) gradient communication.

Parity: reference ``runtime/comm/nccl.py:14 NcclBackend.compressed_allreduce``
(+ ``runtime/comm/mpi.py``, ``runtime/compression/cupy.py`` bit packing) — the
communication engine behind OnebitAdam/OnebitLamb/ZeroOneAdam
(``fp16/onebit/{adam,lamb,zoadam}.py``): each worker sign-compresses its
gradient with error feedback, workers exchange 1-bit chunks (igather), each
worker acts as "server" for its chunk (average → re-compress with server
error feedback), and the compressed result is allgathered.  16× less traffic
than fp32 allreduce during the compression stage.

TPU design
----------
Two layers:

1. ``compressed_allreduce`` — the REAL collective, for use inside
   ``shard_map`` over a data-parallel mesh axis: bit-packs signs into uint8,
   ``all_to_all`` scatters worker chunks (phase 1 = reference igather),
   majority-sign server reduction with server error feedback, ``all_gather``
   of the 1-bit result (phase 2).  On a multi-pod mesh this is the DCN-side
   option where bandwidth, not latency, dominates.
2. ``error_feedback_compress`` — an optax gradient transformation giving the
   OnebitAdam *optimizer semantics* in the SPMD engine: a warmup stage
   (plain Adam; reference ``freeze_step``) followed by a compression stage
   where the (XLA-reduced) gradient is sign-quantized with error feedback
   before the inner update.  The engine selects it via the optimizer names
   ``OneBitAdam``/``ZeroOneAdam``/``OneBitLamb``.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

# ----------------------------------------------------------------------
# bit packing (reference: cupy packbits/unpackbits)
# ----------------------------------------------------------------------


def pack_signs(x: jnp.ndarray) -> jnp.ndarray:
    """Pack the sign bits of flat ``x`` (numel divisible by 8) into uint8."""
    bits = (x >= 0).astype(jnp.uint8).reshape(-1, 8)
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    return (bits * weights).sum(axis=1).astype(jnp.uint8)


def unpack_signs(packed: jnp.ndarray) -> jnp.ndarray:
    """uint8 → ±1 float32, inverse of :func:`pack_signs`."""
    shifts = jnp.asarray([7, 6, 5, 4, 3, 2, 1, 0], jnp.uint8)
    bits = (packed[:, None] >> shifts) & 1
    return jnp.where(bits.reshape(-1) > 0, 1.0, -1.0).astype(jnp.float32)


# ----------------------------------------------------------------------
# the collective (shard_map layer)
# ----------------------------------------------------------------------


def compressed_allreduce(grad: jnp.ndarray, worker_error: jnp.ndarray,
                         server_error: jnp.ndarray, axis_name: str):
    """Error-feedback sign-compressed allreduce of a flat fp32 vector.

    Must run inside ``shard_map`` with ``axis_name`` bound.  ``grad`` is this
    worker's local gradient (full length ``n``); ``worker_error`` has length
    ``n``; ``server_error`` has length ``n // world``.  ``n`` must be
    divisible by ``world * 8`` (pad upstream).

    Returns ``(reduced, new_worker_error, new_server_error)`` where
    ``reduced`` is the same quantity on every worker (the averaged,
    twice-compressed gradient).
    """
    world = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)

    # ---- worker compression (phase-1 sender side) --------------------
    # worker compresses the RAW local grad; the averaging over workers
    # happens once, at the server reduction (reference compressed_allreduce)
    corrected = grad + worker_error
    worker_scale = jnp.mean(jnp.abs(corrected))
    signs = jnp.where(corrected >= 0, 1.0, -1.0).astype(jnp.float32)
    new_worker_error = corrected - worker_scale * signs

    packed = pack_signs(signs)                       # n/8 uint8
    chunk_bytes = packed.shape[0] // world

    # phase 1: worker i sends its j-th chunk to worker j (reference igather)
    send = packed.reshape(world, chunk_bytes)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    recv = recv.reshape(world, chunk_bytes)
    scales = lax.all_gather(worker_scale, axis_name)  # [world]

    # ---- server reduction of my chunk --------------------------------
    # decompress every worker's version of my chunk and average
    worker_chunks = jax.vmap(unpack_signs)(recv)      # [world, chunk*8]
    avg = (worker_chunks * scales[:, None]).mean(axis=0)
    server_corrected = avg + server_error
    server_scale = jnp.mean(jnp.abs(server_corrected))
    server_signs = jnp.where(server_corrected >= 0, 1.0, -1.0)
    new_server_error = server_corrected - server_scale * server_signs

    # phase 2: allgather the 1-bit server results
    out_packed = pack_signs(server_signs)             # chunk_bytes
    all_packed = lax.all_gather(out_packed, axis_name)   # [world, chunk_bytes]
    all_scales = lax.all_gather(server_scale, axis_name)  # [world]
    all_signs = jax.vmap(unpack_signs)(all_packed)    # [world, chunk*8]
    reduced = (all_signs * all_scales[:, None]).reshape(-1)

    del idx
    return reduced, new_worker_error, new_server_error


def quantized_allreduce(x: jnp.ndarray, axis_name: str, bits: int = 8,
                        group_size: int = 256):
    """EQuARX-style quantized allreduce (PAPERS.md: "Efficient Quantized
    AllReduce in XLA"; SURVEY §5 names it as the quantized-collectives
    analogue of the reference's 1-bit backends).

    Both wire phases of a ring allreduce carry intN + per-group fp32 scales
    instead of fp32 — ~``32/bits``x less traffic where bandwidth (DCN
    between pod slices) dominates:

    1. each worker groupwise-quantizes its local vector, ``all_to_all``
       scatters per-peer chunks (the reduce-scatter wire phase),
    2. every worker dequantizes the ``world`` versions of its chunk, sums
       in fp32, requantizes, and ``all_gather``s the result.

    Must run inside ``shard_map`` with ``axis_name`` bound; ``x`` is flat
    fp32 with ``numel`` divisible by ``world * group_size`` (pad upstream
    with :func:`pad_to_multiple`).  Returns the SUM-reduced vector (divide
    by world for a mean), identical on every worker, with two rounds of
    intN quantization error and no error feedback (at 8 bits the error is
    ~1e-2 relative — the EF machinery the 1-bit path needs is unnecessary).
    """
    assert 2 <= bits <= 8, f"int8 storage caps bits at 8, got {bits}"
    world = lax.psum(1, axis_name)

    def q(v):
        g = v.reshape(-1, group_size)
        scale = jnp.max(jnp.abs(g), axis=1, keepdims=True) \
            / float(2 ** (bits - 1) - 1)
        scale = jnp.where(scale == 0, 1.0, scale)
        codes = jnp.clip(jnp.round(g / scale), -(2 ** (bits - 1)),
                         2 ** (bits - 1) - 1).astype(jnp.int8)
        return codes, scale.astype(jnp.float32)

    def dq(codes, scale):
        return (codes.astype(jnp.float32) * scale).reshape(-1)

    n = x.shape[0]
    chunk = n // world
    # phase 1: quantize, scatter chunks (worker i keeps chunk i)
    codes, scales = q(x)
    codes = codes.reshape(world, chunk // group_size, group_size)
    scales = scales.reshape(world, chunk // group_size, 1)
    recv_c = lax.all_to_all(codes, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)              # [world, groups, gs]
    recv_s = lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    mine = jax.vmap(dq)(recv_c.reshape(world, -1, group_size),
                        recv_s.reshape(world, -1, 1)).sum(axis=0)

    # phase 2: requantize the reduced chunk, allgather
    out_c, out_s = q(mine)
    all_c = lax.all_gather(out_c, axis_name)          # [world, groups, gs]
    all_s = lax.all_gather(out_s, axis_name)
    return jax.vmap(dq)(all_c, all_s).reshape(-1)


def quantized_allreduce_bytes(numel: int, world: int, bits: int = 8,
                              group_size: int = 256) -> int:
    """Wire bytes per worker for :func:`quantized_allreduce` (both phases:
    intN payload + fp32 group scales)."""
    payload = numel * bits // 8
    scales = numel // group_size * 4
    return payload + scales + (payload // world + scales // world) * world


def compressed_allreduce_bytes(numel: int, world: int) -> int:
    """Traffic per worker in bytes (both phases) — for comms logging; the
    fp32 ring-allreduce equivalent is ``~2 * 4 * numel``."""
    phase1 = numel // 8                 # send 1 bit/elem total across peers
    phase2 = (numel // world // 8) * world
    return phase1 + phase2 + 8 * world  # + scales


# ----------------------------------------------------------------------
# optimizer-side error feedback (engine layer)
# ----------------------------------------------------------------------


class EFCompressionState(NamedTuple):
    count: jnp.ndarray       # i32 step counter
    error: Any               # pytree of per-leaf error-feedback buffers


def error_feedback_compress(freeze_step: int = 100
                            ) -> optax.GradientTransformation:
    """Optax transform: identity during warmup (``step <= freeze_step``),
    then EF sign quantization per leaf — the OnebitAdam two-stage schedule
    (reference ``fp16/onebit/adam.py`` ``freeze_step`` semantics)."""

    def init_fn(params):
        return EFCompressionState(
            count=jnp.zeros([], jnp.int32),
            error=jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, jnp.float32), params))

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        enabled = count > freeze_step

        def leaf(g, e):
            c = g.astype(jnp.float32) + e
            scale = jnp.mean(jnp.abs(c))
            q = scale * jnp.where(c >= 0, 1.0, -1.0)
            out = jnp.where(enabled, q, g)
            new_e = jnp.where(enabled, c - q, e)
            return out.astype(g.dtype), new_e

        flat = jax.tree_util.tree_map(leaf, updates, state.error)
        outs = jax.tree_util.tree_map(lambda t: t[0], flat,
                                      is_leaf=lambda t: isinstance(t, tuple))
        errs = jax.tree_util.tree_map(lambda t: t[1], flat,
                                      is_leaf=lambda t: isinstance(t, tuple))
        return outs, EFCompressionState(count=count, error=errs)

    return optax.GradientTransformation(init_fn, update_fn)


def pad_to_multiple(x: np.ndarray, multiple: int):
    """Pad a flat vector so ``compressed_allreduce`` size constraints hold;
    returns (padded, original_numel)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    return jnp.concatenate([x, jnp.zeros((rem,), x.dtype)]), n
