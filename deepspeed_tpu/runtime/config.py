"""Top-level config.

Parity: reference ``deepspeed/runtime/config.py`` (``DeepSpeedConfig``,
``_batch_assertion:956`` batch-size triangle).  One JSON dict/file configures
everything; subsystem configs are typed models.

TPU extension: a ``"mesh"`` section ``{"dp":1,"fsdp":-1,"tp":1,"pp":1,"sp":1,
"ep":1}`` choosing the parallel topology; absent → all devices on the fsdp
axis (pure ZeRO-style data parallelism), matching the reference default where
the DP group is the world.
"""

import json
import os
from typing import Any, Dict, Union

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import (DeepSpeedConfigModel,
                                                get_scalar_param)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.parallel.topology import TopologyConfig
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


class FP16Config(DeepSpeedConfigModel):
    enabled = C.FP16_ENABLED_DEFAULT
    loss_scale = C.FP16_LOSS_SCALE_DEFAULT
    initial_scale_power = C.FP16_INITIAL_SCALE_POWER_DEFAULT
    loss_scale_window = C.FP16_LOSS_SCALE_WINDOW_DEFAULT
    hysteresis = C.FP16_HYSTERESIS_DEFAULT
    min_loss_scale = C.FP16_MIN_LOSS_SCALE_DEFAULT
    fp16_master_weights_and_grads = C.FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT
    auto_cast = False


class BF16Config(DeepSpeedConfigModel):
    enabled = C.BFLOAT16_ENABLED_DEFAULT


class CommsConfig(DeepSpeedConfigModel):
    enabled = False
    verbose = False
    prof_all = True
    debug = False
    prof_ops = []


class CommQuantizationConfig(DeepSpeedConfigModel):
    """``"comm.quantization"`` block: the blockwise-int8 wire codec for
    bandwidth-bound collectives (``comm/quantize.py``, EQuARX-style).
    Applies to the verbs listed in ``verbs``; integer tensors and tensors
    under ``min_tensor_bytes`` always pass through unquantized."""
    enabled = False
    scheme = "int8_block"           # none | int8_block | onebit
    dtype = "int8"                  # wire dtype (int8 is the only codec)
    block_size = 256                # elements per absmax scale block
    min_tensor_bytes = 1024         # smaller tensors ride full precision
    verbs = []                      # [] -> all of QUANTIZABLE_VERBS

    def _validate(self):
        from deepspeed_tpu.comm.quantize import (QUANT_SCHEMES,
                                                 QUANTIZABLE_VERBS)
        if self.scheme not in QUANT_SCHEMES:
            raise ValueError(
                f"comm.quantization.scheme must be one of {QUANT_SCHEMES}, "
                f"got {self.scheme!r}")
        if str(self.dtype) != "int8":
            raise ValueError(
                "comm.quantization.dtype: only 'int8' is implemented, got "
                f"{self.dtype!r}")
        if int(self.block_size) < 8:
            raise ValueError("comm.quantization.block_size must be >= 8")
        if int(self.min_tensor_bytes) < 0:
            raise ValueError(
                "comm.quantization.min_tensor_bytes must be >= 0")
        self.verbs = list(self.verbs or QUANTIZABLE_VERBS)
        for v in self.verbs:
            if v not in QUANTIZABLE_VERBS:
                raise ValueError(
                    f"comm.quantization.verbs: {v!r} is not quantizable "
                    f"(expected a subset of {QUANTIZABLE_VERBS})")


class MemoryConfig(DeepSpeedConfigModel):
    """``"memory"`` top-level block: the tiered-memory engine
    (``runtime/tiered_store.py``, ZeRO-Infinity-style HBM ⇄ pinned host
    ⇄ NVMe).  ``placement_policy`` picks the default tier for tensors
    above ``persistence_threshold`` numel (smaller ones stay
    device-resident); ``quantize_tiers`` stores float host/NVMe payloads
    as the PR 15 blockwise-int8 codec with fp32 scale sidecars.  Budgets
    are bytes; 0 / None disables the bound."""
    placement_policy = "host"       # resident | host | nvme
    nvme_dir = None                 # required when any placement is nvme
    host_budget_bytes = 0           # spill host -> nvme past this
    hbm_budget_bytes = 0            # evict staged device copies past this
    persistence_threshold = 0       # numel <= threshold pins to hbm
    quantize_tiers = False          # int8 payloads on host/nvme tiers
    quant_block = 256               # codec block (elements per scale)
    overrides = {}                  # name-prefix -> tier
    aio = {}                        # AsyncIOHandle kwargs

    def _validate(self):
        tiers = ("resident", "hbm", "host", "nvme")
        if self.placement_policy not in tiers:
            raise ValueError(
                f"memory.placement_policy must be one of {tiers}, got "
                f"{self.placement_policy!r}")
        # "resident" is the user-facing alias for the hbm tier
        if self.placement_policy == "resident":
            self.placement_policy = "hbm"
        for k in ("host_budget_bytes", "hbm_budget_bytes",
                  "persistence_threshold"):
            if int(getattr(self, k) or 0) < 0:
                raise ValueError(f"memory.{k} must be >= 0")
        if int(self.quant_block) < 8:
            raise ValueError("memory.quant_block must be >= 8")
        if self.placement_policy == "nvme" and not self.nvme_dir:
            raise ValueError(
                "memory.placement_policy 'nvme' needs memory.nvme_dir")
        for name, tier in dict(self.overrides or {}).items():
            t = "hbm" if tier == "resident" else tier
            if t not in ("hbm", "host", "nvme"):
                raise ValueError(
                    f"memory.overrides[{name!r}]: unknown tier {tier!r}")
            self.overrides[name] = t


class CommConfig(DeepSpeedConfigModel):
    """``"comm"`` top-level block (reference accepts ``comm_*`` sections;
    here it holds the wire-codec policy)."""
    quantization = {}

    def _validate(self):
        if not isinstance(self.quantization, CommQuantizationConfig):
            self.quantization = CommQuantizationConfig(
                self.quantization or {})


class MonitorConfig(DeepSpeedConfigModel):
    enabled = False
    output_path = ""
    job_name = "DeepSpeedJobName"


class TensorBoardConfig(MonitorConfig):
    pass


class WandbConfig(DeepSpeedConfigModel):
    enabled = False
    group = None
    team = None
    project = "deepspeed_tpu"


class CSVConfig(MonitorConfig):
    pass


class TelemetryExportConfig(DeepSpeedConfigModel):
    """``"telemetry.export"`` block: the pull-based metrics exporter
    (``monitor/export.py``) — a rank-0 background HTTP thread serving the
    live registry as Prometheus text (``/metrics``) and a JSON snapshot
    (``/metrics.json``).  Off by default; port 0 binds an ephemeral port
    (the bound address is logged via the ``telemetry/export`` meta
    event)."""
    enabled = False
    host = "127.0.0.1"              # bind address (loopback by default)
    port = 9866                     # 0 -> ephemeral

    def _validate(self):
        if not (0 <= int(self.port) <= 65535):
            raise ValueError("telemetry.export.port must be in [0, 65535]")


class TelemetryDistributedConfig(DeepSpeedConfigModel):
    """``"telemetry.distributed"`` block: per-rank telemetry shards and
    cross-rank aggregation (``monitor/aggregate.py``).  Enabled, EVERY
    process writes its own ``events.rank{N}.jsonl`` shard (rank stamped
    into each record) and rank 0 aggregates the shards into step-time
    skew, per-collective arrival spread, comm bandwidth, and a straggler
    verdict — served on the exporter's ``/cluster`` endpoint and folded
    into the stall watchdog and ``health()``."""
    enabled = False
    shard_dir = ""                  # "" -> <output_path>/<job_name>
    skew_threshold = 2.0            # straggler = beyond this multiple of
    #                                 the cross-rank median step time
    straggler_window = 32           # aligned steps in the verdict window

    def _validate(self):
        if float(self.skew_threshold) <= 1.0:
            raise ValueError(
                "telemetry.distributed.skew_threshold must be > 1.0 "
                "(a multiple of the median; <= 1 flags healthy ranks)")
        if int(self.straggler_window) < 1:
            raise ValueError(
                "telemetry.distributed.straggler_window must be >= 1")


class TelemetryProfilingConfig(DeepSpeedConfigModel):
    """``"telemetry.profiling"`` block: the performance observability
    plane (``monitor/profiling.py``) — compile tracing with a
    recompile-storm verdict, per-span HBM attribution with a
    monotonic-growth leak detector, and the live roofline gauges.  Off
    by default; enabled it costs the hot path host-side fingerprinting
    and periodic allocator-stat reads, never a device sync."""
    enabled = False
    snapshot_interval = 8           # steps between HBM live-buffer samples
    storm_threshold = 3             # jit misses within the window -> storm
    storm_window_s = 60.0           # sliding storm window (seconds)
    leak_window = 8                 # consecutive growing samples -> leak
    peak_hbm_gbps = 0.0             # bandwidth-roofline peak override;
    #                                 0 -> chip table (comm/topology_model)

    def _validate(self):
        if int(self.snapshot_interval) < 1:
            raise ValueError(
                "telemetry.profiling.snapshot_interval must be >= 1")
        if int(self.storm_threshold) < 1:
            raise ValueError(
                "telemetry.profiling.storm_threshold must be >= 1")
        if float(self.storm_window_s) <= 0:
            raise ValueError(
                "telemetry.profiling.storm_window_s must be > 0")
        if int(self.leak_window) < 2:
            raise ValueError(
                "telemetry.profiling.leak_window must be >= 2 "
                "(growth needs at least two samples)")


class TelemetryIncidentsConfig(DeepSpeedConfigModel):
    """``"telemetry.incidents"`` block: the incident plane
    (``monitor/incidents.py``) — an always-on flight-recorder ring over
    recent telemetry events, a multi-window SLO burn-rate alerter, and a
    bundle writer that every verdict source (stall, recompile storm,
    straggler, leak, replica kill/fence, SLO burn) triggers.  Off by
    default; enabled it costs one deque append per emitted event."""
    enabled = False
    ring_capacity = 2048            # flight-recorder events kept
    ring_max_age_s = 600.0          # ...and no older than this at dump
    burn_windows = []               # [[window_s, miss_rate], ...];
    #                                 [] -> ((60, 0.5), (300, 0.1))
    burn_min_requests = 8           # SLO terminals needed per window
    cooldown_s = 60.0               # per-trigger-kind bundle cooldown
    bundle_dir = ""                 # "" -> <telemetry out dir>/incidents
    max_bundles = 16                # oldest bundle dirs pruned past this

    def _validate(self):
        if int(self.ring_capacity) < 1:
            raise ValueError(
                "telemetry.incidents.ring_capacity must be >= 1")
        if float(self.ring_max_age_s) <= 0:
            raise ValueError(
                "telemetry.incidents.ring_max_age_s must be > 0")
        if int(self.burn_min_requests) < 1:
            raise ValueError(
                "telemetry.incidents.burn_min_requests must be >= 1")
        if float(self.cooldown_s) < 0:
            raise ValueError(
                "telemetry.incidents.cooldown_s must be >= 0")
        if int(self.max_bundles) < 1:
            raise ValueError(
                "telemetry.incidents.max_bundles must be >= 1")
        for w in (self.burn_windows or []):
            try:
                pair = ((w.get("window_s"), w.get("threshold"))
                        if isinstance(w, dict) else tuple(w))
                ok = (len(pair) == 2 and float(pair[0]) > 0 and
                      0.0 < float(pair[1]) <= 1.0)
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValueError(
                    "telemetry.incidents.burn_windows entries must be "
                    "[window_s > 0, 0 < miss_rate <= 1] pairs")


class TelemetryAttributionConfig(DeepSpeedConfigModel):
    """``"telemetry.attribution"`` block: the time-attribution plane
    (``monitor/attribution.py``) — per-step exposed-comm decomposition
    into the frozen ``step/attr/*`` gauges (compute / exposed collective
    / input wait / host sync / compile, headline
    ``exposed_comm_frac``) plus the exporter's ``GET /attribution``
    snapshot of recent step decompositions and serving critical paths.
    Off by default; enabled it costs one interval append per
    span/comm/compile event."""
    enabled = False
    history = 64                    # per-step decompositions retained
    serve_history = 256             # serving critical paths retained

    def _validate(self):
        if int(self.history) < 1:
            raise ValueError(
                "telemetry.attribution.history must be >= 1")
        if int(self.serve_history) < 1:
            raise ValueError(
                "telemetry.attribution.serve_history must be >= 1")


class TelemetryConfig(DeepSpeedConfigModel):
    """``"telemetry"`` block: the unified JSONL event stream
    (``monitor/telemetry.py``) plus the step-stall watchdog and the
    optional pull-based metrics exporter."""
    enabled = False
    output_path = ""                # dir for events.jsonl ("" -> ./telemetry)
    job_name = "DeepSpeedJobName"
    max_file_mb = 64                # size-based rotation threshold
    max_files = 4                   # rotated generations kept
    hbm_gauges = True               # per-step device memory_stats() gauges
    stall_watchdog = True
    stall_factor = 10.0             # stall when gap > factor * median step
    stall_min_secs = 1.0            # floor on the stall threshold
    stall_poll_secs = 1.0           # watchdog poll interval
    export = {}                     # TelemetryExportConfig sub-block
    distributed = {}                # TelemetryDistributedConfig sub-block
    profiling = {}                  # TelemetryProfilingConfig sub-block
    incidents = {}                  # TelemetryIncidentsConfig sub-block
    attribution = {}                # TelemetryAttributionConfig sub-block

    def _validate(self):
        if not isinstance(self.export, TelemetryExportConfig):
            self.export = TelemetryExportConfig(self.export or {})
        if not isinstance(self.distributed, TelemetryDistributedConfig):
            self.distributed = TelemetryDistributedConfig(
                self.distributed or {})
        if not isinstance(self.profiling, TelemetryProfilingConfig):
            self.profiling = TelemetryProfilingConfig(self.profiling or {})
        if not isinstance(self.incidents, TelemetryIncidentsConfig):
            self.incidents = TelemetryIncidentsConfig(self.incidents or {})
        if not isinstance(self.attribution, TelemetryAttributionConfig):
            self.attribution = TelemetryAttributionConfig(
                self.attribution or {})


class AsyncPipelineConfig(DeepSpeedConfigModel):
    """``"async_pipeline"`` block: keeps the step loop's host side off the
    dispatch critical path — a background thread prefetches + shards batch
    n+k while step n runs, and metric readback is deferred to a
    ``sync_interval`` boundary (or a drainer thread) instead of a per-step
    device sync."""
    enabled = False
    prefetch_depth = 2     # device batches parked ahead of the consumer
    sync_interval = 1      # steps between batched metric readbacks
    io_workers = 0         # host-side sample-fetch threads (collate pool)
    drain_thread = False   # drain metrics from a thread instead of on-interval

    def _validate(self):
        if int(self.prefetch_depth) < 1:
            raise ValueError("async_pipeline.prefetch_depth must be >= 1")
        if int(self.sync_interval) < 1:
            raise ValueError("async_pipeline.sync_interval must be >= 1")
        if int(self.io_workers) < 0:
            raise ValueError("async_pipeline.io_workers must be >= 0")


class ResilienceConfig(DeepSpeedConfigModel):
    """``"resilience"`` block: the fault-tolerance layer
    (``runtime/resilience.py``) — durable atomic checkpoints with
    validation + fallback, retry policy for checkpoint/host-fs I/O,
    preemption handling, the divergence sentinel, and the deterministic
    fault-injection harness."""
    enabled = True                  # durable ckpt protocol + retries
    max_retries = 3                 # checkpoint/fs I/O retry budget
    retry_backoff_secs = 0.5        # first-retry backoff
    retry_backoff_max_secs = 30.0   # backoff cap
    retry_jitter = 0.25             # jitter fraction on each delay
    keep_last = 0                   # committed tags retained (0 = all)
    checksum = False                # per-leaf crc32 in the manifest
    preemption_handler = False      # hook SIGTERM/SIGINT
    ckpt_dir = ""                   # emergency-save / auto-restore dir
    divergence_sentinel = False     # watch loss / overflow streaks
    max_consecutive_skips = 8       # fp16 skip streak that counts as divergence
    sentinel_interval = 1           # steps between sentinel host readbacks
    on_divergence = "halt"          # "halt" | "restore"
    dataloader_max_retries = 2      # prefetch-worker transient retry budget
    dataloader_retry_backoff_secs = 0.05
    fault_injection = {}            # deterministic FaultInjector spec

    def _validate(self):
        if int(self.max_retries) < 0:
            raise ValueError("resilience.max_retries must be >= 0")
        if int(self.keep_last) < 0:
            raise ValueError("resilience.keep_last must be >= 0")
        if self.on_divergence not in ("halt", "restore"):
            raise ValueError("resilience.on_divergence must be 'halt' or "
                             "'restore'")
        if int(self.sentinel_interval) < 1:
            raise ValueError("resilience.sentinel_interval must be >= 1")
        if int(self.dataloader_max_retries) < 0:
            raise ValueError("resilience.dataloader_max_retries must be >= 0")


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled = False
    profile_step = 1
    module_depth = -1
    top_modules = 1
    detailed = True
    output_file = None
    # per-device peak TFLOP/s for the live train/mfu gauge; 0 -> look up
    # the chip table (comm/topology_model.py) from the device kind.  The
    # gauge emits only when a peak is known (set this on CPU/test runs).
    peak_tflops = 0.0


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    partition_activations = False
    contiguous_memory_optimization = False
    cpu_checkpointing = False
    number_checkpoints = None
    synchronize_checkpoint_boundary = False
    profile = False
    # TPU extension: remat policy name passed to jax.checkpoint
    policy = "nothing_saveable"


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation = "Warn"
    load_universal = False
    use_node_local_storage = False
    parallel_write = {}
    # which checkpoint engine backs save/load: "sync" (blocking orbax
    # StandardCheckpointer) or "async"/"nebula" (orbax AsyncCheckpointer —
    # the reference NebulaCheckpointEngine's background-snapshot semantics;
    # the durable commit protocol waits for the flush before the marker)
    engine = "sync"

    def _validate(self):
        if str(self.engine).lower() not in ("sync", "async", "nebula",
                                            "torch", "orbax"):
            raise ValueError(
                "checkpoint.engine must be one of sync|async|nebula "
                f"(got {self.engine!r})")


class MeshSection(DeepSpeedConfigModel):
    pp = 1
    dp = 1
    fsdp = -1
    sp = 1
    tp = 1
    ep = 1


class OptimizerConfig:
    def __init__(self, param_dict):
        self.type = param_dict.get(C.TYPE)
        self.params = dict(param_dict.get(C.OPTIMIZER_PARAMS, {}))
        self.legacy_fusion = param_dict.get(C.LEGACY_FUSION, False)


class SchedulerConfig:
    def __init__(self, param_dict):
        self.type = param_dict.get(C.TYPE)
        self.params = dict(param_dict.get(C.SCHEDULER_PARAMS, {}))


class DeepSpeedConfig:

    def __init__(self, config: Union[str, Dict[str, Any]], mesh=None,
                 world_size: int = None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(
                    f"Config file {config} not found")
            with open(config) as f:
                self._param_dict = json.load(f)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise DeepSpeedConfigError(
                f"Expected a dict or json path, got {type(config)}")

        # autotuning-v2: when the config names a persisted overlay
        # (autotuning.overlay_path), deep-merge the tuned fragment over
        # the user config before any parsing — initialize() consumes
        # tuned winners with zero caller changes.  Provenance (trial id +
        # snapshot hash) is kept for audit.
        from deepspeed_tpu.autotuning.overlay import maybe_apply_overlay
        self._param_dict, self.overlay_provenance = maybe_apply_overlay(
            self._param_dict)

        pd = self._param_dict
        self._warn_unknown_keys(pd)
        self._note_inert_sparse_attention(pd)
        self.mesh_config = self._parse_mesh(pd.get(C.MESH, {}))

        if world_size is None:
            try:
                import jax
                world_size = jax.device_count()
            except Exception:
                world_size = 1
        self.world_size = world_size

        # effective data-parallel degree for the batch triangle (EP overlays
        # DP, so the ep axis carries batch shards too)
        topo = self.mesh_config.resolve(world_size)
        self.data_parallel_size = topo.dp * topo.fsdp * topo.ep

        self.train_batch_size = pd.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = pd.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = pd.get(C.GRADIENT_ACCUMULATION_STEPS)
        self._maybe_apply_elasticity(pd)
        self._configure_train_batch_size()

        self.steps_per_print = get_scalar_param(pd, C.STEPS_PER_PRINT,
                                                C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get_scalar_param(pd, C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.wall_clock_breakdown = get_scalar_param(
            pd, C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.gradient_clipping = get_scalar_param(pd, C.GRADIENT_CLIPPING,
                                                  C.GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = get_scalar_param(pd, C.PRESCALE_GRADIENTS,
                                                   C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = get_scalar_param(
            pd, C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = get_scalar_param(
            pd, C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)
        self.seed = get_scalar_param(pd, C.SEED, C.SEED_DEFAULT)

        self.zero_config = DeepSpeedZeroConfig(pd.get(C.ZERO_OPTIMIZATION, {}))
        self.fp16_config = FP16Config(pd.get(C.FP16, {}))
        bf16_dict = pd.get(C.BFLOAT16, pd.get(C.BFLOAT16_OLD, {}))
        self.bf16_config = BF16Config(bf16_dict)
        if self.fp16_config.enabled and self.bf16_config.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")

        # data_types.grad_accum_dtype (reference runtime/config.py:943):
        # the dtype the GAS carry / gradient tree rides in.  bfloat16
        # halves grad HBM — the knob that lets a 1B-param model train on
        # one 16 GB chip (Adam math still accumulates fp32 per step).
        dt = pd.get("data_types") or {}
        self.grad_accum_dtype = self._parse_grad_accum_dtype(
            dt.get("grad_accum_dtype"))

        opt_dict = pd.get(C.OPTIMIZER)
        self.optimizer_config = OptimizerConfig(opt_dict) if opt_dict else None
        sched_dict = pd.get(C.SCHEDULER)
        self.scheduler_config = SchedulerConfig(sched_dict) if sched_dict else None

        self.comms_config = CommsConfig(pd.get(C.COMMS_LOGGER, {}))
        self.comm_config = CommConfig(pd.get(C.COMM, {}))
        self.comm_quantization = self.comm_config.quantization
        self.memory_config = MemoryConfig(pd.get("memory", {}))
        self.telemetry_config = TelemetryConfig(pd.get(C.TELEMETRY, {}))
        self.async_pipeline_config = AsyncPipelineConfig(
            pd.get(C.ASYNC_PIPELINE, {}))
        self.monitor_config = {
            "tensorboard": TensorBoardConfig(pd.get(C.MONITOR_TENSORBOARD, {})),
            "wandb": WandbConfig(pd.get(C.MONITOR_WANDB, {})),
            "csv_monitor": CSVConfig(pd.get(C.MONITOR_CSV, {})),
            # the JSONL fourth writer shares the telemetry sink/config
            "telemetry": self.telemetry_config,
        }
        self.flops_profiler_config = FlopsProfilerConfig(pd.get(C.FLOPS_PROFILER, {}))
        self.activation_checkpointing_config = ActivationCheckpointingConfig(
            pd.get(C.ACTIVATION_CHECKPOINTING, {}))
        self.checkpoint_config = CheckpointConfig(pd.get(C.CHECKPOINT, {}))
        self.resilience_config = ResilienceConfig(pd.get(C.RESILIENCE, {}))

        self.elasticity_enabled = bool(pd.get(C.ELASTICITY, {}).get("enabled", False))
        self.data_efficiency_config = pd.get(C.DATA_EFFICIENCY, {})
        self.curriculum_learning_config = pd.get(C.CURRICULUM_LEARNING_LEGACY, {})
        self.progressive_layer_drop_config = pd.get(
            "progressive_layer_drop", {})
        self.eigenvalue_config = pd.get("eigenvalue", {})
        self.compression_config = pd.get(C.COMPRESSION_TRAINING, {})
        self.pipeline_config = pd.get(C.PIPELINE, {})

        self._do_sanity_check()


    # every top-level key this config understands; a typo like
    # "zero_optimisation" silently no-ops otherwise (the reference ignores
    # unknown keys too — warning is strictly more helpful)
    _KNOWN_TOP_LEVEL_KEYS = frozenset({
        C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
        C.GRADIENT_ACCUMULATION_STEPS, C.OPTIMIZER, C.SCHEDULER, C.FP16,
        C.BFLOAT16, C.BFLOAT16_OLD, C.AMP, C.GRADIENT_CLIPPING,
        C.PRESCALE_GRADIENTS, C.GRADIENT_PREDIVIDE_FACTOR,
        C.STEPS_PER_PRINT, C.WALL_CLOCK_BREAKDOWN, C.DUMP_STATE,
        C.SPARSE_GRADIENTS, C.ZERO_OPTIMIZATION, C.COMMS_LOGGER, C.COMM,
        C.MESH,
        C.ACTIVATION_CHECKPOINTING, C.FLOPS_PROFILER,
        C.MONITOR_TENSORBOARD, C.MONITOR_WANDB, C.MONITOR_CSV, C.TELEMETRY,
        C.ASYNC_PIPELINE, C.RESILIENCE,
        C.DATA_EFFICIENCY, C.CURRICULUM_LEARNING_LEGACY, C.CHECKPOINT,
        C.ELASTICITY, C.COMPRESSION_TRAINING,
        C.PIPELINE, C.SEED, C.ZERO_ALLOW_UNTESTED_OPTIMIZER,
        "eigenvalue", "progressive_layer_drop", "autotuning",
        # serving-side knobs (page size, scheduler, fleet) ride the same
        # config file so one tuned overlay can cover both domains; the
        # training engine ignores the block, create_serving_engine()
        # consumes it
        "serving",
        # tiered-memory engine (runtime/tiered_store.py)
        "memory",
        # reference top-level keys accepted for config portability but
        # intentionally inert here (amp -> XLA owns mixed precision, the
        # dtype/memory knobs have no TPU analogue); listed so ported
        # configs don't warn
        "gradient_accumulation_dtype", "communication_data_type",
        "memory_breakdown",
        # data_types IS wired (grad_accum_dtype); nebula /
        # disable_allgather / zero_force_ds_cpu_optimizer are ZeRO-impl
        # knobs with no TPU analogue — accepted so ported configs don't
        # warn (reference runtime/config.py:943,:954)
        "data_types", "nebula", "disable_allgather",
        "zero_force_ds_cpu_optimizer",
        # sparse_attention gets its own notice (_note_inert_sparse_attention)
        "sparse_attention",
        # emitted by Autotuner.tune(): model-side knob winners (remat
        # policy, attention tile sizes) for the CALLER to apply when
        # rebuilding the model; informational for the engine itself
        "autotuning_model_overrides",
    })

    @staticmethod
    def _parse_grad_accum_dtype(name):
        if name is None:
            return None
        table = {"fp32": "float32", "float32": "float32",
                 "bf16": "bfloat16", "bfloat16": "bfloat16",
                 "fp16": "float16", "float16": "float16"}
        key = str(name).lower()
        if key not in table:
            raise DeepSpeedConfigError(
                "data_types.grad_accum_dtype must be one of "
                f"{sorted(set(table))}, got {name!r}")
        return table[key]

    def _note_inert_sparse_attention(self, pd):
        # 'sparse_attention' names functionality this repo DOES ship
        # (ops/sparse_attention, reference runtime/config.py:918) but the
        # engine config doesn't wire it — models opt in via the ops API.
        # One explicit line, not a silent swallow and not a scary
        # unknown-key warning.
        if "sparse_attention" in pd:
            logger.info(
                "config key 'sparse_attention' is accepted for "
                "portability but not engine-wired; enable sparsity via "
                "the model config / deepspeed_tpu.ops.sparse_attention "
                "(SparseSelfAttention / sparsity configs)")

    def _warn_unknown_keys(self, pd):
        unknown = sorted(k for k in pd if k not in
                         self._KNOWN_TOP_LEVEL_KEYS)
        if unknown:
            import difflib
            for k in unknown:
                close = difflib.get_close_matches(
                    k, self._KNOWN_TOP_LEVEL_KEYS, n=1)
                hint = f" (did you mean '{close[0]}'?)" if close else ""
                logger.warning(
                    f"config key '{k}' is not recognized and will be "
                    f"ignored{hint}")

    @staticmethod
    def _parse_mesh(mesh_dict) -> TopologyConfig:
        sec = MeshSection(mesh_dict)
        return TopologyConfig(pp=sec.pp, dp=sec.dp, fsdp=sec.fsdp,
                              sp=sec.sp, tp=sec.tp, ep=sec.ep)

    def _maybe_apply_elasticity(self, pd):
        """Elastic mode resolves the batch triangle FOR THE CURRENT WORLD
        SIZE during config parsing (parity: reference runtime/config.py
        766-806 — compute_elastic_config runs inside DeepSpeedConfig, so a
        restarted worker at a new world size gets the right batch without
        touching its config file)."""
        esec = pd.get(C.ELASTICITY, {})
        if not esec.get("enabled", False):
            return
        from deepspeed_tpu.elasticity import compute_elastic_config
        # pass the FULL param dict: compute_elastic_config also validates
        # that fixed batch keys don't conflict with elastic mode.
        # world_size is the TOTAL chip count (the solver divides by its
        # own model_parallel_size — which should match the mesh's tp so
        # the derived micro batch lines up with our dp degree)
        batch, valid, micro = compute_elastic_config(
            pd, world_size=max(1, self.world_size))
        self.train_batch_size = batch
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = None   # triangle derives it
        logger.info(f"elasticity: batch={batch} micro={micro} for "
                    f"world={self.world_size}")

    # ------------------------------------------------------------------
    # Batch-size triangle: train = micro × gas × dp_world
    # (parity: reference runtime/config.py _batch_assertion / _set_batch_related_parameters)
    # ------------------------------------------------------------------
    def _configure_train_batch_size(self):
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        dp = max(1, self.data_parallel_size)

        if train is not None and micro is not None and gas is not None:
            pass
        elif train is not None and micro is not None:
            gas = train // (micro * dp)
        elif train is not None and gas is not None:
            micro = train // (gas * dp)
        elif micro is not None and gas is not None:
            train = micro * gas * dp
        elif train is not None:
            gas = 1
            micro = train // dp
        elif micro is not None:
            gas = 1
            train = micro * dp
        else:
            raise DeepSpeedConfigError(
                "At least one of train_batch_size / "
                "train_micro_batch_size_per_gpu must be set")

        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas
        self._batch_assertion()

    def _batch_assertion(self):
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        dp = max(1, self.data_parallel_size)
        if train <= 0:
            raise DeepSpeedConfigError(
                f"train_batch_size: {train} must be positive")
        if micro <= 0:
            raise DeepSpeedConfigError(
                f"micro_batch_size: {micro} must be positive")
        if gas <= 0:
            raise DeepSpeedConfigError(
                f"gradient_accumulation_steps: {gas} must be positive")
        if train != micro * gas * dp:
            raise DeepSpeedConfigError(
                f"Check batch-size settings: train_batch_size={train} must "
                f"equal micro_batch={micro} * gradient_accumulation={gas} "
                f"* dp_world={dp}")

    def _do_sanity_check(self):
        if self.zero_config.stage > 0 and self.fp16_config.enabled:
            if self.fp16_config.fp16_master_weights_and_grads and self.zero_config.stage != 2:
                raise DeepSpeedConfigError(
                    "fp16_master_weights_and_grads only supported with ZeRO-2")
        if self.optimizer_config and self.optimizer_config.type:
            from deepspeed_tpu.runtime.optimizers import OPTIMIZER_REGISTRY
            if self.optimizer_config.type.lower() not in OPTIMIZER_REGISTRY and \
                    not self._param_dict.get(C.ZERO_ALLOW_UNTESTED_OPTIMIZER, False):
                logger.warning(
                    f"Optimizer '{self.optimizer_config.type}' is not built in; "
                    "will fall back to user-supplied optax transform")

    # Convenience parity accessors used across the engine
    @property
    def zero_enabled(self):
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self):
        return self.zero_config.stage

    @property
    def fp16_enabled(self):
        return self.fp16_config.enabled

    @property
    def bfloat16_enabled(self):
        return self.bf16_config.enabled

    @property
    def loss_scale(self):
        return self.fp16_config.loss_scale

    @property
    def initial_dynamic_scale(self):
        return 2 ** self.fp16_config.initial_scale_power

    @property
    def dynamic_loss_scale(self):
        return self.fp16_config.loss_scale == 0

    def print(self, name="DeepSpeedConfig"):
        logger.info(f"{name}:")
        logger.info(json.dumps(self._param_dict, indent=2, sort_keys=True, default=str))
