"""Block Hessian eigenvalue estimation (MoQ quantization scheduling).

Parity: reference ``runtime/eigenvalue.py`` (``Eigenvalue``: power iteration
on per-block Hessians via double backward; the engine feeds the values to
the quantizer to schedule per-layer quantization aggressiveness).

TPU design: Hessian-vector products are a one-liner under jax
(``jvp`` of ``grad``), so the power iteration is exact and jittable —
no retain_graph bookkeeping.
"""

from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np


class Eigenvalue:

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "layers", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    # ------------------------------------------------------------------
    def _hvp(self, loss_fn: Callable, params, vec):
        """Hessian-vector product: jvp of grad."""
        grad_fn = jax.grad(loss_fn)
        _, hv = jax.jvp(grad_fn, (params,), (vec,))
        return hv

    def compute_eigenvalue(self, loss_fn: Callable, params,
                           rng=None) -> float:
        """Largest Hessian eigenvalue of ``loss_fn(params)`` by power
        iteration over the whole params block."""
        rng = rng if rng is not None else jax.random.key(0)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, jnp.shape(x), jnp.float32)
                      for k, x in zip(keys, leaves)])

        def normalize(t):
            n = jnp.sqrt(sum(jnp.vdot(x, x)
                             for x in jax.tree_util.tree_leaves(t)))
            n = jnp.maximum(n, self.stability)
            return jax.tree_util.tree_map(lambda x: x / n, t), n

        v, _ = normalize(v)
        eig = 0.0
        for it in range(self.max_iter):
            hv = self._hvp(loss_fn, params, v)
            v, norm = normalize(hv)
            new_eig = float(norm)
            if eig and abs(new_eig - eig) / max(abs(eig), 1e-12) < self.tol:
                eig = new_eig
                break
            eig = new_eig
        return eig

    def compute_layer_eigenvalues(self, loss_fn: Callable, params,
                                  layer_params: List[Any],
                                  rng=None) -> List[float]:
        """Per-block eigenvalues: power-iterate with perturbations confined
        to each block (other blocks' tangents zero) — the reference's
        per-layer scheme."""
        rng = rng if rng is not None else jax.random.key(0)
        out = []
        for i, block in enumerate(layer_params):
            def block_loss(b):
                # splice block back into params by object identity
                def swap(leaf):
                    return b if leaf is block else leaf
                return loss_fn(jax.tree_util.tree_map(
                    swap, params, is_leaf=lambda x: x is block))
            out.append(self.compute_eigenvalue(
                block_loss, block, jax.random.fold_in(rng, i)))
        return out

    def post_process(self, eigenvalues: List[float]) -> List[float]:
        """Reference normalises by the max so the quantizer gets [0,1]."""
        mx = max(eigenvalues) if eigenvalues else 1.0
        return [e / mx if mx > 0 else 0.0 for e in eigenvalues]
