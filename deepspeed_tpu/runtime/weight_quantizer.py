"""Checkpoint-load-time weight quantization (MoQ inference path).

Parity: reference ``deepspeed/runtime/weight_quantizer.py`` —
``WeightQuantization`` quantizes a model's transformer matmul weights to
intN at checkpoint-load time, with category-aware group counts
(``mlp_extra_grouping`` doubles groups for the 4x-wide MLP projections,
BERT QKV triples them) and per-category scale bookkeeping that is merged
into one scale tensor the fused inference kernels index
(``merge_scales``/``merge_scales_split`` for TP splits).

TPU redesign: weights live in pytrees, not ``nn.Module`` children, so
``model_quantize`` walks a params pytree and replaces linear-weight leaves
with the same ``{"qv", "qs", "qz"}`` records the inference engine's int8
path consumes (``inference/engine.py _quantize_tree`` /
``ops/quantizer.quantize``) — dequantization then happens inside jit where
XLA fuses it into the consuming matmul.  The Megatron state-dict surface
(``sd_quantize_megatron``) and the scale-merge helpers keep the reference's
shapes so TP-degree resharding of scales round-trips.
"""

import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

QKV_PATTERNS = ("attention.query_key_value.weight",)
DENSE_PATTERNS = ("attention.dense.weight",)
MLP_H4H_PATTERNS = ("mlp.dense_h_to_4h.weight",)
MLP_4HH_PATTERNS = ("mlp.dense_4h_to_h.weight",)


class WeightQuantization:
    """Reference surface ``weight_quantizer.py:8``."""

    def __init__(self, mlp_extra_grouping: bool = True, mp_size: int = 1):
        self.dense_scales: List[np.ndarray] = []
        self.qkv_scales: List[np.ndarray] = []
        self.mlp4hh_scales: List[np.ndarray] = []
        self.mlph4h_scales: List[np.ndarray] = []
        self.mlp_extra_grouping = mlp_extra_grouping
        self.mp_size = max(1, int(mp_size))

    # -- core groupwise symmetric quant --------------------------------
    def quantize_data(self, data, quantize_bits: int, groups: int,
                      key: Optional[str] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Flat groupwise symmetric intN: scale = 2^bits / (2*max|g|), int
        values clamped to the signed range (reference ``quantize_data``)."""
        if quantize_bits > 8:
            raise ValueError(
                f"quantize_bits={quantize_bits}: int8 storage holds at most "
                "8 bits; a wider cast would silently wrap")
        arr = np.asarray(data, np.float32)
        groups = max(1, int(np.gcd(arr.size, max(1, int(groups)))))
        flat = arr.reshape(groups, -1)
        max_d = np.abs(flat).max(axis=-1, keepdims=True)
        scale = float(1 << quantize_bits) / (2.0 * max_d + 1e-5)
        lo = -(1 << (quantize_bits - 1))
        hi = (1 << (quantize_bits - 1)) - 1
        q = np.clip(np.round(flat * scale), lo, hi).astype(np.int8)
        return q.reshape(arr.shape), scale.reshape(1, -1)

    # -- shape heuristics (reference :31, :35) -------------------------
    def is_mlp(self, data, merge_count: int = 1) -> bool:
        s = np.shape(data)
        if len(s) < 2:
            return False
        return (self.mp_size * s[0] * merge_count) / s[1] == 4 or \
               (self.mp_size * s[1] * merge_count) / s[0] == 4

    def is_qkv(self, data) -> bool:
        s = np.shape(data)
        if len(s) < 2:
            return False
        return (self.mp_size * s[0]) / s[1] == 3 or \
               (self.mp_size * s[1]) / s[0] == 3

    # -- categorised quantization (reference Quantize :39) -------------
    def Quantize(self, value_list: List[Any], quantize_bits: int,
                 groups: int, key: str, merge_dim: int = 0) -> List[Any]:
        if self.mlp_extra_grouping and \
                self.is_mlp(value_list[0], merge_count=len(value_list)):
            groups *= 2
        q_scales = []
        for i, data in enumerate(value_list):
            q, scale = self.quantize_data(data, quantize_bits, groups, key)
            q_scales.append(scale.reshape(-1))
            value_list[i] = q
        stacked = np.stack(q_scales)            # [shards, G]
        if merge_dim == 1:
            # row-parallel merges: the merged weight interleaves shards
            # within each group span, so scales order group-major
            # (reference cat(dim=1) on (G,1) scales)
            stacked = stacked.T
        inv = 1.0 / stacked.reshape(1, -1)
        if any(p in key for p in MLP_4HH_PATTERNS):
            self.mlp4hh_scales.append(inv)
        elif any(p in key for p in MLP_H4H_PATTERNS):
            self.mlph4h_scales.append(inv)
        elif any(p in key for p in QKV_PATTERNS):
            self.qkv_scales.append(inv)
        else:
            self.dense_scales.append(inv)
        return value_list

    # -- scale merging (reference :65, :76, :87) -----------------------
    @staticmethod
    def merge_layer_scales(layer_scales: List[np.ndarray]) -> np.ndarray:
        max_dim = max(s.shape[-1] for s in layer_scales)
        padded = [np.concatenate(
            [s, np.zeros((1, max_dim - s.shape[-1]), s.dtype)], axis=-1)
            if s.shape[-1] < max_dim else s for s in layer_scales]
        return np.concatenate(padded, axis=0)[None]

    def merge_scales(self) -> np.ndarray:
        all_scales = [
            self.merge_layer_scales([qkv, dense, h4h, fhh])
            for dense, qkv, fhh, h4h in zip(
                self.dense_scales, self.qkv_scales,
                self.mlp4hh_scales, self.mlph4h_scales)]
        return np.concatenate(all_scales, axis=0)

    def merge_scales_split(self, split_count: int) -> List[List[np.ndarray]]:
        """Per-TP-rank scale groups for a checkpoint being split
        ``split_count``-ways (reference ``merge_scales_split``)."""
        split_count = max(1, int(split_count))
        out: List[List[np.ndarray]] = [[] for _ in range(split_count)]
        for dense, qkv, fhh, h4h in zip(
                self.dense_scales, self.qkv_scales,
                self.mlp4hh_scales, self.mlph4h_scales):
            parts = [np.array_split(s.reshape(-1), split_count)
                     for s in (qkv, dense, h4h, fhh)]
            for r in range(split_count):
                rows = [p[r][None] for p in parts]
                # zero-pad narrower categories (qkv/dense when
                # mlp_extra_grouping doubled the MLP group count) so the
                # per-rank block is rectangular (reference merge_scales_split)
                width = max(x.shape[1] for x in rows)
                rows = [np.concatenate(
                    [x, np.zeros((1, width - x.shape[1]), x.dtype)], axis=1)
                    if x.shape[1] < width else x for x in rows]
                out[r].append(np.concatenate(rows, axis=0))
        return out

    # -- Megatron state-dict surface (reference :112) ------------------
    def sd_quantize_megatron(self, sd: Dict[str, Any], quantize_bits: int,
                             groups: int
                             ) -> Tuple[Dict[str, Any], np.ndarray]:
        sd = dict(sd)
        patterns = (QKV_PATTERNS + DENSE_PATTERNS + MLP_H4H_PATTERNS
                    + MLP_4HH_PATTERNS)
        for key in list(sd):
            if any(p in key for p in patterns):
                sd[key] = self.Quantize([sd[key]], quantize_bits, groups,
                                        key=key)[0]
        return sd, self.merge_scales()

    # -- pytree surface (reference model_quantize :124) ----------------
    # our model layout: per-layer stacked weights; category by leaf name.
    # fused-QKV leaves get 3x groups (reference: BERT qkv, Q/K/V magnitude
    # ranges differ so one scale across them is ~3x coarser); the separate
    # wq/wk/wv leaves of our layout don't need it.
    _QKV_NAMES = ("qkv", "query_key_value")
    _MLP_NAMES = ("w_up", "w_gate", "w_down", "h_to_4h", "4h_to_h",
                  "fc_in", "fc_out")

    def model_quantize(self, params, quantize_bits: int = 8,
                       groups: int = 1, quantize_policy=None):
        """Walk a params pytree; replace matmul-weight leaves with
        ``{"qv": int8, "qs": scale, "qz": zero}`` records (the repo's
        quantized-leaf convention) using category-aware group counts.
        ``quantize_policy``: optional ``{regex: groups_multiplier}`` to
        override category detection per leaf path."""
        from deepspeed_tpu.ops.quantizer import quantize as _q

        def leaf_groups(key: str, leaf) -> Optional[int]:
            lkey = key.lower()
            name = lkey.rsplit("[", 1)[-1].strip("']")
            if "norm" in lkey or "embed" in lkey or "bias" in lkey \
                    or name.endswith("_b") or name == "wg" \
                    or np.ndim(leaf) < 2:
                return None
            if quantize_policy:
                for pat, mult in quantize_policy.items():
                    if re.search(pat, key):
                        return groups * int(mult)
            per_layer = leaf[0] if np.ndim(leaf) >= 3 else leaf
            # explicit names win over shape heuristics: a 3x-FFN w_up must
            # stay in the MLP category even though its ratio matches is_qkv
            if any(n in name for n in self._MLP_NAMES):
                return groups * 2 if self.mlp_extra_grouping else groups
            if any(n in name for n in self._QKV_NAMES) \
                    or self.is_qkv(per_layer):
                return groups * 3
            if self.mlp_extra_grouping and self.is_mlp(per_layer):
                return groups * 2
            return groups

        scales: List[np.ndarray] = []

        def visit(path, leaf):
            key = jax.tree_util.keystr(path)
            g = leaf_groups(key, leaf)
            if g is None:
                return leaf
            arr = np.asarray(leaf)
            g = max(1, int(np.gcd(arr.size, g)))
            qt = _q(arr, groups=g, num_bits=quantize_bits)
            scales.append(np.asarray(qt.scale, np.float32).reshape(1, -1))
            return {"qv": qt.values, "qs": qt.scale, "qz": qt.zero_point}

        qparams = jax.tree_util.tree_map_with_path(visit, params)
        all_scales = (self.merge_layer_scales(scales)[0]
                      if scales else np.zeros((0, 0), np.float32))
        return qparams, all_scales
