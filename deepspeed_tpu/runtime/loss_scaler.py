"""Loss scaling for fp16 training.

Parity: reference ``runtime/fp16/loss_scaler.py`` (``LossScaler``,
``DynamicLossScaler``).  Jit-friendly redesign: the scaler state is a small
pytree carried through the compiled train step, and scale updates are
``jnp.where`` branches — no Python control flow on device values, so the whole
overflow check/skip-step/rescale dance compiles into the step program (the
reference does this eagerly on the host, reference ``stage3.py:1840``).
"""

from typing import NamedTuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    cur_scale: jnp.ndarray       # f32 scalar
    cur_hysteresis: jnp.ndarray  # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    iteration: jnp.ndarray       # i32 scalar


def static_loss_scale_state(scale: float, hysteresis: int = 0) -> LossScaleState:
    return LossScaleState(
        cur_scale=jnp.asarray(scale, jnp.float32),
        cur_hysteresis=jnp.asarray(hysteresis, jnp.int32),
        last_overflow_iter=jnp.asarray(-1, jnp.int32),
        iteration=jnp.asarray(0, jnp.int32),
    )


def dynamic_loss_scale_state(initial_scale_power=16,
                             hysteresis: int = 2) -> LossScaleState:
    # start with the full hysteresis budget (reference DynamicLossScaler
    # initializes cur_hysteresis = delayed_shift)
    return static_loss_scale_state(2.0 ** initial_scale_power,
                                   hysteresis=hysteresis)


def has_inf_or_nan(tree) -> jnp.ndarray:
    """True if any leaf contains inf/nan (reference ``check_overflow``)."""
    import jax
    leaves = jax.tree_util.tree_leaves(tree)
    bad = jnp.asarray(False)
    for leaf in leaves:
        bad = bad | ~jnp.isfinite(jnp.asarray(leaf, jnp.float32)).all()
    return bad


def update_scale(state: LossScaleState, overflow: jnp.ndarray, *,
                 dynamic: bool, scale_factor: float = 2.0,
                 scale_window: int = 1000, min_scale: float = 1.0,
                 hysteresis: int = 2) -> LossScaleState:
    """One step of the dynamic loss-scale automaton, as pure array math.

    overflow → scale/2 (after hysteresis consumed); ``scale_window`` clean
    steps → scale*2.  Mirrors reference ``DynamicLossScaler.update_scale``.
    """
    it = state.iteration
    if not dynamic:
        return state._replace(iteration=it + 1)

    hyst = jnp.where(overflow, jnp.maximum(state.cur_hysteresis - 1, 0),
                     state.cur_hysteresis)
    shrink = overflow & (state.cur_hysteresis <= 1)
    grown_due = (~overflow) & (((it - state.last_overflow_iter) % scale_window) == scale_window - 1)

    new_scale = jnp.where(
        shrink,
        jnp.maximum(state.cur_scale / scale_factor, min_scale),
        jnp.where(grown_due, state.cur_scale * scale_factor, state.cur_scale))
    new_hyst = jnp.where(shrink, jnp.asarray(hysteresis, jnp.int32), hyst)
    new_last = jnp.where(overflow, it, state.last_overflow_iter)
    return LossScaleState(cur_scale=new_scale, cur_hysteresis=new_hyst,
                          last_overflow_iter=new_last, iteration=it + 1)


class HostLossScale:
    """Host-side mirror of :func:`update_scale` for the param-stream path.

    The host-orchestrated (offload / param-stream) paths need the NEXT
    step's loss scale as a python float before dispatch; reading it from
    the device state costs a per-step sync.  This mirror advances the
    identical automaton on host ints/floats — the overflow bool it
    consumes is already fetched for the skip-step decision, so keeping the
    scale on the host adds zero extra device round-trips.  A randomized
    equivalence test pins it step-for-step to :func:`update_scale`.
    """

    def __init__(self, initial_scale, *, dynamic, scale_factor=2.0,
                 scale_window=1000, min_scale=1.0, hysteresis=2):
        self.dynamic = bool(dynamic)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.hysteresis = int(hysteresis)
        self.cur_scale = float(initial_scale)
        self.cur_hysteresis = int(hysteresis) if dynamic else 0
        self.last_overflow_iter = -1
        self.iteration = 0

    def load(self, cur_scale, cur_hysteresis, last_overflow_iter, iteration):
        """Resync from a device ``LossScaleState`` (checkpoint restore)."""
        self.cur_scale = float(cur_scale)
        self.cur_hysteresis = int(cur_hysteresis)
        self.last_overflow_iter = int(last_overflow_iter)
        self.iteration = int(iteration)

    def update(self, overflow: bool) -> float:
        """Advance one step; returns the scale for the NEXT step."""
        overflow = bool(overflow)
        it = self.iteration
        if not self.dynamic:
            self.iteration = it + 1
            return self.cur_scale

        hyst = (max(self.cur_hysteresis - 1, 0) if overflow
                else self.cur_hysteresis)
        shrink = overflow and self.cur_hysteresis <= 1
        grown_due = (not overflow) and (
            (it - self.last_overflow_iter) % self.scale_window
            == self.scale_window - 1)

        if shrink:
            self.cur_scale = max(self.cur_scale / self.scale_factor,
                                 self.min_scale)
            self.cur_hysteresis = self.hysteresis
        else:
            if grown_due:
                self.cur_scale = self.cur_scale * self.scale_factor
            self.cur_hysteresis = hyst
        if overflow:
            self.last_overflow_iter = it
        self.iteration = it + 1
        return self.cur_scale
