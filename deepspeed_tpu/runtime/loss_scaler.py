"""Loss scaling for fp16 training.

Parity: reference ``runtime/fp16/loss_scaler.py`` (``LossScaler``,
``DynamicLossScaler``).  Jit-friendly redesign: the scaler state is a small
pytree carried through the compiled train step, and scale updates are
``jnp.where`` branches — no Python control flow on device values, so the whole
overflow check/skip-step/rescale dance compiles into the step program (the
reference does this eagerly on the host, reference ``stage3.py:1840``).
"""

from typing import NamedTuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    cur_scale: jnp.ndarray       # f32 scalar
    cur_hysteresis: jnp.ndarray  # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    iteration: jnp.ndarray       # i32 scalar


def static_loss_scale_state(scale: float, hysteresis: int = 0) -> LossScaleState:
    return LossScaleState(
        cur_scale=jnp.asarray(scale, jnp.float32),
        cur_hysteresis=jnp.asarray(hysteresis, jnp.int32),
        last_overflow_iter=jnp.asarray(-1, jnp.int32),
        iteration=jnp.asarray(0, jnp.int32),
    )


def dynamic_loss_scale_state(initial_scale_power=16,
                             hysteresis: int = 2) -> LossScaleState:
    # start with the full hysteresis budget (reference DynamicLossScaler
    # initializes cur_hysteresis = delayed_shift)
    return static_loss_scale_state(2.0 ** initial_scale_power,
                                   hysteresis=hysteresis)


def has_inf_or_nan(tree) -> jnp.ndarray:
    """True if any leaf contains inf/nan (reference ``check_overflow``)."""
    import jax
    leaves = jax.tree_util.tree_leaves(tree)
    bad = jnp.asarray(False)
    for leaf in leaves:
        bad = bad | ~jnp.isfinite(jnp.asarray(leaf, jnp.float32)).all()
    return bad


def update_scale(state: LossScaleState, overflow: jnp.ndarray, *,
                 dynamic: bool, scale_factor: float = 2.0,
                 scale_window: int = 1000, min_scale: float = 1.0,
                 hysteresis: int = 2) -> LossScaleState:
    """One step of the dynamic loss-scale automaton, as pure array math.

    overflow → scale/2 (after hysteresis consumed); ``scale_window`` clean
    steps → scale*2.  Mirrors reference ``DynamicLossScaler.update_scale``.
    """
    it = state.iteration
    if not dynamic:
        return state._replace(iteration=it + 1)

    hyst = jnp.where(overflow, jnp.maximum(state.cur_hysteresis - 1, 0),
                     state.cur_hysteresis)
    shrink = overflow & (state.cur_hysteresis <= 1)
    grown_due = (~overflow) & (((it - state.last_overflow_iter) % scale_window) == scale_window - 1)

    new_scale = jnp.where(
        shrink,
        jnp.maximum(state.cur_scale / scale_factor, min_scale),
        jnp.where(grown_due, state.cur_scale * scale_factor, state.cur_scale))
    new_hyst = jnp.where(shrink, jnp.asarray(hysteresis, jnp.int32), hyst)
    new_last = jnp.where(overflow, it, state.last_overflow_iter)
    return LossScaleState(cur_scale=new_scale, cur_hysteresis=new_hyst,
                          last_overflow_iter=new_last, iteration=it + 1)
