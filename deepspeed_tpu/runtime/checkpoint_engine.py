"""Checkpoint engines.

Parity: reference ``runtime/checkpoint_engine/checkpoint_engine.py:6``
(``CheckpointEngine`` ABC: create/save/load/commit) with a Torch engine and an
async Nebula engine.  TPU design: the default engine is **Orbax** — sharded,
multi-host-safe, tensorstore-backed — which natively covers what the reference
builds by hand:

* per-rank ZeRO shard files (``*_optim_states.pt``) → orbax writes each
  host's shards of the sharded arrays;
* elastic DP-degree rescaling of ZeRO-1/2 checkpoints → restore with *target*
  shardings: orbax reshards on load;
* ``_zero3_consolidated_16bit_state_dict`` → restore replicated;
* Nebula-style async snapshotting → ``AsyncCheckpointer``.
"""

import json
import os
from abc import ABC, abstractmethod

import jax

from deepspeed_tpu.monitor.telemetry import get_telemetry
from deepspeed_tpu.utils.logging import log_dist, logger


class CheckpointEngine(ABC):

    def __init__(self, config_params=None):
        pass

    def create(self, tag):
        log_dist(f"checkpoint tag {tag}", ranks=[0])

    @abstractmethod
    def save(self, state, save_dir, tag, client_state=None):
        ...

    @abstractmethod
    def load(self, template_state, load_dir, tag, mesh,
             load_optimizer_states=True, load_module_only=False):
        ...

    def commit(self, tag):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):

    def __init__(self, config_params=None, use_async=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.use_async = use_async
        self._async_ckptr = None

    def _path(self, save_dir, tag):
        return os.path.join(os.path.abspath(save_dir), tag)

    def save(self, state, save_dir, tag, client_state=None):
        with get_telemetry().span("checkpoint/save", attrs={"tag": str(tag)}):
            return self._save(state, save_dir, tag, client_state)

    def _save(self, state, save_dir, tag, client_state=None):
        ocp = self._ocp
        path = self._path(save_dir, tag)
        os.makedirs(path, exist_ok=True)
        if self.use_async:
            if self._async_ckptr is None:
                self._async_ckptr = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler())
            ckptr = self._async_ckptr
        else:
            ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(path, "state"), state, force=True)
        if jax.process_index() == 0 and client_state is not None:
            with open(os.path.join(path, "client_state.json"), "w") as f:
                json.dump(client_state, f, default=str)
        if not self.use_async:
            ckptr.wait_until_finished() if hasattr(ckptr, "wait_until_finished") else None
        return True

    def load(self, template_state, load_dir, tag, mesh,
             load_optimizer_states=True, load_module_only=False):
        with get_telemetry().span("checkpoint/load", attrs={"tag": str(tag)}):
            return self._load(template_state, load_dir, tag, mesh,
                              load_optimizer_states, load_module_only)

    def _load(self, template_state, load_dir, tag, mesh,
              load_optimizer_states=True, load_module_only=False):
        ocp = self._ocp
        path = self._path(load_dir, tag)
        # Restore with the *current* shardings as target: orbax reshards,
        # giving elastic ZeRO checkpoints (save at dp=8, load at dp=2) for free.
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x,
            template_state)
        ckptr = ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.join(path, "state"), abstract)
        if load_module_only or not load_optimizer_states:
            restored = template_state.replace(params=restored.params)
        client_state = {}
        cs_path = os.path.join(path, "client_state.json")
        if os.path.exists(cs_path):
            with open(cs_path) as f:
                client_state = json.load(f)
        return restored, client_state

    def commit(self, tag):
        if self._async_ckptr is not None:
            self._async_ckptr.wait_until_finished()
        return True


class NebulaCheckpointEngine(OrbaxCheckpointEngine):
    """Async-snapshot engine (reference ``NebulaCheckpointEngine``): orbax
    AsyncCheckpointer does the background write + atomic commit."""

    def __init__(self, config_params=None):
        super().__init__(config_params, use_async=True)


TorchCheckpointEngine = OrbaxCheckpointEngine  # parity alias

_engine = None


def get_checkpoint_engine(config_params=None):
    global _engine
    if _engine is None:
        _engine = OrbaxCheckpointEngine(config_params)
    return _engine


def set_checkpoint_engine(engine):
    global _engine
    _engine = engine
