"""Checkpoint engines.

Parity: reference ``runtime/checkpoint_engine/checkpoint_engine.py:6``
(``CheckpointEngine`` ABC: create/save/load/commit) with a Torch engine and an
async Nebula engine.  TPU design: the default engine is **Orbax** — sharded,
multi-host-safe, tensorstore-backed — which natively covers what the reference
builds by hand:

* per-rank ZeRO shard files (``*_optim_states.pt``) → orbax writes each
  host's shards of the sharded arrays;
* elastic DP-degree rescaling of ZeRO-1/2 checkpoints → restore with *target*
  shardings: orbax reshards on load;
* ``_zero3_consolidated_16bit_state_dict`` → restore replicated;
* Nebula-style async snapshotting → ``AsyncCheckpointer``.
"""

import json
import os
from abc import ABC, abstractmethod

import jax

from deepspeed_tpu.monitor.telemetry import get_telemetry
from deepspeed_tpu.utils.logging import log_dist, logger


class CheckpointEngine(ABC):

    def __init__(self, config_params=None):
        pass

    def create(self, tag):
        log_dist(f"checkpoint tag {tag}", ranks=[0])

    @abstractmethod
    def save(self, state, save_dir, tag, client_state=None):
        ...

    @abstractmethod
    def load(self, template_state, load_dir, tag, mesh,
             load_optimizer_states=True, load_module_only=False):
        ...

    def commit(self, tag):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):

    def __init__(self, config_params=None, use_async=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.use_async = use_async
        self._async_ckptr = None

    def _path(self, save_dir, tag):
        return os.path.join(os.path.abspath(save_dir), tag)

    # Typed PRNG keys (dtype key<fry>) are not serializable by orbax's
    # array handler: unwrap to raw uint32 key data on save and re-wrap
    # (preserving the impl from the template state) on restore.
    @staticmethod
    def _is_typed_key(x):
        return isinstance(x, jax.Array) and jax.dtypes.issubdtype(
            x.dtype, jax.dtypes.prng_key)

    @classmethod
    def _unwrap_keys(cls, tree):
        return jax.tree_util.tree_map(
            lambda x: jax.random.key_data(x) if cls._is_typed_key(x) else x,
            tree)

    @classmethod
    def _rewrap_keys(cls, template, restored):
        return jax.tree_util.tree_map(
            lambda t, r: jax.random.wrap_key_data(
                r, impl=jax.random.key_impl(t))
            if cls._is_typed_key(t) else r,
            template, restored)

    def save(self, state, save_dir, tag, client_state=None):
        with get_telemetry().span("checkpoint/save", attrs={"tag": str(tag)}):
            return self._save(state, save_dir, tag, client_state)

    def _save(self, state, save_dir, tag, client_state=None):
        ocp = self._ocp
        path = self._path(save_dir, tag)
        os.makedirs(path, exist_ok=True)
        if self.use_async:
            if self._async_ckptr is None:
                self._async_ckptr = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler())
            ckptr = self._async_ckptr
        else:
            ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(path, "state"), self._unwrap_keys(state),
                   force=True)
        if not self.use_async:
            # StandardCheckpointer commits in a background thread (it is an
            # AsyncCheckpointer in orbax>=0.5); a sync engine must not
            # return before the payload is durable — the resilience layer
            # writes the manifest + commit marker right after this call.
            ckptr.wait_until_finished()
        if jax.process_index() == 0 and client_state is not None:
            with open(os.path.join(path, "client_state.json"), "w") as f:
                json.dump(client_state, f, default=str)
        return True

    def load(self, template_state, load_dir, tag, mesh,
             load_optimizer_states=True, load_module_only=False):
        with get_telemetry().span("checkpoint/load", attrs={"tag": str(tag)}):
            return self._load(template_state, load_dir, tag, mesh,
                              load_optimizer_states, load_module_only)

    def _load(self, template_state, load_dir, tag, mesh,
              load_optimizer_states=True, load_module_only=False):
        ocp = self._ocp
        path = self._path(load_dir, tag)
        # Restore with the *current* shardings as target: orbax reshards,
        # giving elastic ZeRO checkpoints (save at dp=8, load at dp=2) for free.
        def _abstract(x):
            if not isinstance(x, jax.Array):
                return x
            if self._is_typed_key(x):
                data = jax.eval_shape(jax.random.key_data, x)
                return jax.ShapeDtypeStruct(data.shape, data.dtype,
                                            sharding=x.sharding)
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)

        abstract = jax.tree_util.tree_map(_abstract, template_state)
        ckptr = ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.join(path, "state"), abstract)
        restored = self._rewrap_keys(template_state, restored)
        if load_module_only or not load_optimizer_states:
            restored = template_state.replace(params=restored.params)
        client_state = {}
        cs_path = os.path.join(path, "client_state.json")
        if os.path.exists(cs_path):
            with open(cs_path) as f:
                client_state = json.load(f)
        client_state = broadcast_client_state(client_state)
        return restored, client_state

    def commit(self, tag):
        if self._async_ckptr is not None:
            self._async_ckptr.wait_until_finished()
        return True


class NebulaCheckpointEngine(OrbaxCheckpointEngine):
    """Async-snapshot engine (reference ``NebulaCheckpointEngine``): orbax
    AsyncCheckpointer does the background write + atomic commit."""

    def __init__(self, config_params=None):
        super().__init__(config_params, use_async=True)


TorchCheckpointEngine = OrbaxCheckpointEngine  # parity alias


def broadcast_client_state(client_state):
    """Broadcast process 0's ``client_state`` dict to every host.

    ``save`` writes ``client_state.json`` only on process 0, so on shared
    filesystems every host reads it, but on node-local storage non-zero
    hosts would silently see ``{}`` and resume from step 0.  Serialize to
    JSON bytes and broadcast length + payload from the coordinator.
    """
    if jax.process_count() <= 1:
        return client_state
    import numpy as np
    from jax.experimental import multihost_utils

    payload = np.frombuffer(
        json.dumps(client_state, default=str).encode("utf-8"), dtype=np.uint8)
    length = int(multihost_utils.broadcast_one_to_all(
        np.asarray(payload.size, dtype=np.int64)))
    buf = np.zeros(length, dtype=np.uint8)
    buf[:min(payload.size, length)] = payload[:length]
    buf = multihost_utils.broadcast_one_to_all(buf)
    return json.loads(bytes(buf).decode("utf-8"))


_ENGINE_NAMES = {
    "sync": OrbaxCheckpointEngine,
    "orbax": OrbaxCheckpointEngine,
    "torch": TorchCheckpointEngine,
    "async": NebulaCheckpointEngine,
    "nebula": NebulaCheckpointEngine,
}

_engine = None


def _engine_cls_from_config(config_params):
    name = "sync"
    if config_params is None:
        name = "sync"
    elif hasattr(config_params, "checkpoint_config"):  # DeepSpeedConfig
        name = getattr(config_params.checkpoint_config, "engine", "sync")
    elif isinstance(config_params, dict):
        name = config_params.get("checkpoint", {}).get("engine", "sync")
    cls = _ENGINE_NAMES.get(str(name).lower())
    if cls is None:
        logger.warning(f"unknown checkpoint engine {name!r}; using sync orbax")
        cls = OrbaxCheckpointEngine
    return cls


def get_checkpoint_engine(config_params=None):
    """Return the process-wide checkpoint engine.

    With ``config_params`` (a DeepSpeedConfig or raw config dict), the
    engine class is resolved from ``checkpoint.engine`` ("sync" |
    "async"/"nebula") and the cached engine is **rebuilt when the
    requested type differs** — earlier revisions cached the first engine
    forever and silently ignored later configs.  A no-arg call returns
    the existing engine (or the sync default).
    """
    global _engine
    if config_params is not None:
        cls = _engine_cls_from_config(config_params)
        if type(_engine) is not cls:
            _engine = cls(config_params)
    elif _engine is None:
        _engine = OrbaxCheckpointEngine(config_params)
    return _engine


def set_checkpoint_engine(engine):
    global _engine
    _engine = engine
