"""LR schedules.

Parity: reference ``runtime/lr_schedules.py`` (LRRangeTest:308, OneCycle:415,
WarmupLR:704, WarmupDecayLR:800) with the same config ``params`` keys.

TPU design: a schedule is a pure function ``step -> lr`` (optax convention) so
it can live *inside* the jitted train step — the reference mutates
``param_group['lr']`` on the host every step, which would force a retrace
here.  ``build_schedule`` returns the callable; the engine threads the step
counter through the compiled update.  Stateful wrapper objects with the
reference's ``.step()``/``get_lr()`` API are provided for user loops that
drive schedules manually.
"""

import math
from typing import Any, Callable, Dict

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]


# one source of truth for schedule-parameter defaults: the schedule
# builders AND the add_tuning_arguments CLI table both read this, so a
# config-dict setup and a parsed-args setup cannot drift
TUNING_DEFAULTS: Dict[str, Any] = {
    "lr_range_test_min_lr": 1e-3,
    "lr_range_test_step_size": 2000,
    "lr_range_test_step_rate": 1.0,
    "lr_range_test_staircase": False,
    "cycle_min_lr": 1e-3,
    "cycle_max_lr": 1e-2,
    "decay_lr_rate": 0.0,
    "cycle_first_step_size": 2000,
    "cycle_second_step_size": None,   # None -> mirror first_step_size
    "cycle_first_stair_count": 1,
    "cycle_second_stair_count": None,
    "decay_step_size": 0,
    "cycle_min_mom": 0.8,
    "cycle_max_mom": 0.9,
    "decay_mom_rate": 0.0,
    "warmup_min_lr": 0.0,
    "warmup_max_lr": 0.001,
    "warmup_num_steps": 1000,
    "warmup_type": "log",
}


def _param(params: Dict[str, Any], key: str):
    v = params.get(key, TUNING_DEFAULTS.get(key))
    return TUNING_DEFAULTS.get(key) if v is None else v


def lr_range_test(params: Dict[str, Any]) -> Callable:
    min_lr = _param(params, "lr_range_test_min_lr")
    step_size = _param(params, "lr_range_test_step_size")
    step_rate = _param(params, "lr_range_test_step_rate")
    staircase = _param(params, "lr_range_test_staircase")

    def schedule(step):
        interval = jnp.asarray(step, jnp.float32) / step_size
        if staircase:
            interval = jnp.floor(interval)
        return min_lr * (1.0 + interval * step_rate)
    return schedule


def _cycle_phase(params: Dict[str, Any]):
    """Shared 1Cycle geometry: returns ``phase(step) -> (scale, in_cycle,
    decay_intervals)`` where ``scale`` is the up/down triangle in [0, 1]
    (both the lr and the momentum schedule ride the same triangle, so the
    two can't desynchronize)."""
    first = _param(params, "cycle_first_step_size")
    second = params.get("cycle_second_step_size")
    if second is None:
        second = first
    decay_step = _param(params, "decay_step_size")
    total = first + second

    def phase(step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / first, 0.0, 1.0)
        down = jnp.clip((step - first) / second, 0.0, 1.0)
        past = jnp.maximum(step - total, 0.0)
        # reference OneCycle sets skip_lr_decay/skip_mom_decay when
        # decay_step_size==0 (the default): lr/momentum hold constant after
        # the cycle.  intervals=0 reproduces that; a per-step interval here
        # would grow momentum past 1.0 and diverge Adam.
        intervals = past / decay_step if decay_step > 0 else jnp.zeros_like(past)
        return up - down, step <= total, intervals
    return phase


def one_cycle(params: Dict[str, Any]) -> Callable:
    cycle_min_lr = _param(params, "cycle_min_lr")
    cycle_max_lr = _param(params, "cycle_max_lr")
    decay_lr_rate = _param(params, "decay_lr_rate")
    phase = _cycle_phase(params)

    def schedule(step):
        scale, in_cycle, intervals = phase(step)
        in_cycle_lr = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * scale
        decayed = cycle_min_lr / (1.0 + decay_lr_rate * intervals)
        return jnp.where(in_cycle, in_cycle_lr, decayed)
    return schedule


def one_cycle_mom(params: Dict[str, Any]):
    """Momentum schedule of the 1Cycle policy (reference ``OneCycle``:
    momentum cycles INVERSELY to lr — ``mom = max - (max-min)*scale`` over
    the same up/down triangle, then ``max * (1 + decay_mom_rate * t)``
    after the cycle).  ``cycle_momentum`` defaults ON like the reference
    (bounds default 0.8/0.9 from TUNING_DEFAULTS when not given); returns
    None only when explicitly disabled."""
    if not params.get("cycle_momentum", True):
        return None
    min_mom = _param(params, "cycle_min_mom")
    max_mom = _param(params, "cycle_max_mom")
    decay_mom_rate = _param(params, "decay_mom_rate")
    phase = _cycle_phase(params)

    def schedule(step):
        scale, in_cycle, intervals = phase(step)
        in_cycle_mom = max_mom - (max_mom - min_mom) * scale
        # post-cycle growth only: Adam's (1-b1) weighting must stay
        # positive (user-configured cycle bounds are not clamped)
        decayed = jnp.minimum(
            max_mom * (1.0 + decay_mom_rate * intervals), 0.999)
        return jnp.where(in_cycle, in_cycle_mom, decayed)
    return schedule


def warmup_lr(params: Dict[str, Any]) -> Callable:
    warmup_min_lr = _param(params, "warmup_min_lr")
    warmup_max_lr = _param(params, "warmup_max_lr")
    warmup_num_steps = max(1, _param(params, "warmup_num_steps"))
    warmup_type = _param(params, "warmup_type")

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / warmup_num_steps, 0.0, 1.0)
        if warmup_type == "log":
            # log(1+step)/log(1+N): reference's default warmup curve
            gamma = jnp.log1p(step) / math.log(1 + warmup_num_steps)
            gamma = jnp.clip(gamma, 0.0, 1.0)
        else:
            gamma = frac
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma
    return schedule


def warmup_decay_lr(params: Dict[str, Any]) -> Callable:
    total_num_steps = params.get("total_num_steps", 10000)
    warmup_num_steps = max(1, params.get("warmup_num_steps", 1000))
    base = warmup_lr(params)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        w = base(step)
        decay = jnp.clip(
            (total_num_steps - step) /
            max(1.0, float(total_num_steps - warmup_num_steps)),
            0.0, 1.0)
        return jnp.where(step < warmup_num_steps, w, w * decay)
    return schedule


SCHEDULE_REGISTRY = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
}


def build_schedule(name: str, params: Dict[str, Any]) -> Callable:
    if name not in SCHEDULE_REGISTRY:
        raise ValueError(
            f"Unknown scheduler '{name}'. Valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_REGISTRY[name](params)


class LRScheduler:
    """Stateful wrapper with the reference's torch-style API
    (``step``/``get_lr``/``state_dict``/``load_state_dict``)."""

    def __init__(self, schedule_fn: Callable, last_batch_iteration: int = -1):
        self.schedule_fn = schedule_fn
        self.last_batch_iteration = last_batch_iteration

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        return [float(self.schedule_fn(max(0, self.last_batch_iteration)))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


def _str2bool(v) -> bool:
    if isinstance(v, bool):
        return v
    if str(v).lower() in ("yes", "true", "t", "1"):
        return True
    if str(v).lower() in ("no", "false", "f", "0"):
        return False
    raise ValueError(f"boolean flag got {v!r}")


def add_tuning_arguments(parser):
    """CLI args for schedule tuning (reference ``lr_schedules.py``
    ``add_tuning_arguments`` — exported at the package top level).  One
    ``--<key>`` flag per TUNING_DEFAULTS entry, so CLI defaults are the
    schedule builders' defaults by construction."""
    group = parser.add_argument_group(
        "Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    for key, default in TUNING_DEFAULTS.items():
        if isinstance(default, bool):
            typ = _str2bool
        elif isinstance(default, int):
            typ = int
        elif isinstance(default, float):
            typ = float
        elif default is None:
            typ = int          # the None-defaulted step sizes
        else:
            typ = str
        group.add_argument(f"--{key}", type=typ, default=default,
                           help=f"{key} (default {default})")
    return parser
