"""Built-in optimizer registry.

Parity: reference ``runtime/engine.py:1321 _configure_basic_optimizer``
(Adam/AdamW → FusedAdam | DeepSpeedCPUAdam, Lamb, OneBit*, Adagrad).

TPU design: optimizers are optax ``GradientTransformation``s.  The reference's
"fused" multi-tensor CUDA kernels exist because eager torch launches one
kernel per tensor; under XLA every optimizer is already fused across the whole
pytree in one compiled program, so ``FusedAdam``/``Adam`` converge to the same
thing.  A standalone fused-Adam over a flat partition buffer exists in
``ops/adam.py`` (the op_builder surface; the engine's optax update compiles
to the same fused program).

``OneBitAdam``/``ZeroOneAdam``/``OneBitLamb`` (reference ``fp16/onebit/*``) are
error-feedback *communication* compressors; on TPU the gradient reduction is
inside XLA, so the analogue is sign-compressed gradient all-reduce implemented
in ``runtime/comm_compression.py`` and selected via the same optimizer names.
"""

from typing import Any, Callable, Dict

import optax

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM = "fusedadam"
CPU_ADAM = "cpuadam"  # host-offloaded Adam (ZeRO-Offload); see zero/offload
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB = "fusedlamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"


def _adam(params: Dict[str, Any], adamw_mode=True) -> optax.GradientTransformation:
    lr = params.get("lr", 1e-3)
    betas = params.get("betas", (0.9, 0.999))
    eps = params.get("eps", 1e-8)
    wd = params.get("weight_decay", 0.01 if adamw_mode else 0.0)
    b1_schedule = params.get("_b1_schedule")   # 1Cycle momentum cycling
    if b1_schedule is not None:
        # inject_hyperparams lets b1 follow a schedule (the reference's
        # OneCycle sets optimizer momentum per step); lr may itself be a
        # schedule — both are resolved per step
        base = optax.adamw if adamw_mode else optax.adam
        kw = dict(learning_rate=lr, b1=b1_schedule, b2=betas[1], eps=eps)
        if adamw_mode:
            kw["weight_decay"] = wd
        tx = optax.inject_hyperparams(base)(**kw)
        if not adamw_mode and wd:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    if adamw_mode:
        return optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
    tx = optax.adam(lr, b1=betas[0], b2=betas[1], eps=eps)
    if wd:
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    return tx


def _lamb(params: Dict[str, Any]) -> optax.GradientTransformation:
    lr = params.get("lr", 1e-3)
    betas = params.get("betas", (0.9, 0.999))
    eps = params.get("eps", 1e-6)
    wd = params.get("weight_decay", 0.0)
    return optax.lamb(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)


def _sgd(params: Dict[str, Any]) -> optax.GradientTransformation:
    lr = params.get("lr", 1e-3)
    momentum = params.get("momentum", 0.0)
    nesterov = params.get("nesterov", False)
    wd = params.get("weight_decay", 0.0)
    tx = optax.sgd(lr, momentum=momentum or None, nesterov=nesterov)
    if wd:
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    return tx


def _adagrad(params: Dict[str, Any]) -> optax.GradientTransformation:
    lr = params.get("lr", 1e-2)
    eps = params.get("eps", 1e-10)
    return optax.adagrad(lr, eps=eps)


def _onebit(params: Dict[str, Any],
            inner: optax.GradientTransformation
            ) -> optax.GradientTransformation:
    """Two-stage 1-bit optimizer (reference ``fp16/onebit/*``): warmup runs
    the inner rule on raw grads; after ``freeze_step`` the gradient is
    sign-quantized with error feedback (``runtime/comm_compression.py``)
    before the inner update — the trajectory of compressed communication."""
    from deepspeed_tpu.runtime.comm_compression import error_feedback_compress
    freeze_step = int(params.get("freeze_step", 100))
    return optax.chain(error_feedback_compress(freeze_step), inner)


def _onebit_adam(params: Dict[str, Any]) -> optax.GradientTransformation:
    return _onebit(params, _adam(params, adamw_mode=False))


def _onebit_lamb(params: Dict[str, Any]) -> optax.GradientTransformation:
    return _onebit(params, _lamb(params))


OPTIMIZER_REGISTRY: Dict[str, Callable[[Dict[str, Any]], optax.GradientTransformation]] = {
    ADAM_OPTIMIZER: lambda p: _adam(p, adamw_mode=p.get("adam_w_mode", True)),
    ADAMW_OPTIMIZER: lambda p: _adam(p, adamw_mode=True),
    FUSED_ADAM: lambda p: _adam(p, adamw_mode=p.get("adam_w_mode", True)),
    CPU_ADAM: lambda p: _adam(p, adamw_mode=p.get("adamw_mode", True)),
    LAMB_OPTIMIZER: _lamb,
    FUSED_LAMB: _lamb,
    ONEBIT_ADAM_OPTIMIZER: _onebit_adam,
    ZERO_ONE_ADAM_OPTIMIZER: _onebit_adam,
    ONEBIT_LAMB_OPTIMIZER: _onebit_lamb,
    SGD_OPTIMIZER: _sgd,
    ADAGRAD_OPTIMIZER: _adagrad,
}

# Optimizers whose comm path uses 1-bit sign compression with error feedback
COMPRESSED_COMM_OPTIMIZERS = {
    ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER,
}


def build_optimizer(name: str, params: Dict[str, Any]) -> optax.GradientTransformation:
    key = name.lower()
    if key not in OPTIMIZER_REGISTRY:
        raise ValueError(f"Unknown optimizer '{name}'. "
                         f"Built-ins: {sorted(OPTIMIZER_REGISTRY)}")
    return OPTIMIZER_REGISTRY[key](params)
