"""Built-in optimizer registry.

Parity: reference ``runtime/engine.py:1321 _configure_basic_optimizer``
(Adam/AdamW → FusedAdam | DeepSpeedCPUAdam, Lamb, OneBit*, Adagrad).

TPU design: optimizers are optax ``GradientTransformation``s.  The reference's
"fused" multi-tensor CUDA kernels exist because eager torch launches one
kernel per tensor; under XLA every optimizer is already fused across the whole
pytree in one compiled program, so ``FusedAdam``/``Adam`` converge to the same
thing.  A standalone fused-Adam over a flat partition buffer exists in
``ops/adam.py`` (the op_builder surface; the engine's optax update compiles
to the same fused program).

``OneBitAdam``/``ZeroOneAdam``/``OneBitLamb`` (reference ``fp16/onebit/*``) are
error-feedback *communication* compressors; on TPU the gradient reduction is
inside XLA, so the analogue is sign-compressed gradient all-reduce implemented
in ``runtime/comm_compression.py`` and selected via the same optimizer names.
"""

from typing import Any, Callable, Dict

import optax

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM = "fusedadam"
CPU_ADAM = "cpuadam"  # host-offloaded Adam (ZeRO-Offload); see zero/offload
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB = "fusedlamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"


def _sr_cast(x32, key, dtype):
    """Stochastic-round an fp32 array to ``dtype`` (bf16): add uniform noise
    to the truncated mantissa bits, then truncate.  Unbiased in expectation,
    so low-precision moment accumulation does not systematically lose the
    (1-beta)-scaled increments the way nearest-rounding does — the reason
    plain bf16 second moments decay under b2=0.999."""
    import jax
    import jax.numpy as jnp
    if dtype == jnp.float32:
        return x32
    bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    rnd = jax.random.bits(key, x32.shape, jnp.uint16).astype(jnp.uint32)
    out = (bits + rnd) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(out, jnp.float32).astype(dtype)


def _scale_by_adam_dtyped(b1, b2, eps, moment_dtype) -> optax.GradientTransformation:
    """``optax.scale_by_adam`` with BOTH moments stored in ``moment_dtype``
    (optax only supports ``mu_dtype``).  Accumulation happens in fp32 every
    step; the stored state is stochastically rounded down to the target dtype.
    Halves Adam's optimizer-state HBM (8 bytes/param -> 4 at bf16), which is
    what lets a >=1B-param model train on one 16 GB chip without host offload
    (cf. reference ZeRO-Offload's motivation, runtime/zero/offload.py)."""
    import jax
    import jax.numpy as jnp

    def init(params):
        zeros = lambda p: jnp.zeros(jnp.shape(p), moment_dtype)  # noqa: E731
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(zeros, params),
            nu=jax.tree_util.tree_map(zeros, params))

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - jnp.power(jnp.float32(b1), cf)
        bc2 = 1.0 - jnp.power(jnp.float32(b2), cf)
        base = jax.random.fold_in(jax.random.key(0), count)
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        n = max(1, len(leaves))
        mu_keys = treedef.unflatten(list(jax.random.split(
            jax.random.fold_in(base, 0), n))[:len(leaves)])
        nu_keys = treedef.unflatten(list(jax.random.split(
            jax.random.fold_in(base, 1), n))[:len(leaves)])

        mu32 = jax.tree_util.tree_map(
            lambda g, m: b1 * m.astype(jnp.float32) +
            (1.0 - b1) * g.astype(jnp.float32), updates, state.mu)
        nu32 = jax.tree_util.tree_map(
            lambda g, v: b2 * v.astype(jnp.float32) +
            (1.0 - b2) * jnp.square(g.astype(jnp.float32)),
            updates, state.nu)
        out = jax.tree_util.tree_map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu32, nu32)

        mu_new = jax.tree_util.tree_map(
            lambda m, k: _sr_cast(m, k, moment_dtype), mu32, mu_keys)
        nu_new = jax.tree_util.tree_map(
            lambda v, k: _sr_cast(v, k, moment_dtype), nu32, nu_keys)
        return out, optax.ScaleByAdamState(count=count, mu=mu_new, nu=nu_new)

    return optax.GradientTransformation(init, update)


def _moment_dtype(params: Dict[str, Any]):
    import jax.numpy as jnp
    name = str(params.get("moment_dtype", "float32")).lower()
    table = {"float32": jnp.float32, "fp32": jnp.float32,
             "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16}
    if name not in table:
        raise ValueError(f"moment_dtype must be one of {sorted(table)}, "
                         f"got '{name}'")
    return table[name]


def _adam(params: Dict[str, Any], adamw_mode=True) -> optax.GradientTransformation:
    lr = params.get("lr", 1e-3)
    betas = params.get("betas", (0.9, 0.999))
    eps = params.get("eps", 1e-8)
    wd = params.get("weight_decay", 0.01 if adamw_mode else 0.0)
    mdt = _moment_dtype(params)
    import jax.numpy as jnp
    if mdt != jnp.float32:
        if params.get("_b1_schedule") is not None:
            raise ValueError("moment_dtype != float32 is not supported "
                             "together with OneCycle momentum cycling")
        # reduced-precision moments: custom scale_by_adam (optax only casts
        # mu), chained to match optax.adamw/adam semantics exactly
        tx = optax.chain(
            _scale_by_adam_dtyped(betas[0], betas[1], eps, mdt),
            optax.add_decayed_weights(wd) if (adamw_mode and wd)
            else optax.identity(),
            optax.scale_by_learning_rate(lr))
        if not adamw_mode and wd:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    b1_schedule = params.get("_b1_schedule")   # 1Cycle momentum cycling
    if b1_schedule is not None:
        # inject_hyperparams lets b1 follow a schedule (the reference's
        # OneCycle sets optimizer momentum per step); lr may itself be a
        # schedule — both are resolved per step
        base = optax.adamw if adamw_mode else optax.adam
        kw = dict(learning_rate=lr, b1=b1_schedule, b2=betas[1], eps=eps)
        if adamw_mode:
            kw["weight_decay"] = wd
        tx = optax.inject_hyperparams(base)(**kw)
        if not adamw_mode and wd:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    if adamw_mode:
        return optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
    tx = optax.adam(lr, b1=betas[0], b2=betas[1], eps=eps)
    if wd:
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    return tx


def _lamb(params: Dict[str, Any]) -> optax.GradientTransformation:
    lr = params.get("lr", 1e-3)
    betas = params.get("betas", (0.9, 0.999))
    eps = params.get("eps", 1e-6)
    wd = params.get("weight_decay", 0.0)
    return optax.lamb(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)


def _sgd(params: Dict[str, Any]) -> optax.GradientTransformation:
    lr = params.get("lr", 1e-3)
    momentum = params.get("momentum", 0.0)
    nesterov = params.get("nesterov", False)
    wd = params.get("weight_decay", 0.0)
    tx = optax.sgd(lr, momentum=momentum or None, nesterov=nesterov)
    if wd:
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    return tx


def _adagrad(params: Dict[str, Any]) -> optax.GradientTransformation:
    lr = params.get("lr", 1e-2)
    eps = params.get("eps", 1e-10)
    return optax.adagrad(lr, eps=eps)


def _onebit(params: Dict[str, Any],
            inner: optax.GradientTransformation
            ) -> optax.GradientTransformation:
    """Two-stage 1-bit optimizer (reference ``fp16/onebit/*``): warmup runs
    the inner rule on raw grads; after ``freeze_step`` the gradient is
    sign-quantized with error feedback (``runtime/comm_compression.py``)
    before the inner update — the trajectory of compressed communication."""
    from deepspeed_tpu.runtime.comm_compression import error_feedback_compress
    freeze_step = int(params.get("freeze_step", 100))
    return optax.chain(error_feedback_compress(freeze_step), inner)


def _onebit_adam(params: Dict[str, Any]) -> optax.GradientTransformation:
    return _onebit(params, _adam(params, adamw_mode=False))


def _onebit_lamb(params: Dict[str, Any]) -> optax.GradientTransformation:
    return _onebit(params, _lamb(params))


OPTIMIZER_REGISTRY: Dict[str, Callable[[Dict[str, Any]], optax.GradientTransformation]] = {
    ADAM_OPTIMIZER: lambda p: _adam(p, adamw_mode=p.get("adam_w_mode", True)),
    ADAMW_OPTIMIZER: lambda p: _adam(p, adamw_mode=True),
    FUSED_ADAM: lambda p: _adam(p, adamw_mode=p.get("adam_w_mode", True)),
    CPU_ADAM: lambda p: _adam(p, adamw_mode=p.get("adamw_mode", True)),
    LAMB_OPTIMIZER: _lamb,
    FUSED_LAMB: _lamb,
    ONEBIT_ADAM_OPTIMIZER: _onebit_adam,
    ZERO_ONE_ADAM_OPTIMIZER: _onebit_adam,
    ONEBIT_LAMB_OPTIMIZER: _onebit_lamb,
    SGD_OPTIMIZER: _sgd,
    ADAGRAD_OPTIMIZER: _adagrad,
}

# Optimizers whose comm path uses 1-bit sign compression with error feedback
COMPRESSED_COMM_OPTIMIZERS = {
    ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER,
}


def build_optimizer(name: str, params: Dict[str, Any]) -> optax.GradientTransformation:
    key = name.lower()
    if key not in OPTIMIZER_REGISTRY:
        raise ValueError(f"Unknown optimizer '{name}'. "
                         f"Built-ins: {sorted(OPTIMIZER_REGISTRY)}")
    return OPTIMIZER_REGISTRY[key](params)
