"""Config keys + defaults.

Parity: reference ``deepspeed/runtime/constants.py`` — same JSON key spellings
so a DeepSpeed config file drops in unchanged ("per_gpu" keys are accepted and
mean "per chip").
"""

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

AMP = "amp"

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

ZERO_OPTIMIZATION = "zero_optimization"

COMMS_LOGGER = "comms_logger"

# quantized-collective wire codec (comm/quantize.py): {"quantization": {...}}
COMM = "comm"

MESH = "mesh"  # TPU extension: {"dp": n, "fsdp": n, "tp": n, "pp": n, "sp": n, "ep": n}

ACTIVATION_CHECKPOINTING = "activation_checkpointing"

FLOPS_PROFILER = "flops_profiler"

MONITOR_TENSORBOARD = "tensorboard"
MONITOR_WANDB = "wandb"
MONITOR_CSV = "csv_monitor"

TELEMETRY = "telemetry"  # unified JSONL event stream + stall watchdog
TELEMETRY_INCIDENTS = "incidents"  # telemetry sub-block: incident plane
INCIDENT_DIRNAME_DEFAULT = "incidents"  # bundles under the telemetry dir

ASYNC_PIPELINE = "async_pipeline"  # prefetched input feed + metric drain

RESILIENCE = "resilience"  # durable ckpts, retries, preemption, fault injection

GRADIENT_ACCUMULATION_STEPS_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
TRAIN_BATCH_SIZE_DEFAULT = None

DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"

CHECKPOINT = "checkpoint"
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"

ELASTICITY = "elasticity"
COMPRESSION_TRAINING = "compression_training"

PIPELINE = "pipeline"

SEED = "seed"
SEED_DEFAULT = 42
