from deepspeed_tpu.runtime.activation_checkpointing import checkpointing  # noqa: F401
