"""Activation checkpointing — Megatron-compatible surface on jax.checkpoint.

Parity: reference ``runtime/activation_checkpointing/checkpointing.py``
(``checkpoint:749``, ``CheckpointFunction:499``, ``configure:831``,
``partition_activations:373``, ``CudaRNGStatesTracker:123``,
``model_parallel_cuda_manual_seed:199``).

TPU-first redesign
------------------
The reference re-implements torch checkpointing with four extra tricks:
partitioning saved activations across TP ranks, moving them to CPU,
contiguous buffers, and a CUDA RNG state tracker so dropout replays
identically in the recompute pass.  Under XLA:

* recompute-in-backward IS ``jax.checkpoint`` (with a policy choosing what
  to save);
* "partition activations over TP" = a sharding constraint on the saved
  residuals — expressed by constraining the wrapped function's inputs to
  the tp axis, so what gets saved is the sharded array;
* "checkpoint_in_cpu" = ``jax.checkpoint`` offload policies
  (``save_and_offload_only_these_names`` / pinned-host offload);
* the RNG tracker is trivial: JAX PRNG keys are values, so replay
  determinism is automatic.  The tracker below exists for API parity and
  for deriving distinct named streams (e.g. tensor-model-parallel dropout
  seeds offset per tp rank, reference ``:199``).
"""

import contextlib
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import TP_AXIS
from deepspeed_tpu.utils.logging import logger

# ----------------------------------------------------------------------
# module-level config (parity: reference module globals)
# ----------------------------------------------------------------------
PARTITION_ACTIVATIONS = False
CPU_CHECKPOINT = False
CONTIGUOUS_CHECKPOINTING = False
SYNCHRONIZE = False
PROFILE_TIME = False
NUM_CHECKPOINTS = None
_POLICY_NAME = "nothing_saveable"
_CONFIGURED = False

_OFFLOAD_POLICIES = ("save_and_offload_only_these_names",
                     "offload_dot_with_no_batch_dims")


def _resolve_policy():
    """The jax.checkpoint policy implied by the configured knobs."""
    if CPU_CHECKPOINT:
        # offload the dot-product residuals to pinned host memory — the XLA
        # analogue of the reference copying partitioned activations to CPU
        try:
            return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                "device", "pinned_host")
        except Exception:  # pragma: no cover - older jax
            logger.warning("offload policy unavailable; saving nothing")
            return jax.checkpoint_policies.nothing_saveable
    pol = getattr(jax.checkpoint_policies, _POLICY_NAME, None)
    if pol is None:
        raise ValueError(
            f"unknown activation-checkpointing policy '{_POLICY_NAME}' "
            "(see jax.checkpoint_policies)")
    return pol


def _maybe_partition(x):
    """Shard a to-be-saved tensor over the tp axis (reference
    ``partition_activations:373`` slices the flattened activation across
    model-parallel ranks).  Constraint applies on the first dim divisible
    by the tp degree; replicated otherwise."""
    if not hasattr(x, "ndim") or x.ndim == 0:
        return x
    mesh = groups.get_mesh()
    tp = mesh.shape.get(TP_AXIS, 1)
    if tp <= 1:
        return x
    from jax.sharding import PartitionSpec as P
    for dim in range(x.ndim):
        if x.shape[dim] % tp == 0:
            spec = [None] * x.ndim
            spec[dim] = TP_AXIS
            return jax.lax.with_sharding_constraint(x, P(*spec))
    return x


def checkpoint(function: Callable, *args):
    """Checkpoint a model block: recompute its internals in backward.

    Parity: reference ``checkpoint:749`` (drop-in for
    ``torch.utils.checkpoint.checkpoint``).  Returns ``function(*args)``
    with gradient rematerialisation under the configured policy.
    """
    policy = _resolve_policy()

    fn = function
    if PARTITION_ACTIVATIONS:
        def fn(*inner):  # noqa: F811 — wrap to shard the saved inputs
            inner = jax.tree_util.tree_map(_maybe_partition, inner)
            return function(*inner)

    return jax.checkpoint(fn, policy=policy)(*args)


def checkpoint_wrapper(function: Callable) -> Callable:
    """Decorator form: ``f = checkpoint_wrapper(f)``."""
    def wrapped(*args):
        return checkpoint(function, *args)
    return wrapped


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, checkpoint_in_cpu=None,
              synchronize=None, profile=None, num_checkpoints=None,
              policy=None):
    """Parity: reference ``configure:831`` — set module-level knobs from the
    DeepSpeed config and/or explicit args (explicit args win)."""
    global PARTITION_ACTIVATIONS, CPU_CHECKPOINT, CONTIGUOUS_CHECKPOINTING
    global SYNCHRONIZE, PROFILE_TIME, NUM_CHECKPOINTS, _POLICY_NAME, _CONFIGURED

    cfg = None
    if deepspeed_config is not None:
        cfg = getattr(deepspeed_config, "activation_checkpointing_config",
                      None)
        if cfg is None and isinstance(deepspeed_config, dict):
            from deepspeed_tpu.runtime.config import (
                ActivationCheckpointingConfig)
            cfg = ActivationCheckpointingConfig(
                deepspeed_config.get("activation_checkpointing", {}))
    if cfg is not None:
        PARTITION_ACTIVATIONS = cfg.partition_activations
        CONTIGUOUS_CHECKPOINTING = cfg.contiguous_memory_optimization
        CPU_CHECKPOINT = cfg.cpu_checkpointing
        SYNCHRONIZE = cfg.synchronize_checkpoint_boundary
        PROFILE_TIME = cfg.profile
        NUM_CHECKPOINTS = cfg.number_checkpoints
        _POLICY_NAME = cfg.policy

    if partition_activations is not None:
        PARTITION_ACTIVATIONS = partition_activations
    if contiguous_checkpointing is not None:
        CONTIGUOUS_CHECKPOINTING = contiguous_checkpointing
    if checkpoint_in_cpu is not None:
        CPU_CHECKPOINT = checkpoint_in_cpu
    if synchronize is not None:
        SYNCHRONIZE = synchronize
    if profile is not None:
        PROFILE_TIME = profile
    if num_checkpoints is not None:
        NUM_CHECKPOINTS = num_checkpoints
    if policy is not None:
        _POLICY_NAME = policy
    if CONTIGUOUS_CHECKPOINTING:
        # XLA lays out saved residuals itself; the reference's hand-managed
        # contiguous buffers have no analogue (and need NUM_CHECKPOINTS)
        logger.info("contiguous_memory_optimization: handled by XLA buffer "
                    "assignment; no user-visible effect")
    _CONFIGURED = True


def is_configured():
    return _CONFIGURED


def reset():
    """Parity: reference ``reset()`` — drop per-iteration buffers (no-op
    here; kept for API compatibility)."""


def model_parallel_reconfigure_tp_seed(seed):
    get_rng_tracker().add("model-parallel-rng",
                          _tp_offset_seed(seed))


# ----------------------------------------------------------------------
# RNG state tracker (parity: CudaRNGStatesTracker:123)
# ----------------------------------------------------------------------

_MODEL_PARALLEL_RNG = "model-parallel-rng"
_DEFAULT_RNG = "default-rng"


def _tp_offset_seed(seed: int) -> int:
    """Distinct seed per tp rank (reference ``:199``: tensor-model-parallel
    regions use ``seed + 2718 + tp_rank``)."""
    return int(seed) + 2718 + groups.get_model_parallel_rank()


class RNGStatesTracker:
    """Named PRNG streams.  Keys are split on every ``fork`` so repeated
    forks yield fresh-but-deterministic keys — the functional analogue of
    get_state/set_state in the reference tracker."""

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise Exception(f"RNG state {name} already exists")
        self.states_[name] = jax.random.key(int(seed))

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise Exception(f"RNG state {name} is not added")
        key, sub = jax.random.split(self.states_[name])
        self.states_[name] = key
        yield sub


_RNG_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _RNG_TRACKER


# reference name kept as an alias (no CUDA here)
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_manual_seed(seed: int):
    """Seed the default + model-parallel RNG streams; tp ranks get offset
    seeds so e.g. dropout differs across tensor-parallel shards.
    Parity: reference ``model_parallel_cuda_manual_seed:199``."""
    tracker = get_rng_tracker()
    tracker.reset()
    tracker.add(_DEFAULT_RNG, seed)
    tracker.add(_MODEL_PARALLEL_RNG, _tp_offset_seed(seed))
    return tracker


model_parallel_cuda_manual_seed = model_parallel_manual_seed
