"""Sparse tensor support (sparse embedding-gradient reduction).

Parity: reference ``runtime/sparse_tensor.py`` (``SparseTensor``: index/value
COO wrapper built from torch sparse grads) + engine
``sparse_allreduce_no_retain:2477`` (allgather indices+values across DP
instead of dense allreduce).

TPU design: embedding gradients under jax are dense by default; for very
large vocabularies the win is reducing only the touched rows.  ``SparseTensor``
carries (indices, values, dense_shape); ``sparse_grad_from_dense`` extracts
touched rows; ``sparse_allreduce`` concatenates row sets across the dp axis
(the allgather the reference does) and ``to_dense`` scatter-adds.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class SparseTensor:
    """COO rows: ``indices`` [nnz] row ids, ``values`` [nnz, ...row shape]."""

    def __init__(self, indices, values, dense_size: Tuple[int, ...]):
        self.indices = jnp.asarray(indices)
        self.values = jnp.asarray(values)
        self.dense_size = tuple(dense_size)

    @staticmethod
    def from_dense(dense, max_rows: Optional[int] = None) -> "SparseTensor":
        """Extract non-zero rows.  ``max_rows`` bounds nnz for static shapes
        under jit (extra slots point at row 0 with zero values)."""
        dense = jnp.asarray(dense)
        row_nz = jnp.any(dense != 0, axis=tuple(range(1, dense.ndim)))
        k = int(max_rows or dense.shape[0])
        # top-k by nonzero flag gives the nonzero rows first (stable order)
        _, idx = lax.top_k(row_nz.astype(jnp.int32), k)
        vals = dense[idx] * row_nz[idx][(...,) + (None,) * (dense.ndim - 1)]
        return SparseTensor(idx, vals, dense.shape)

    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self) -> int:
        return int(self.indices.size + np.prod(self.values.shape))

    def __repr__(self):
        return (f"SparseTensor(nnz_rows={self.indices.shape[0]}, "
                f"dense_size={self.dense_size})")


def sparse_allreduce(st: SparseTensor, axis_name: str) -> SparseTensor:
    """Inside shard_map: allgather row sets over the dp axis and average —
    the reference's indices/values allgather (``sparse_allreduce:2492``)."""
    world = lax.psum(1, axis_name)
    all_idx = lax.all_gather(st.indices, axis_name, tiled=True)
    all_val = lax.all_gather(st.values, axis_name, tiled=True) / world
    return SparseTensor(all_idx, all_val, st.dense_size)
