"""Data loading.

Parity: reference ``runtime/dataloader.py`` (``DeepSpeedDataLoader`` wrapping a
torch ``DataLoader`` + ``DistributedSampler``).  TPU design: one process may
feed many chips, so the loader yields **global** batches of numpy arrays and
the engine shards them onto the mesh with ``device_put`` (the device transfer
is where "distribution" happens — there is no per-rank sampler state to keep
in sync).  For multi-host, each process yields its process-local slice
(``process_index``-strided), matching ``DistributedSampler`` semantics.
"""

import math

import numpy as np

import jax


class RepeatingLoader:
    """Parity: reference ``runtime/dataloader.py RepeatingLoader`` — wraps an
    iterator, restarting it at StopIteration."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batches an indexable dataset into numpy pytrees.

    dataset: a sequence of samples; each sample is an array or a pytree of
    arrays (dicts/tuples).  ``collate_fn`` overrides the default np.stack.
    """

    def __init__(self, dataset, batch_size, collate_fn=None, seed=0,
                 shuffle=True, drop_last=True, num_processes=None,
                 process_index=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or self._default_collate
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.num_processes = (num_processes if num_processes is not None
                              else jax.process_count())
        self.process_index = (process_index if process_index is not None
                              else jax.process_index())
        self.epoch = 0
        assert batch_size % self.num_processes == 0, \
            "global batch must divide across processes"
        self.local_batch = batch_size // self.num_processes

    def set_epoch(self, epoch):
        self.epoch = epoch

    @staticmethod
    def _default_collate(samples):
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *samples)

    def __len__(self):
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def __iter__(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        # process-strided shard of each global batch (DistributedSampler-style)
        for start in range(0, n - self.batch_size + 1 if self.drop_last else n,
                           self.batch_size):
            idx = order[start:start + self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                break
            local = idx[self.process_index::self.num_processes]
            yield self.collate_fn([self.dataset[int(i)] for i in local])
        self.epoch += 1
