"""Data loading.

Parity: reference ``runtime/dataloader.py`` (``DeepSpeedDataLoader`` wrapping a
torch ``DataLoader`` + ``DistributedSampler``).  TPU design: one process may
feed many chips, so the loader yields **global** batches of numpy arrays and
the engine shards them onto the mesh with ``device_put`` (the device transfer
is where "distribution" happens — there is no per-rank sampler state to keep
in sync).  For multi-host, each process yields its process-local slice
(``process_index``-strided), matching ``DistributedSampler`` semantics.

Async input feed: :class:`DevicePrefetchIterator` moves the whole host side
of the step — sample fetch, collate, gas-stack, curriculum transform and the
sharded ``device_put`` — onto a background thread that works on batch *n+k*
while step *n* runs, so the training loop's only input cost is a queue pop.
This is the input-channel analogue of the param-stream overlap
(ZeRO-Infinity's "keep every transfer channel busy under compute").
"""

import math
import queue as queue_lib
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

import jax


class RepeatingLoader:
    """Parity: reference ``runtime/dataloader.py RepeatingLoader`` — wraps an
    iterator, restarting it at StopIteration."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batches an indexable dataset into numpy pytrees.

    dataset: a sequence of samples; each sample is an array or a pytree of
    arrays (dicts/tuples).  ``collate_fn`` overrides the default np.stack.
    ``num_workers`` > 1 fetches the samples of each batch through a thread
    pool (the reference's ``num_local_io_workers``) — ``pool.map`` preserves
    index order, so worker count never changes the produced batches.
    """

    def __init__(self, dataset, batch_size, collate_fn=None, seed=0,
                 shuffle=True, drop_last=True, num_processes=None,
                 process_index=None, num_workers=0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or self._default_collate
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.num_processes = (num_processes if num_processes is not None
                              else jax.process_count())
        self.process_index = (process_index if process_index is not None
                              else jax.process_index())
        self.num_workers = int(num_workers or 0)
        self._pool = None
        self.epoch = 0
        assert batch_size % self.num_processes == 0, \
            "global batch must divide across processes"
        self.local_batch = batch_size // self.num_processes

    def set_epoch(self, epoch):
        self.epoch = epoch

    @staticmethod
    def _default_collate(samples):
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *samples)

    def _fetch(self, indices):
        if self.num_workers > 1 and len(indices) > 1:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.num_workers,
                    thread_name_prefix="ds-io-worker")
            samples = list(self._pool.map(self.dataset.__getitem__,
                                          [int(i) for i in indices]))
        else:
            samples = [self.dataset[int(i)] for i in indices]
        return self.collate_fn(samples)

    def __len__(self):
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def __iter__(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        # process-strided shard of each global batch (DistributedSampler-style)
        for start in range(0, n - self.batch_size + 1 if self.drop_last else n,
                           self.batch_size):
            idx = order[start:start + self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                break
            local = idx[self.process_index::self.num_processes]
            yield self._fetch(local)
        self.epoch += 1


class DevicePrefetchIterator:
    """Device-prefetched input feed.

    A daemon worker pulls ``gas`` microbatches from ``source``, stacks them
    (gas>1), applies ``transform`` (curriculum truncation / data routing) and
    ``shard_fn`` (the engine's sharded device_put), and parks the finished
    device batch in a bounded queue of ``depth`` while earlier steps run.
    The consumer's ``next()`` is a queue pop — zero host-side input work on
    the hot path once the queue is warm.

    Termination is explicit and loss-free: ``StopIteration`` from the source
    drains through the queue as a sentinel (every already-prefetched batch
    is still delivered first), and a worker exception is re-raised in the
    consumer at the position it occurred.  ``close()`` stops the worker and
    releases queued device batches.

    Fault tolerance (``resilience.dataloader_max_retries``): a transient
    worker exception (OSError family — flaky storage, timeouts) is
    retried up to ``max_retries`` times with exponential backoff before
    it becomes fatal; non-I/O exceptions propagate immediately; ``injector`` hooks the
    deterministic ``dataloader_next`` fault site *before* the source
    iterator is consumed, so a retried attempt re-produces the same batch
    and ordering is preserved exactly.  A fatal exception still drains
    through the queue in order — every batch prefetched before it is
    delivered first, then the error re-raises in the consumer.
    """

    _END = object()

    def __init__(self, source, gas=1, shard_fn=None, transform=None,
                 depth=2, start_index=0, name="input-feed",
                 max_retries=0, retry_backoff_secs=0.05, injector=None,
                 telemetry=None):
        self._source = iter(source)
        self._gas = max(1, int(gas))
        self._shard_fn = shard_fn
        self._transform = transform
        self._index = int(start_index)
        self._queue = queue_lib.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._exhausted = False
        self._closed = False
        self._max_retries = max(0, int(max_retries))
        self._retry_backoff = float(retry_backoff_secs)
        self._injector = injector
        self._telemetry = telemetry
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ds-prefetch-{name}")
        self._thread.start()

    # -- worker --------------------------------------------------------
    def _produce_one(self):
        if self._injector is not None:
            # the fault site sits BEFORE next(source): a retried attempt
            # re-produces the identical batch, never skips one
            self._injector.check("dataloader_next")
        micro = [next(self._source) for _ in range(self._gas)]
        leading = self._gas > 1
        batch = (jax.tree_util.tree_map(lambda *xs: np.stack(xs), *micro)
                 if leading else micro[0])
        if self._transform is not None:
            batch = self._transform(batch, self._index, leading)
        if self._shard_fn is not None:
            batch = self._shard_fn(batch, leading_gas_dim=leading)
        self._index += 1
        return batch

    def _put(self, item):
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue_lib.Full:
                continue
        return False

    def _run(self):
        retries = 0
        try:
            while not self._stop.is_set():
                try:
                    batch = self._produce_one()
                    retries = 0
                except StopIteration:
                    self._put((self._END, None))
                    return
                except Exception as exc:
                    # Only OSError-family failures are transient (flaky
                    # storage, timeouts).  Anything else — including an
                    # exception raised inside a generator source, which
                    # is closed by the raise and would silently yield
                    # StopIteration on retry — propagates immediately.
                    if not isinstance(exc, OSError) or \
                            retries >= self._max_retries:
                        self._put(("err", exc))
                        return
                    retries += 1
                    if self._telemetry is not None:
                        self._telemetry.fault(
                            "fault/dataloader_retry",
                            attrs={"attempt": retries,
                                   "max_retries": self._max_retries,
                                   "error": repr(exc)[:200]})
                    delay = self._retry_backoff * (2.0 ** (retries - 1))
                    if delay > 0:
                        self._stop.wait(delay)  # interruptible backoff
                    continue
                if not self._put(("ok", batch)):
                    return
        except BaseException as exc:  # re-raised in the consumer
            self._put(("err", exc))

    # -- consumer ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted or self._closed:
            raise StopIteration
        while True:
            try:
                kind, payload = self._queue.get(timeout=0.5)
            except queue_lib.Empty:
                if not self._thread.is_alive() and self._queue.empty():
                    # can't happen through the normal paths (the worker
                    # always parks a sentinel) — defensive, not expected
                    raise RuntimeError("prefetch worker died without a "
                                       "sentinel")
                continue
            if kind is self._END:
                self._exhausted = True
                raise StopIteration
            if kind == "err":
                self._exhausted = True
                raise payload
            return payload

    def qsize(self):
        """Device batches parked and ready (host-side; sync-free)."""
        return self._queue.qsize()

    def close(self):
        """Stop the worker and drop queued batches.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # unblock a worker stuck in put() and release device references
        while True:
            try:
                self._queue.get_nowait()
            except queue_lib.Empty:
                break
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PrefetchingDataLoader:
    """What ``deepspeed_io`` returns when ``async_pipeline`` is enabled:
    iterating it yields PRE-SHARDED device train batches (gas-stacked)
    produced by a :class:`DevicePrefetchIterator`, so ``train_batch``
    consumes them with no host-side input work.  Starting a new epoch
    (``iter()``) closes the previous prefetcher first."""

    def __init__(self, loader, make_prefetcher):
        self.loader = loader
        self._make_prefetcher = make_prefetcher
        self._active = None

    def __len__(self):
        return len(self.loader)

    def set_epoch(self, epoch):
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def __iter__(self):
        if self._active is not None:
            self._active.close()
        self._active = self._make_prefetcher(iter(self.loader))
        return self._active

    def close(self):
        if self._active is not None:
            self._active.close()
            self._active = None
