"""Mixture-of-Quantization (MoQ): annealed weight quantization for training.

Parity: reference ``deepspeed/runtime/quantize.py:11`` (``Quantizer``) wired
at ``engine.py:1799`` — after each optimizer step the reference re-quantizes
the fp16 weight copies in place, annealing per-parameter precision from
``start_bits`` down to ``target_bits`` (halving-period schedule, optional
eigenvalue-scaled periods), with an optional fp16/quantized blend
(``fp16_mixed_quantize``) whose ratio decays each step.

TPU-first redesign: our engine stores only fp32 master params and casts to
the compute dtype inside the jitted step, so "quantize the fp16 copy after
step k" becomes "quantize-dequantize the compute-dtype view at cast time in
step k+1" — mathematically the same weights reach the forward pass, but the
QDQ is one fused elementwise pass XLA schedules with the cast (no extra HBM
round-trip, no in-place mutation).  The bit schedule is a pure function of
the (traced) global step, so a single compiled program covers the whole
anneal:

* drop thresholds: bit drop ``k`` (1-indexed) happens when
  ``qsteps >= period * 2**(k-1)`` — the closed form of the reference's
  ``q_period <<= 1`` on every drop;
* the mixed-fp16 ratio is ``max(0, 1 - change_ratio * (qsteps - t_last))``
  where ``t_last`` is the most recent drop threshold — the closed form of
  the reference's per-step decrement with reset-to-1.0 on each drop.

The eigenvalue-scaled period factor (``factor = 1 + floor(ev * 4)``,
reference ``quantize.py:71``) is inherently runtime-dynamic, so it is
supported on the host-driven :meth:`Quantizer.step_quantize` surface (which
mirrors the reference call signature) rather than inside jit.  The reference
itself hard-asserts eigenvalue MoQ disabled in config parsing
(``runtime/config.py:577`` area), so the in-jit path not supporting it drops
nothing the reference ships.

Config surface (same JSON): ``compression_training.weight_quantization.
shared_parameters`` — ``quantize_enabled``, ``quantize_weight_in_forward``
(False → this module owns quantization), ``quantize_groups``,
``quantization_type`` (symmetric|asymmetric), ``rounding``
(nearest|stochastic), ``fp16_mixed_quantize.{enabled,quantize_change_ratio}``,
``schedule_offset``; per-group ``start_bits``/``target_bits``/
``quantize_period`` in ``different_groups``.
"""

import math
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.compression.compress import _glob_to_regex
from deepspeed_tpu.utils.logging import logger


@dataclass
class MoQSchedule:
    """Per-parameter anneal plan (reference attaches these as tensor attrs
    ``start_bits``/``target_bits``/``q_period``)."""
    start_bits: int
    target_bits: int
    period: int              # initial period; doubles on every bit drop

    def thresholds(self) -> List[int]:
        """Steps at which drops 1..(start-target) fire, closed form."""
        n = max(0, self.start_bits - self.target_bits)
        return [self.period * (2 ** (k - 1)) for k in range(1, n + 1)]

    def bits_at(self, qsteps: int) -> int:
        drops = sum(1 for t in self.thresholds() if qsteps >= t)
        return max(self.target_bits, self.start_bits - drops)


# ---------------------------------------------------------------------------
# groupwise quantize-dequantize math (jit-traceable; ``bits`` may be traced)
# ---------------------------------------------------------------------------

def _group_view(x, groups: int):
    g = math.gcd(int(np.prod(x.shape)), max(1, int(groups)))
    return x.reshape(g, -1), g


def qdq_highbit(x, bits, groups: int = 1, q_type: str = "symmetric",
                rng=None):
    """>=3-bit groupwise quantize→dequantize (reference ``quantize_highbit``,
    ``quantize.py:79``).  ``bits`` may be a traced scalar; ``rng`` enables
    stochastic rounding (uniform [-0.5, 0.5) dither before round)."""
    orig_dtype = x.dtype
    flat, _ = _group_view(x.astype(jnp.float32), groups)
    q_range = jnp.asarray(2.0, jnp.float32) ** bits
    p = (jax.random.uniform(rng, flat.shape, jnp.float32, -0.5, 0.5)
         if rng is not None else 0.0)
    g_min = flat.min(axis=-1, keepdims=True)
    g_max = flat.max(axis=-1, keepdims=True)
    if q_type == "symmetric":
        scale = 2.0 * jnp.maximum(jnp.abs(g_min), jnp.abs(g_max)) / q_range
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(flat / scale + p),
                     -q_range / 2, q_range / 2 - 1) * scale
    elif q_type == "asymmetric":
        scale = (g_max - g_min) / q_range
        scale = jnp.where(scale == 0, 1.0, scale)
        zero = jnp.round(g_min / scale) * scale
        q = jnp.clip(jnp.round((flat - zero) / scale + p),
                     0, q_range - 1) * scale + zero
    else:
        raise ValueError(f"unknown quantization_type '{q_type}'")
    return q.reshape(x.shape).astype(orig_dtype)


def qdq_ternary(x, groups: int = 1):
    """2-bit symmetric ternary {-a, 0, +a} (reference ``quantize_tenary``)."""
    orig_dtype = x.dtype
    flat, _ = _group_view(x.astype(jnp.float32), groups)
    thres = 0.7 * jnp.mean(jnp.abs(flat), axis=-1, keepdims=True)
    mask = (jnp.abs(flat) > thres).astype(jnp.float32)
    denom = jnp.maximum(mask.sum(axis=-1, keepdims=True), 1.0)
    alpha = (mask * jnp.abs(flat)).sum(axis=-1, keepdims=True) / denom
    q = alpha * jnp.sign(flat) * mask
    return q.reshape(x.shape).astype(orig_dtype)


def qdq_binary(x, groups: int = 1):
    """1-bit sign * mean|x| (reference ``quantize_binary``)."""
    orig_dtype = x.dtype
    flat, _ = _group_view(x.astype(jnp.float32), groups)
    m = jnp.mean(jnp.abs(flat), axis=-1, keepdims=True)
    q = jnp.sign(flat) * m
    return q.reshape(x.shape).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Quantizer
# ---------------------------------------------------------------------------

class Quantizer:
    """MoQ controller over a params pytree.

    Reference surface: ``deepspeed/runtime/quantize.py:11``.  Construction
    args keep the reference names; schedules are attached per-leaf from the
    ``different_groups`` patterns via :meth:`attach`.
    """

    def __init__(self, q_groups: int = 1, q_mixed_fp16: bool = False,
                 q_change_ratio: float = 0.001, q_type: str = "symmetric",
                 q_rounding: str = "nearest", q_verbose: bool = False,
                 q_eigenvalue: bool = False, use_quantizer_kernel: bool = False,
                 layer_num: int = 0):
        self.q_groups = max(1, int(q_groups))
        self.q_mixed_fp16 = bool(q_mixed_fp16)
        self.q_change_ratio = float(q_change_ratio)
        self.q_type = q_type
        self.q_rounding = q_rounding
        self.q_verbose = bool(q_verbose)
        self.q_eigenvalue = bool(q_eigenvalue)
        self.use_quantizer_kernel = bool(use_quantizer_kernel)
        self.layer_num = layer_num
        # host-surface state (reference ``qsteps`` / ``quantize_real_ratio``)
        self.qsteps = 0
        self.quantize_real_ratio = 1.0
        self.schedule_offset = 0
        self.groups_cfg: Optional[List[Dict[str, Any]]] = None
        # path -> MoQSchedule (static plan) and path -> [bits, period,
        # last_drop] (host-mutable state for step_quantize)
        self.schedules: Dict[str, MoQSchedule] = {}
        self._host_state: Dict[str, List[int]] = {}

    # -- schedule attachment -------------------------------------------
    def attach(self, params, groups_cfg: Optional[List[Dict[str, Any]]] = None,
               default_start_bits: int = 16, default_target_bits: int = 8,
               default_period: int = 1000) -> "Quantizer":
        """Match >=2-D leaves against ``different_groups`` module patterns
        (reference: the compression wrapper sets ``start_bits`` etc. on each
        matched parameter) and record an anneal plan for each."""
        groups_cfg = groups_cfg or [{"modules": ["*"],
                                     "start_bits": default_start_bits,
                                     "target_bits": default_target_bits,
                                     "quantize_period": default_period}]

        def visit(path, leaf):
            if np.ndim(leaf) < 2:
                return leaf
            key = jax.tree_util.keystr(path)
            # the reference's ndim>1 test excludes torch's 1-D norm scales;
            # in our stacked-layers layout norms/embeddings are 2-D ([L, d]),
            # so the faithful exclusion is by name (same rule as the
            # inference int8 path, ADVICE r1 finding 3)
            lkey = key.lower()
            if "norm" in lkey or "embed" in lkey or lkey.endswith("_b']"):
                return leaf
            for g in groups_cfg:
                pats = g.get("modules", ["*"])
                if any(re.search(_glob_to_regex(p), key) for p in pats):
                    sched = MoQSchedule(
                        start_bits=int(g.get("start_bits",
                                             default_start_bits)),
                        target_bits=int(g.get("target_bits",
                                              default_target_bits)),
                        period=max(1, int(g.get("quantize_period",
                                                default_period))),
                    )
                    self.schedules[key] = sched
                    self._host_state[key] = [sched.start_bits, sched.period, 0]
                    break
            return leaf

        jax.tree_util.tree_map_with_path(visit, params)
        if self.q_verbose:
            logger.info(f"MoQ: attached schedules to "
                        f"{len(self.schedules)} parameter(s)")
        return self

    # -- in-jit surface (engine cast-site hook) -------------------------
    def transform(self, params, step, rng=None, schedule_offset: int = 0):
        """Quantize-dequantize the compute-dtype view of every scheduled
        leaf.  ``step`` may be a traced scalar; one compiled program covers
        warmup (< ``schedule_offset``: identity) and the entire anneal."""
        step = jnp.asarray(step, jnp.int32)
        qstep = step - int(schedule_offset)   # anneal clock starts at offset
        use_sr = self.q_rounding == "stochastic"
        leaf_keys = sorted(self.schedules)
        rngs = {}
        if use_sr and rng is not None:
            for k, r in zip(leaf_keys,
                            jax.random.split(rng, max(1, len(leaf_keys)))):
                rngs[k] = r

        def visit(path, leaf):
            key = jax.tree_util.keystr(path)
            sched = self.schedules.get(key)
            if sched is None or np.ndim(leaf) < 2:
                return leaf
            thresholds = sched.thresholds()
            if thresholds:
                tarr = jnp.asarray(thresholds, jnp.int32)
                fired = (qstep >= tarr)
                drops = fired.sum()
                t_last = jnp.max(jnp.where(fired, tarr, 0))
            else:
                drops = jnp.int32(0)
                t_last = jnp.int32(0)
            bits = jnp.maximum(sched.target_bits, sched.start_bits - drops)
            q = qdq_highbit(leaf, bits, self.q_groups, self.q_type,
                            rngs.get(key))
            if sched.target_bits <= 2:
                # low-bit endgame: select ternary/binary once bits anneal
                # past 3 (reference compute_quantization dispatch)
                q = jnp.where(bits >= 3, q,
                              jnp.where(bits == 2,
                                        qdq_ternary(leaf, self.q_groups),
                                        qdq_binary(leaf, self.q_groups)))
            if self.q_mixed_fp16:
                ratio = jnp.clip(
                    1.0 - self.q_change_ratio
                    * (qstep - t_last).astype(jnp.float32), 0.0, 1.0)
                blend = (ratio * leaf.astype(jnp.float32)
                         + (1.0 - ratio) * q.astype(jnp.float32)
                         ).astype(leaf.dtype)
                q = jnp.where(bits >= sched.target_bits - 1, blend, q)
            return jnp.where(qstep >= 0, q, leaf)

        return jax.tree_util.tree_map_with_path(visit, params)

    # -- host-driven surface (reference-shaped; eigenvalue-aware) -------
    def step(self):
        self.qsteps += 1

    def update_fp16_ratio(self):
        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(
                0.0, self.quantize_real_ratio - self.q_change_ratio)

    def step_quantize(self, params, overflow: bool = False,
                      eigenvalue_enabled: bool = False,
                      block_eigenvalue: Optional[Dict[str, float]] = None,
                      rng=None):
        """Post-step quantization with host-side schedule bookkeeping —
        the reference ``Quantizer.quantize`` call shape (``quantize.py:48``):
        skips on overflow (unless eigenvalue-driven), advances ``qsteps``,
        decays the mixed-fp16 ratio, and — when a drop fires — doubles the
        period scaled by ``factor = 1 + floor(ev * 4)`` for leaves with a
        block eigenvalue.  Returns the quantized tree."""
        if overflow and not eigenvalue_enabled:
            return params
        self.step()
        self.update_fp16_ratio()

        def visit(path, leaf):
            key = jax.tree_util.keystr(path)
            st = self._host_state.get(key)
            if st is None or np.ndim(leaf) < 2:
                return leaf
            sched = self.schedules[key]
            ev = (block_eigenvalue or {}).get(key)
            factor = 1 + math.floor(ev * 4) if ev is not None else 1
            if st[0] > sched.target_bits and self.qsteps >= st[1]:
                st[1] = st[1] * 2 * factor
                st[0] -= 1
                self.quantize_real_ratio = 1.0
                if self.q_verbose:
                    logger.info(f"MoQ: {key} -> {st[0]} bits at step "
                                f"{self.qsteps}, next period {st[1]}")
            bits = st[0]
            if bits >= 3:
                q = qdq_highbit(leaf, bits, self.q_groups, self.q_type, rng)
            elif bits == 2:
                q = qdq_ternary(leaf, self.q_groups)
            else:
                q = qdq_binary(leaf, self.q_groups)
            if self.q_mixed_fp16 and bits >= sched.target_bits - 1:
                r = self.quantize_real_ratio
                q = (r * leaf.astype(jnp.float32)
                     + (1.0 - r) * q.astype(jnp.float32)).astype(leaf.dtype)
            return q

        return jax.tree_util.tree_map_with_path(visit, params)

    def any_precision_switch(self) -> bool:
        """True while any leaf still has bits left to anneal."""
        return any(st[0] > self.schedules[k].target_bits
                   for k, st in self._host_state.items())


def build_quantizer_from_config(compression_cfg: Dict[str, Any]
                                ) -> Optional[Quantizer]:
    """Engine hook: parse ``compression_training.weight_quantization``;
    returns a Quantizer when MoQ (quantize in step, not in forward) is
    enabled (reference ``engine._configure_quantization:1407``)."""
    wq = (compression_cfg or {}).get("weight_quantization", {})
    shared = wq.get("shared_parameters", {})
    # reference spelling is "enabled" (WEIGHT_QUANTIZE_ENABLED =
    # TECHNIQUE_ENABLED, compression/constants.py:10); accept
    # "quantize_enabled" as a lenient alias
    if not (shared.get("enabled", False)
            or shared.get("quantize_enabled", False)):
        return None
    if shared.get("quantize_weight_in_forward", False):
        return None      # compression's in-forward STE path owns it
    q = quantizer_from_shared(shared)
    q.groups_cfg = [dict(g, name=name) for name, g in
                    wq.get("different_groups", {}).items()
                    for g in [dict(g.get("params", {}),
                               modules=g.get("modules", ["*"]))]]
    return q


def quantizer_from_shared(shared: Dict[str, Any]) -> Quantizer:
    """The single place the ``shared_parameters`` keys/defaults are read
    (both the live builder and ``engine.quantize_training()`` use it, so the
    two can't drift)."""
    mixed = shared.get("fp16_mixed_quantize", {})
    q = Quantizer(
        q_groups=shared.get("quantize_groups", 1),
        q_mixed_fp16=mixed.get("enabled", False),
        q_change_ratio=mixed.get("quantize_change_ratio", 0.001),
        q_type=shared.get("quantization_type", "symmetric"),
        q_rounding=shared.get("rounding", "nearest"),
        q_verbose=shared.get("quantize_verbose", False),
        q_eigenvalue=shared.get("eigenvalue", {}).get("enabled", False),
        use_quantizer_kernel=shared.get("quantizer_kernel", False),
    )
    q.schedule_offset = int(shared.get("schedule_offset", 0))
    return q
