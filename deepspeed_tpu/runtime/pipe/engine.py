"""PipelineEngine — training engine for PipelineModule models.

Parity: reference ``runtime/pipe/engine.py`` (``PipelineEngine``:
``train_batch:295``, ``eval_batch:380``, ``_exec_schedule:1360``).

TPU-first: the reference subclasses DeepSpeedEngine and replaces the train
step with an imperative instruction interpreter.  Here the subclass only
changes *what gets jitted*: the whole GPipe clock (fill → steady → drain →
reverse/backward → reduce → step) is the single compiled program produced
by ``PipelineModule.loss`` + autodiff (see ``pipe/pipeline.py``), so
``train_batch`` keeps the parent's shape: shard batch, run step, log.

Composition rules match the reference: ZeRO stages 0/1 compose with PP
(``engine.py:1541`` — ZeRO-2/3 do not); grads for body params reduce over
the data axes only (XLA scopes collectives per named axis automatically —
body grads are pp-sharded so no reduction crosses stages, the
``ReduceGrads``/``ReduceTiedGrads`` distinction falls out of the sharding).
"""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.engine import (DeepSpeedEngine, TrainState,
                                          moq_anneal_step)
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(DeepSpeedEngine):

    def __init__(self, model, config, **kwargs):
        assert isinstance(model, PipelineModule), \
            "PipelineEngine requires a PipelineModule model"
        if kwargs.get("params") is None:
            raise ValueError("model_parameters (from PipelineModule.init) "
                             "is required")
        # tp_rules default comes from the base engine's auto-TP
        # (DeepSpeedEngine.__init__ pulls model.tp_rules())
        if config.zero_config.offload_param_device != "none":
            raise ValueError(
                "offload_param (param-stream) does not compose with "
                "pipeline parallelism: the pipelined step is one jitted "
                "SPMD scan with no per-layer program boundary to stream "
                "through (the reference draws the same line — ZeRO-3 param "
                "partitioning is incompatible with PP, engine.py:1541).  "
                "Use offload_optimizer (host Adam at the step boundary) "
                "with PP instead.")
        super().__init__(model=model, config=config, **kwargs)
        assert self.zero_stage <= 1, (
            "ZeRO-2/3 is incompatible with pipeline parallelism "
            "(reference engine.py:1541); use stage 0 or 1")
        self.micro_batches = self.gradient_accumulation_steps_
        self.num_stages = model.num_stages
        log_dist(
            f"PipelineEngine: stages={self.num_stages} "
            f"micro_batches={self.micro_batches} "
            f"bubble={(self.num_stages - 1) / (self.micro_batches + self.num_stages - 1):.2f}",
            ranks=[0])
        if self._tel_enabled:
            self._emit_schedule_telemetry()

    def _emit_schedule_telemetry(self):
        """One ``meta`` event per stage describing the schedule phases the
        compiled scan realises (fill/active/drain tick counts plus an
        instruction census from :class:`TrainSchedule`).  The per-phase
        spans *inside* the step are the trace-time ``pipe/*`` named scopes
        (see ``pipe/pipeline.py``) — visible in xprof, not host-timeable,
        because the whole clock is one XLA program."""
        M, P = self.micro_batches, self.num_stages
        ap = self._config.async_pipeline_config
        for s in range(P):
            counts = {}
            for cmds in TrainSchedule(micro_batches=M, stages=P, stage_id=s):
                for c in cmds:
                    k = type(c).__name__
                    counts[k] = counts.get(k, 0) + 1
            self.telemetry.emit(
                "meta", f"pipe/schedule/stage{s}",
                attrs={"stage": s, "stages": P, "micro_batches": M,
                       "fill_ticks": s, "active_ticks": M,
                       "drain_ticks": P - 1 - s,
                       "bubble": (P - 1) / (M + P - 1),
                       "instructions": counts,
                       # whether the microbatch stack arrives prefetched
                       # and how often metric readback syncs the host
                       "async_pipeline": bool(ap.enabled),
                       "prefetch_depth": int(ap.prefetch_depth),
                       "sync_interval": int(ap.sync_interval)})

    # the compiled step: ONE loss call over the microbatch stack — the
    # microbatch dim is the pipeline clock, not a grad-accumulation scan
    def _build_train_step(self, gas: int):
        cfg = self._config
        fp16 = cfg.fp16_enabled

        def train_step(state: TrainState, batch):
            if gas == 1:  # ensure the leading microbatch dim exists
                batch = jax.tree_util.tree_map(lambda x: x[None], batch)
            scale = state.loss_scale.cur_scale if fp16 else jnp.float32(1.0)
            rng, step_rng = jax.random.split(state.rng)
            loss, grads = self._loss_and_grads(
                state.params, scale, batch, step_rng,
                step=state.global_step,
                qstep=moq_anneal_step(state))
            return self._finish_step(state, loss, grads, rng)

        return train_step

    # ZeRO-Offload x PP: the base builder wraps a GAS scan around the loss,
    # but here the microbatch dim IS the pipeline clock — build the grad
    # step from the pipelined loss directly.  The host tail (streamed D2H /
    # C++ Adam / streamed H2D, engine._offload_host_apply) is shared.
    def _get_compiled_offload_grad_step(self, gas: int):
        if gas not in self._compiled_offload_grad:
            from deepspeed_tpu.runtime.engine import (_global_norm_f32,
                                                      constrain,
                                                      has_inf_or_nan)
            fp16 = self._config.fp16_enabled

            def grad_step(state: TrainState, batch):
                if gas == 1:
                    batch = jax.tree_util.tree_map(lambda x: x[None], batch)
                scale = (state.loss_scale.cur_scale if fp16
                         else jnp.float32(1.0))
                rng, step_rng = jax.random.split(state.rng)
                loss, grads = self._loss_and_grads(
                    state.params, scale, batch, step_rng,
                    step=state.global_step, qstep=moq_anneal_step(state))
                grads = constrain(grads, self.plan.grad_specs(state.params),
                                  self.mesh)
                overflow = (has_inf_or_nan(grads) if fp16
                            else jnp.asarray(False))
                grad_norm = _global_norm_f32(grads)
                return loss, grads, overflow, grad_norm, rng
            self._compiled_offload_grad[gas] = self._wrap_compiled(
                jax.jit(grad_step), f"pipe/offload_grad:{gas}")
        return self._compiled_offload_grad[gas]

    def _model_scaled_loss(self, p_c, batch, rng, loss_scale):
        """Scale AT THE SOURCE: the interleaved 1F1B backward runs inside
        module.loss — fp16 cotangents must enter the pipe pre-amplified
        (reference scales the loss before backward; multiplying afterwards
        in the outer vjp would let small fp16 cotangents flush to zero
        inside the scan)."""
        with jax.named_scope("pipe/train_clock"):
            scaled = self.module.loss(p_c, batch, rng, loss_scale=loss_scale)
        return scaled.astype(jnp.float32), scaled / loss_scale

    # the 3-call API is train-schedule-incompatible with pipelining
    # (reference PipelineEngine raises the same way)
    def forward(self, *args, **kwargs):
        raise RuntimeError(
            "PipelineEngine does not support forward(); "
            "use train_batch() / eval_batch() instead")

    def backward(self, *args, **kwargs):
        raise RuntimeError(
            "PipelineEngine does not support backward(); "
            "use train_batch() instead")

    def step(self, *args, **kwargs):
        raise RuntimeError(
            "PipelineEngine does not support step(); "
            "use train_batch() instead")

    # eval_batch is the parent's, with pipelined batch prep: stack a flat
    # batch into an M=1 microbatch dim and keep the leading clock dim
    # (reference ``eval_batch:380``).
    _eval_leading_gas_dim = True

    def _prep_eval_batch(self, batch):
        return self._stack_if_flat(batch)

    def _stack_if_flat(self, batch):
        """Add an M=1 microbatch dim when the caller passed a flat batch."""
        probe = jax.tree_util.tree_leaves(batch)[0]
        ids_ndim = 2  # [B, S] token batches
        if np.ndim(probe) <= ids_ndim:
            return jax.tree_util.tree_map(lambda x: np.asarray(x)[None], batch)
        return batch

    # parity introspection ------------------------------------------------
    def is_pipe_parallel(self):
        return self.num_stages > 1

    def train_schedule(self, stage_id: int = 0) -> TrainSchedule:
        """The instruction stream the compiled program realises for one
        stage (introspection/debugging parity)."""
        return TrainSchedule(micro_batches=self.micro_batches,
                             stages=self.num_stages, stage_id=stage_id)
