"""Pipeline schedules — instruction-stream descriptions of the pipelined step.

Parity: reference ``runtime/pipe/schedule.py`` (``PipeSchedule:10``,
``InferenceSchedule:131``, ``TrainSchedule:184``, instruction classes
:324-483).

Role difference: the reference *executes* these instructions imperatively
(``pipe/engine.py:1360 _exec_schedule`` maps each to a method doing NCCL
p2p / compute).  Here execution is a single compiled SPMD program
(:mod:`deepspeed_tpu.runtime.pipe.pipeline`); the schedule classes describe
that program tick-by-tick so tools/tests can reason about ordering, buffer
counts and the bubble — and so code written against the reference's schedule
API ports over.
"""

from typing import List


# ----------------------------------------------------------------------
# Instructions (parity: schedule.py:324-483)
# ----------------------------------------------------------------------
class PipeInstruction:
    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        if self.kwargs:
            args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
            return f"{self.name}({args})"
        return self.name

    def __eq__(self, other):
        return (self.__class__ is other.__class__ and
                self.kwargs == other.kwargs)


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


# ----------------------------------------------------------------------
# Schedules
# ----------------------------------------------------------------------
class PipeSchedule:
    """Yields a list of :class:`PipeInstruction` per step for one stage.

    Parity: reference ``schedule.py:10`` — same constructor signature and
    iteration protocol (``steps()`` generator, ``__iter__``)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    def steps(self):
        raise NotImplementedError()

    def num_pipe_buffers(self) -> int:
        return 2

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id) -> bool:
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id) -> bool:
        return 0 <= stage_id < self.stages

    def _buffer_idx(self, micro_batch_id) -> int:
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Forward-only GPipe clocking — exactly the tick loop compiled by
    :func:`pipeline_spmd` (parity: reference ``schedule.py:131``)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            valid = self._valid_micro_batch(micro_batch_id)
            if valid:
                buf = self._buffer_idx(micro_batch_id)
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf))
                else:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            yield cmds

    def num_pipe_buffers(self) -> int:
        return 2


class TrainSchedule(PipeSchedule):
    """Training clocking: the forward GPipe sweep, then the autodiff-reversed
    backward sweep, then grad reduction + optimizer step.

    Parity note: the reference ``TrainSchedule:184`` interleaves 1F1B to cap
    live buffers at ``stages`` (``num_pipe_buffers``); our compiled program
    caps memory with remat instead, so the instruction stream here is the
    fill/drain order the compiled scan actually executes.  Total instruction
    counts per stage (forwards, backwards, sends, recvs) match the reference
    exactly — tests assert this invariant.
    """

    def steps(self):
        fwd_steps = self.micro_batches + self.stages - 1
        # forward sweep
        for step_id in range(fwd_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf))
                else:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            yield cmds
        # backward sweep (reverse clock: grads flow last stage → first)
        rev_stage = self.stages - 1 - self.stage_id
        for step_id in range(fwd_steps):
            micro_batch_id = self.micro_batches - 1 - (step_id - rev_stage)
            cmds = []
            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                if not self.is_last_stage:
                    cmds.append(RecvGrad(buf))
                cmds.append(BackwardPass(buf))
                if not self.is_first_stage:
                    cmds.append(SendGrad(buf))
            yield cmds
        # epilogue: DP gradient reduction + step (one fused XLA region)
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]

    def num_pipe_buffers(self) -> int:
        """Live activation buffers. With remat the compiled program keeps
        ``stages`` boundary buffers live (reference 1F1B keeps the same
        bound: ``min(stages, micro_batches)``)."""
        return min(self.stages, self.micro_batches)


class DataParallelSchedule(PipeSchedule):
    """Degenerate no-pipeline schedule (parity: reference ``schedule.py``)."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(buffer_id=0), ForwardPass(buffer_id=0),
                    BackwardPass(buffer_id=0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self) -> int:
        return 1
