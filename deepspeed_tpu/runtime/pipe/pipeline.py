"""SPMD pipeline executor — the TPU-native replacement for the reference's
instruction-interpreter pipeline engine.

Reference design (``runtime/pipe/engine.py:1360 _exec_schedule``): every rank
runs a Python loop over schedule instructions (LoadMicroBatch / ForwardPass /
SendActivation / ... ) and moves activations with point-to-point NCCL calls
(``pipe/p2p.py``).

TPU-first redesign: the WHOLE pipelined step is one jitted SPMD program.

* Stage parameters carry a leading ``[P, ...]`` dim sharded over the ``pp``
  mesh axis; each device therefore *is* one pipeline stage.
* A ``lax.scan`` over ``T = M + P - 1`` clock ticks advances a ``[P, ...]``
  activation buffer.  Per tick every stage applies its chunk of layers
  (``jax.vmap`` over the stage dim — the SPMD partitioner assigns each
  stage's compute to its pp rank), then the buffer is shifted one slot with
  ``jnp.roll`` along the pp-sharded dim, which XLA lowers to a
  ``CollectivePermute`` over ICI — the p2p send/recv of the reference.
* The backward pipeline is **not hand-written**: differentiating the scan
  yields the reverse-clocked pipeline (grad ticks flow last-stage→first),
  which is exactly the reference's BackwardPass/SendGrad/RecvGrad stream.

Schedules (both have bubble fraction ``(P-1)/(M+P-1)``; they differ in
peak activation memory, exactly like the reference's ``InferenceSchedule``
vs ``TrainSchedule``):

* ``"gpipe"`` — one flat scan over the T clock ticks.  Scan autodiff saves
  every tick's [P, ...] stage-input buffer: O(M) residuals per device.
* ``"1f1b"`` (default) — the T ticks run as an outer scan over chunks of P
  ticks with the chunk body rematerialised (``jax.checkpoint``).  Autodiff
  then saves only the [P, ...] carry at each chunk boundary and replays a
  chunk's ticks during backward: O(M/P + P) residuals per device — the
  1F1B operating point (peak ≈ P in-flight microbatches), bought with one
  forward recompute, the same price the reference pays for
  activation-checkpointed 1F1B (``runtime/pipe/schedule.py:184``
  ``TrainSchedule`` + activation checkpointing).
"""

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import BATCH_AXES, PP_AXIS
from deepspeed_tpu.runtime.zero.stage_plan import maybe_constrain


def _buf_spec(ndim: int) -> P:
    """[P, mb, ...]: stage dim over pp, microbatch dim over the data axes."""
    entries = [PP_AXIS, tuple(BATCH_AXES)] + [None] * (ndim - 2)
    return P(*entries)


def pipeline_spmd(stage_fn: Callable,
                  stage_params: Any,
                  x_mbs: jax.Array,
                  num_stages: int,
                  remat: bool = False,
                  schedule: str = "1f1b") -> jax.Array:
    """Run ``M`` microbatches through ``P = num_stages`` pipeline stages.

    Args:
      stage_fn: ``(stage_params_slice, x) -> y`` with ``y.shape == x.shape``
        (one stage's chunk of layers).
      stage_params: pytree whose leaves have leading dim ``P`` (shard it over
        the ``pp`` mesh axis).
      x_mbs: ``[M, ...]`` microbatched activations entering stage 0.
      remat: rematerialise the stage body itself (intra-stage activations).
      schedule: ``"1f1b"`` (chunked remat over ticks — peak activation
        residuals capped at ~P in-flight microbatches) or ``"gpipe"``
        (flat scan — O(M) residuals, no tick recompute).

    Returns: ``[M, ...]`` outputs of the last stage.
    """
    if schedule not in ("1f1b", "gpipe"):
        raise ValueError(f"unknown pipeline schedule '{schedule}' "
                         "(1f1b|gpipe)")
    M = x_mbs.shape[0]
    Pn = num_stages
    T = M + Pn - 1
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    if Pn == 1:
        # degenerate pipeline: plain microbatch loop
        def one(carry, x):
            return carry, stage_fn(
                jax.tree_util.tree_map(lambda p: p[0], stage_params), x)
        _, ys = jax.lax.scan(one, (), x_mbs)
        return ys

    vstage = jax.vmap(stage_fn)
    feat_shape = x_mbs.shape[1:]
    buf = jnp.zeros((Pn,) + feat_shape, x_mbs.dtype)
    buf = maybe_constrain(buf, _buf_spec(buf.ndim))

    def tick(buf, t):
        # LoadMicroBatch: microbatch t enters stage 0 while t < M
        inp = jax.lax.dynamic_index_in_dim(
            x_mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        slot0 = jnp.where(t < M, inp, buf[0])
        buf = jax.lax.dynamic_update_index_in_dim(buf, slot0, 0, 0)
        buf = maybe_constrain(buf, _buf_spec(buf.ndim))
        # ForwardPass on every stage (stage s holds microbatch t - s)
        y = vstage(stage_params, buf)
        y = maybe_constrain(y, _buf_spec(y.ndim))
        # SendActivation/RecvActivation: shift one slot down the pipe
        # (roll over the pp-sharded dim → CollectivePermute); the last
        # stage's output is this tick's exit (microbatch t - (P-1))
        return jnp.roll(y, 1, axis=0), y[Pn - 1]

    if schedule == "gpipe":
        _, ys = jax.lax.scan(tick, buf, jnp.arange(T))
    else:
        # 1f1b-memory schedule: chunks of P ticks, chunk body remat'd, so
        # autodiff saves one [P, ...] carry per chunk boundary instead of
        # every tick's buffer (padding ticks past T are harmless: they
        # load nothing and their outputs are sliced off below)
        chunk = Pn
        T_pad = -(-T // chunk) * chunk

        def run_chunk(buf, ts):
            return jax.lax.scan(tick, buf, ts)

        run_chunk = jax.checkpoint(run_chunk, prevent_cse=False)
        _, ys = jax.lax.scan(run_chunk, buf,
                             jnp.arange(T_pad).reshape(-1, chunk))
        ys = ys.reshape((T_pad,) + ys.shape[2:])
    # tick t emits microbatch t-(P-1): the valid window is [P-1, P-1+M)
    out = jax.lax.slice_in_dim(ys, Pn - 1, Pn - 1 + M, axis=0)
    entries = [None, tuple(BATCH_AXES)] + [None] * (out.ndim - 2)
    return maybe_constrain(out, P(*entries))


def stack_stage_params(body_params: Any, num_stages: int) -> Any:
    """Reshape stacked per-layer params ``[L, ...]`` into per-stage chunks
    ``[P, L/P, ...]`` (contiguous layer ranges per stage, like the
    reference's ``PipelineModule`` uniform partitioning)."""
    def reshape(leaf):
        L = leaf.shape[0]
        assert L % num_stages == 0, \
            f"n_layers {L} not divisible by num_stages {num_stages}"
        return leaf.reshape((num_stages, L // num_stages) + leaf.shape[1:])
    return jax.tree_util.tree_map(reshape, body_params)


def unstack_stage_params(stage_params: Any) -> Any:
    """Inverse of :func:`stack_stage_params`: ``[P, L/P, ...]`` → ``[L, ...]``."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((-1,) + leaf.shape[2:]), stage_params)
