"""SPMD pipeline executor — the TPU-native replacement for the reference's
instruction-interpreter pipeline engine.

Reference design (``runtime/pipe/engine.py:1360 _exec_schedule``): every rank
runs a Python loop over schedule instructions (LoadMicroBatch / ForwardPass /
SendActivation / ... ) and moves activations with point-to-point NCCL calls
(``pipe/p2p.py``).

TPU-first redesign: the WHOLE pipelined step is one jitted SPMD program.

* Stage parameters carry a leading ``[P, ...]`` dim sharded over the ``pp``
  mesh axis; each device therefore *is* one pipeline stage.
* A ``lax.scan`` over ``T = M + P - 1`` clock ticks advances a ``[P, ...]``
  activation buffer.  Per tick every stage applies its chunk of layers
  (``jax.vmap`` over the stage dim — the SPMD partitioner assigns each
  stage's compute to its pp rank), then the buffer is shifted one slot with
  ``jnp.roll`` along the pp-sharded dim, which XLA lowers to a
  ``CollectivePermute`` over ICI — the p2p send/recv of the reference.
* The backward pipeline is **not hand-written**: differentiating the scan
  yields the reverse-clocked pipeline (grad ticks flow last-stage→first),
  which is exactly the reference's BackwardPass/SendGrad/RecvGrad stream.

Schedules (all have bubble fraction ``O(P/M)``; they differ in peak
activation memory and recompute, like the reference's ``InferenceSchedule``
vs ``TrainSchedule``):

* ``"gpipe"`` — one flat scan over the T clock ticks.  Scan autodiff saves
  every tick's residuals: O(M) in-flight microbatches per device.
* ``"1f1b"`` (default) — TRUE interleaved 1F1B
  (:func:`pipeline_train_1f1b`): one scan whose every tick runs a forward
  sub-tick AND a backward sub-tick, with each stage keeping the VJP
  residuals of its in-flight microbatches in a ring buffer of ``2P-1``
  slots.  Peak residual memory is O(P) in-flight microbatches per device —
  independent of M — with NO forward recompute, matching the reference's
  ``TrainSchedule`` (``runtime/pipe/schedule.py:184``) which interleaves
  fwd/bwd so peak in-flight activations stay ≈P without checkpointing.
  (The lockstep SPMD formulation holds ≤2P-1 in-flight at stage 0 vs the
  reference's P — same asymptotics, a constant-factor trade for running
  every stage's fwd+bwd in one compiled program.)
* ``"1f1b-remat"`` — the previous round's schedule: GPipe ordering with the
  tick scan rematerialised in chunks of P.  Same O(P) residual cap, bought
  with one extra forward recompute per chunk — the price the reference
  pays for activation-checkpointed 1F1B.  Kept for models whose stage
  functions defeat the residual-threading of true 1F1B.
"""

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import BATCH_AXES, PP_AXIS
from deepspeed_tpu.runtime.zero.stage_plan import maybe_constrain


def _buf_spec(ndim: int) -> P:
    """[P, mb, ...]: stage dim over pp, microbatch dim over the data axes."""
    entries = [PP_AXIS, tuple(BATCH_AXES)] + [None] * (ndim - 2)
    return P(*entries)


def pipeline_spmd(stage_fn: Callable,
                  stage_params: Any,
                  x_mbs: jax.Array,
                  num_stages: int,
                  remat: bool = False,
                  schedule: str = "1f1b",
                  with_aux: bool = False):
    """Run ``M`` microbatches through ``P = num_stages`` pipeline stages.

    Args:
      stage_fn: ``(stage_params_slice, x) -> y`` with ``y.shape == x.shape``
        (one stage's chunk of layers).
      stage_params: pytree whose leaves have leading dim ``P`` (shard it over
        the ``pp`` mesh axis).
      x_mbs: ``[M, ...]`` microbatched activations entering stage 0.
      remat: rematerialise the stage body itself (intra-stage activations).
      schedule: ``"1f1b-remat"`` (chunked remat over ticks — peak
        activation residuals capped at ~P in-flight microbatches, one fwd
        replay), ``"gpipe"`` (flat scan — O(M) residuals, no recompute), or
        ``"1f1b"`` (alias for ``"1f1b-remat"`` here: TRUE interleaved 1F1B
        training lives in :func:`pipeline_train_1f1b`; this function is the
        forward pipeline only).

    Returns: ``[M, ...]`` outputs of the last stage.
    """
    if schedule not in ("1f1b", "1f1b-remat", "gpipe"):
        raise ValueError(f"unknown pipeline schedule '{schedule}' "
                         "(1f1b|1f1b-remat|gpipe)")
    if schedule == "1f1b":
        # training goes through pipeline_train_1f1b (interleaved backward);
        # a direct caller differentiating THIS function still deserves the
        # O(P) residual cap, so map to the chunked-remat scan — on a
        # forward-only path jax.checkpoint costs nothing
        schedule = "1f1b-remat"
    M = x_mbs.shape[0]
    Pn = num_stages
    T = M + Pn - 1
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def call_stage(sp, x):
        # with_aux contract: stage_fn returns (y, aux_scalar) — MoE bodies
        # emit the gate load-balance loss per (stage, microbatch)
        out = stage_fn(sp, x)
        return out if with_aux else (out, jnp.float32(0.0))

    if Pn == 1:
        # degenerate pipeline: plain microbatch loop
        def one(aux, x):
            y, a = call_stage(
                jax.tree_util.tree_map(lambda p: p[0], stage_params), x)
            return aux + a, y
        aux, ys = jax.lax.scan(one, jnp.float32(0.0), x_mbs)
        return (ys, aux) if with_aux else ys

    vstage = jax.vmap(call_stage)
    feat_shape = x_mbs.shape[1:]
    buf = jnp.zeros((Pn,) + feat_shape, x_mbs.dtype)
    buf = maybe_constrain(buf, _buf_spec(buf.ndim))
    stage_ids = jnp.arange(Pn)

    def tick(buf, t):
        # LoadMicroBatch: microbatch t enters stage 0 while t < M
        inp = jax.lax.dynamic_index_in_dim(
            x_mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        slot0 = jnp.where(t < M, inp, buf[0])
        buf = jax.lax.dynamic_update_index_in_dim(buf, slot0, 0, 0)
        buf = maybe_constrain(buf, _buf_spec(buf.ndim))
        # ForwardPass on every stage (stage s holds microbatch t - s)
        with jax.named_scope("pipe/forward_pass"):
            y, aux_s = vstage(stage_params, buf)
        y = maybe_constrain(y, _buf_spec(y.ndim))
        # aux only from slots holding a REAL microbatch (warmup/drain slots
        # run on zero/stale activations — their gate stats are garbage)
        mb_s = t - stage_ids
        valid = (mb_s >= 0) & (mb_s < M)
        aux_t = jnp.sum(jnp.where(valid, aux_s, 0.0))
        # SendActivation/RecvActivation: shift one slot down the pipe
        # (roll over the pp-sharded dim → CollectivePermute); the last
        # stage's output is this tick's exit (microbatch t - (P-1))
        with jax.named_scope("pipe/send_activation"):
            shifted = jnp.roll(y, 1, axis=0)
        return shifted, (y[Pn - 1], aux_t)

    if schedule == "gpipe":
        _, (ys, auxs) = jax.lax.scan(tick, buf, jnp.arange(T))
    else:
        # 1f1b-memory schedule: chunks of P ticks, chunk body remat'd, so
        # autodiff saves one [P, ...] carry per chunk boundary instead of
        # every tick's buffer (padding ticks past T are harmless: they
        # load nothing, their outputs are sliced off below, and their aux
        # is masked out)
        chunk = Pn
        T_pad = -(-T // chunk) * chunk

        def run_chunk(buf, ts):
            return jax.lax.scan(tick, buf, ts)

        run_chunk = jax.checkpoint(run_chunk, prevent_cse=False)
        _, (ys, auxs) = jax.lax.scan(run_chunk, buf,
                                     jnp.arange(T_pad).reshape(-1, chunk))
        ys = ys.reshape((T_pad,) + ys.shape[2:])
        auxs = auxs.reshape(-1)
    # tick t emits microbatch t-(P-1): the valid window is [P-1, P-1+M)
    out = jax.lax.slice_in_dim(ys, Pn - 1, Pn - 1 + M, axis=0)
    entries = [None, tuple(BATCH_AXES)] + [None] * (out.ndim - 2)
    out = maybe_constrain(out, P(*entries))
    return (out, jnp.sum(auxs)) if with_aux else out


# ----------------------------------------------------------------------
# True interleaved 1F1B (reference runtime/pipe/schedule.py:184
# TrainSchedule): every tick runs one forward AND one backward sub-tick,
# so backward for microbatch m starts the tick after its forward exits and
# each stage's live residual count is bounded by the ring size 2P-1 —
# independent of M, with no forward recompute.
#
# The stage backward is hand-threaded: jax.vjp's pullback closure is
# converted to a pure function + explicit residual arrays
# (jax.closure_convert); residuals that depend on the stage INPUT are
# carried per-(stage, in-flight microbatch) in ring buffers, while
# residuals that depend only on the stage params (weight matrices saved
# for matmul transposes) are computed once and shared across ticks — the
# same storage split torch autograd gets implicitly (shared weight refs +
# per-microbatch activation residuals).
# ----------------------------------------------------------------------

def _ring_spec(ndim: int) -> P:
    """[K, P, ...]: ring dim replicated, stage dim over pp."""
    return P(*([None, PP_AXIS] + [None] * (ndim - 2)))


def _x_dependence(fn, sp_slice, x_slice):
    """For ``fn(sp, x) -> (y, c0, c1, ...)`` return a bool per output:
    does it depend (conservatively) on ``x``?  Walks the jaxpr dataflow;
    any equation touching an x-descendant marks all its outputs."""
    jpr = jax.make_jaxpr(fn)(sp_slice, x_slice)
    jaxpr = jpr.jaxpr
    n_sp = len(jax.tree_util.tree_leaves(sp_slice))
    Var = type(jaxpr.invars[0])
    dep = set(jaxpr.invars[n_sp:])
    for eqn in jaxpr.eqns:
        if any(isinstance(v, Var) and v in dep for v in eqn.invars):
            dep.update(eqn.outvars)
    return [isinstance(v, Var) and v in dep for v in jaxpr.outvars], \
        [(v.aval.shape, v.aval.dtype) for v in jaxpr.outvars]


def pipeline_train_1f1b(stage_fn: Callable,
                        head_fn: Callable,
                        num_stages: int,
                        stage_params: Any,
                        head_params: Any,
                        x_mbs: jax.Array,
                        batch_mbs: Any,
                        loss_ct=None):
    """Pipelined ``mean_m head_fn(head_params, pipe(x_m), batch_m)`` with a
    true-1F1B gradient schedule.

    Differentiable wrt ``stage_params``, ``head_params``, ``x_mbs`` and the
    floating leaves of ``batch_mbs`` (``jax.custom_vjp``: the interleaved
    scan computes the gradients itself; the outer autodiff only chain-rules
    through them, so embedding/pre layers and ZeRO machinery compose
    unchanged).

    Args:
      stage_fn: ``(stage_params_slice, x) -> y`` (shape-preserving).
      head_fn: ``(head_params, y_exit, microbatch) -> scalar loss`` — the
        post-pipeline layers + loss, applied per microbatch at exit time
        (1F1B needs the exit cotangent while later microbatches are still
        in the forward pipe, so the loss head must live inside).
      num_stages: P.
      x_mbs: ``[M, ...]`` activations entering stage 0.
      batch_mbs: pytree with leading microbatch dim M (loss targets).
      loss_ct: optional loss-scale seed.  fp16 cotangents must ride the
        pipe PRE-amplified (the reference scales the loss before backward;
        applying the scale afterwards in the vjp would let small fp16
        cotangents flush to zero inside the scan).  When given, the return
        value is ``loss * loss_ct`` and internal gradients carry the scale.

    Returns: scalar loss (× ``loss_ct`` if given), mean over microbatches.
    """
    if loss_ct is None:
        loss_ct = jnp.float32(1.0)
    return _pipeline_1f1b_vjp(stage_fn, head_fn, num_stages)(
        stage_params, head_params, x_mbs, batch_mbs, loss_ct)


def _pipeline_1f1b_vjp(stage_fn, head_fn, num_stages):
    """Build the custom-vjp'd closure for one (stage_fn, head_fn, P)."""

    @jax.custom_vjp
    def run(stage_params, head_params, x_mbs, batch_mbs, loss_ct):
        # primal-only path (no grad requested): plain forward pipeline
        ys = pipeline_spmd(stage_fn, stage_params, x_mbs, num_stages,
                           schedule="gpipe")
        M = x_mbs.shape[0]

        def mb_loss(i, acc):
            y = jax.tree_util.tree_map(lambda l: l[i], ys)
            mb = jax.tree_util.tree_map(lambda l: l[i], batch_mbs)
            return acc + head_fn(head_params, y, mb)
        total = jax.lax.fori_loop(0, M, mb_loss, jnp.float32(0.0))
        return total / M * loss_ct

    # bwd rebuilds the batch cotangent structure (float0 for integer
    # leaves); the structure is captured at fwd trace time — a trace-time
    # constant, never a runtime value
    batch_struct = [None]

    def fwd(stage_params, head_params, x_mbs, batch_mbs, loss_ct):
        batch_struct[0] = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), batch_mbs)
        loss, grads = _interleaved_1f1b(stage_fn, head_fn, num_stages,
                                        stage_params, head_params,
                                        x_mbs, batch_mbs, loss_ct)
        return loss, grads

    def bwd(grads, ct):
        # grads already carry loss_ct; ct is the OUTER cotangent (1.0 when
        # the engine consumes the pre-scaled loss directly)
        gstage, ghead, gx, gmb_f = grads

        def scale_leaf(l):
            if l.dtype == jax.dtypes.float0:
                return l
            return (l * ct).astype(l.dtype)

        scale = lambda g: jax.tree_util.tree_map(scale_leaf, g)
        b_leaves, b_treedef = jax.tree_util.tree_flatten(batch_struct[0])
        it_f = iter(gmb_f)
        gbatch = jax.tree_util.tree_unflatten(b_treedef, [
            scale_leaf(next(it_f)) if jnp.issubdtype(l.dtype, jnp.inexact)
            else np.zeros(l.shape, jax.dtypes.float0) for l in b_leaves])
        return (scale(gstage), scale(ghead), scale(gx), gbatch,
                jnp.zeros((), jnp.float32))  # d/d(loss_scale) is never used

    run.defvjp(fwd, bwd)
    return run


def _interleaved_1f1b(stage_fn, head_fn, num_stages, stage_params,
                      head_params, x_mbs, batch_mbs, loss_ct):
    """The interleaved scan.  Returns
    ``(loss, (gstage, ghead, gx_mbs, gbatch))``.

    Clock bookkeeping (tick t, stage s, microbatch m):
      fwd   of m at stage s:     t = m + s
      exit + head vjp of m:      t = m + P - 1
      bwd   of m at stage s:     t = m + 2(P-1) - s
      dx of m exits stage 0:     t = m + 2(P-1)
    so T = M + 2P - 2 ticks; the residual for (s, m) lives 2(P-1-s) ticks
    and a ring of K = 2P-1 slots never collides.
    """
    M = x_mbs.shape[0]
    Pn = int(num_stages)
    K = 2 * Pn - 1
    T = M + 2 * Pn - 2
    feat_shape = x_mbs.shape[1:]

    # batch partition: floating leaves get real gradients (soft labels,
    # loss masks); integer leaves (token ids) get float0 cotangents
    b_leaves, b_treedef = jax.tree_util.tree_flatten(batch_mbs)
    b_is_float = [jnp.issubdtype(l.dtype, jnp.inexact) for l in b_leaves]

    def fwd_parts(sp_slice, x):
        """(y, *input-dependent-or-not residual consts) for ONE stage."""
        y, pullback = jax.vjp(stage_fn, sp_slice, x)
        _, consts = jax.closure_convert(pullback, y)
        return (y, *consts)

    sp_slice_aval = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape[1:], l.dtype), stage_params)
    x_aval = jax.ShapeDtypeStruct(feat_shape, x_mbs.dtype)
    xdep, out_avals = _x_dependence(fwd_parts, sp_slice_aval, x_aval)
    # output 0 is y itself
    xdep_consts = xdep[1:]
    const_avals = out_avals[1:]
    n_consts = len(const_avals)

    fbuf0 = jnp.zeros((Pn,) + feat_shape, x_mbs.dtype)
    fbuf0 = maybe_constrain(fbuf0, _buf_spec(fbuf0.ndim))

    # params-only residuals: computed once, shared by every tick (these are
    # the weight matrices the matmul transposes read — one copy, not K)
    vparts = jax.vmap(fwd_parts)
    warm = jax.jit(vparts)(stage_params, fbuf0)
    shared_consts = [warm[1 + i] for i in range(n_consts)
                     if not xdep_consts[i]]

    # ring buffers for input-dependent residuals: [K, P, ...]
    rings = [jnp.zeros((K, Pn) + tuple(shape), dtype)
             for (shape, dtype), dep in zip(const_avals, xdep_consts) if dep]
    rings = [maybe_constrain(r, _ring_spec(r.ndim)) for r in rings]

    stage_ids = jnp.arange(Pn)

    def head_vjp(y_exit, mb_leaves, ct):
        mb_float = [l for l, f in zip(mb_leaves, b_is_float) if f]

        def head_of(hp, y, *mbf):
            it_f = iter(mbf)
            leaves = [next(it_f) if f else l
                      for l, f in zip(mb_leaves, b_is_float)]
            return head_fn(hp, y, jax.tree_util.tree_unflatten(
                b_treedef, leaves))
        loss_m, pb = jax.vjp(head_of, head_params, y_exit, *mb_float)
        ghead_m, gy, *gmb_float = pb(ct)
        return loss_m, ghead_m, gy, tuple(gmb_float)

    gstage0 = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, jnp.float32), stage_params)
    ghead0 = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, jnp.float32) if
        jnp.issubdtype(l.dtype, jnp.inexact) else jnp.zeros((), jnp.float32),
        head_params)

    def tick(carry, t):
        fbuf, bshift, rings, gstage, ghead, loss_acc = carry

        # ---- forward sub-tick ---------------------------------------
        inp = jax.lax.dynamic_index_in_dim(
            x_mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        slot0 = jnp.where(t < M, inp, fbuf[0])
        fbuf = jax.lax.dynamic_update_index_in_dim(fbuf, slot0, 0, 0)
        fbuf = maybe_constrain(fbuf, _buf_spec(fbuf.ndim))
        with jax.named_scope("pipe/fwd_subtick"):
            parts = vparts(stage_params, fbuf)
        y = parts[0]
        y = maybe_constrain(y, _buf_spec(y.ndim))
        new_consts = list(parts[1:])
        if len(new_consts) != n_consts or any(
                tuple(c.shape[1:]) != tuple(a[0])
                for c, a in zip(new_consts, const_avals)):
            raise RuntimeError(
                "1f1b residual structure diverged between discovery and "
                "scan traces; use schedule='1f1b-remat'")

        # write input-dependent residuals at ring slot t mod K
        w_idx = jnp.mod(t, K)
        rings = [jax.lax.dynamic_update_index_in_dim(
                    r, c, w_idx, 0)
                 for r, c in zip(rings,
                                 [c for c, d in zip(new_consts, xdep_consts)
                                  if d])]
        rings = [maybe_constrain(r, _ring_spec(r.ndim)) for r in rings]

        # ---- exit + loss head ---------------------------------------
        me = t - (Pn - 1)
        head_valid = (me >= 0) & (me < M)
        mb_leaves = [jax.lax.dynamic_index_in_dim(
            l, jnp.clip(me, 0, M - 1), 0, keepdims=False) for l in b_leaves]
        # seed the backward with the loss scale: fp16 cotangents must be
        # amplified BEFORE they enter the pipe, not after (reference
        # scales the loss pre-backward)
        with jax.named_scope("pipe/loss_head"):
            loss_m, ghead_m, gy, gmb_f = head_vjp(
                y[Pn - 1], mb_leaves, jnp.asarray(loss_ct, jnp.float32))
        gy = jnp.where(head_valid, gy, jnp.zeros_like(gy))
        gmb_f = tuple(jnp.where(head_valid, g, jnp.zeros_like(g))
                      for g in gmb_f)
        loss_acc = loss_acc + jnp.where(head_valid, loss_m, 0.0)
        ghead = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(
                head_valid, g.astype(jnp.float32), 0.0)
            if jnp.issubdtype(g.dtype, jnp.inexact) else a,
            ghead, ghead_m)

        # ---- backward sub-tick --------------------------------------
        bct = bshift.at[Pn - 1].set(gy.astype(bshift.dtype))
        bct = maybe_constrain(bct, _buf_spec(bct.ndim))
        # stage s reads the residual written at tick t - 2(P-1) + 2s
        r_idx = jnp.mod(t - 2 * (Pn - 1) + 2 * stage_ids, K)
        old_xdep = [
            jax.vmap(lambda rs, i: jax.lax.dynamic_index_in_dim(
                rs, i, 0, keepdims=False), in_axes=(1, 0))(r, r_idx)
            for r in rings]
        # reassemble the full const list in discovery order
        consts_now, xi, si = [], 0, 0
        for dep in xdep_consts:
            if dep:
                consts_now.append(old_xdep[xi]); xi += 1
            else:
                consts_now.append(shared_consts[si]); si += 1

        def stage_bwd(sp_slice, x, ct, *consts):
            _, pullback = jax.vjp(stage_fn, sp_slice, x)
            conv, _ = jax.closure_convert(pullback, ct)
            return conv(ct, *consts)
        # NB: conv is a PURE function of its consts — re-deriving it per
        # body trace just rebuilds the same jaxpr; the x passed here only
        # shapes the trace and is never read by conv
        with jax.named_scope("pipe/bwd_subtick"):
            gsp_t, gx_t = jax.vmap(stage_bwd)(stage_params, fbuf, bct,
                                              *consts_now)
        gx_t = maybe_constrain(gx_t, _buf_spec(gx_t.ndim))

        mb_b = t - 2 * (Pn - 1) + stage_ids
        bwd_valid = (mb_b >= 0) & (mb_b < M)

        def acc_gstage(a, g):
            mask = bwd_valid.reshape((Pn,) + (1,) * (g.ndim - 1))
            return a + jnp.where(mask, g.astype(jnp.float32), 0.0)
        gstage = jax.tree_util.tree_map(acc_gstage, gstage, gsp_t)

        gx_exit = jnp.where(bwd_valid[0], gx_t[0], jnp.zeros_like(gx_t[0]))
        bshift = jnp.roll(gx_t, -1, axis=0)
        bshift = maybe_constrain(bshift, _buf_spec(bshift.ndim))

        fbuf = jnp.roll(y, 1, axis=0)
        return ((fbuf, bshift, rings, gstage, ghead, loss_acc),
                (gx_exit, gmb_f))

    bshift0 = jnp.zeros((Pn,) + feat_shape, x_mbs.dtype)
    carry0 = (fbuf0, bshift0, rings, gstage0, ghead0, jnp.float32(0.0))
    (_, _, _, gstage, ghead, loss_acc), (gx_ticks, gmb_ticks) = jax.lax.scan(
        tick, carry0, jnp.arange(T))

    inv_m = 1.0 / M
    # dx of microbatch m exits at tick m + 2(P-1)
    gx_mbs = jax.lax.slice_in_dim(gx_ticks, 2 * (Pn - 1), 2 * (Pn - 1) + M,
                                  axis=0)
    gx_mbs = (gx_mbs * inv_m).astype(x_mbs.dtype)
    # float-batch grads for microbatch m were emitted at tick m + P - 1
    gmb_f = [(jax.lax.slice_in_dim(g, Pn - 1, Pn - 1 + M, axis=0)
              * inv_m).astype(d)
             for g, d in zip(gmb_ticks,
                             [l.dtype for l, f in zip(b_leaves, b_is_float)
                              if f])]
    gstage = jax.tree_util.tree_map(
        lambda g, p: (g * inv_m).astype(p.dtype), gstage, stage_params)
    ghead = jax.tree_util.tree_map(
        lambda g, p: (g * inv_m).astype(p.dtype)
        if jnp.issubdtype(p.dtype, jnp.inexact) else
        np.zeros(p.shape, jax.dtypes.float0),
        ghead, head_params)
    return loss_acc / M * loss_ct, (gstage, ghead, gx_mbs, tuple(gmb_f))


# ----------------------------------------------------------------------
# Interleaved virtual stages (Megatron-style; the reference's interleaved
# TrainSchedule assigns each device V non-contiguous layer chunks —
# device s hosts global chunks s, s+P, ..., s+(V-1)P — cutting the
# pipeline bubble from (P-1)/(M+P-1) to roughly (P-1)/(V·M) because a
# microbatch re-enters the pipe V times with 1/V the work per visit)
# ----------------------------------------------------------------------

def stack_interleaved_params(body_params: Any, num_stages: int,
                             num_virtual: int) -> Any:
    """[L, ...] → [P, V, L/(V·P), ...]: leaf[s, v] holds global layer
    chunk ``v·P + s`` (stage dim leads so the pp sharding is unchanged)."""
    P_, V = num_stages, num_virtual

    def reshape(leaf):
        L = leaf.shape[0]
        assert L % (P_ * V) == 0, \
            f"n_layers {L} not divisible by stages*virtual {P_}x{V}"
        k = L // (P_ * V)
        # [V, P, k, ...] in (chunk, stage) order, then stage-major
        return leaf.reshape((V, P_, k) + leaf.shape[1:]).swapaxes(0, 1)
    return jax.tree_util.tree_map(reshape, body_params)


def pipeline_interleaved(stage_fn: Callable,
                         stage_params: Any,
                         x_mbs: jax.Array,
                         num_stages: int,
                         num_virtual: int) -> jax.Array:
    """Forward pipeline with V virtual stages per device.

    Clock: microbatches advance in groups of P injection ticks; the
    circular ``roll`` delivers both stage-to-stage sends AND the
    chunk-(c)→chunk-(c+1) wraparound (slot P-1 → slot 0).  Slot 0 takes a
    NEW microbatch only during injection groups (G % V == 0); otherwise it
    keeps the wrapped activation.  The chunk a slot is executing is a pure
    function of the clock: v(s, t) = ((t - s) // P) mod V.

    Differentiable via scan autodiff (total residual volume ≈ GPipe's:
    V× the ticks at 1/V the per-tick size); combine with per-layer remat
    for the memory cap.
    """
    M = x_mbs.shape[0]
    Pn, V = int(num_stages), int(num_virtual)
    if V == 1:
        return pipeline_spmd(stage_fn, stage_params, x_mbs, Pn,
                             schedule="gpipe")
    groups_inject = -(-M // Pn)            # ceil(M/P) injection groups
    # device 0's group stream: V groups per injection group; the last
    # microbatch's final chunk then drains P-1 ticks
    T = (groups_inject * V) * Pn + (Pn - 1)
    vstage = jax.vmap(stage_fn)
    feat_shape = x_mbs.shape[1:]
    buf = jnp.zeros((Pn,) + feat_shape, x_mbs.dtype)
    buf = maybe_constrain(buf, _buf_spec(buf.ndim))
    stage_ids = jnp.arange(Pn)

    def params_at(t):
        # per-stage virtual-chunk selection: leaf [P, V, k, ...] → [P, k, ...]
        v = jnp.mod(jnp.maximum(t - stage_ids, 0) // Pn, V)
        return jax.tree_util.tree_map(
            lambda leaf: jax.vmap(
                lambda ls, vi: jax.lax.dynamic_index_in_dim(
                    ls, vi, 0, keepdims=False))(leaf, v),
            stage_params)

    def tick(buf, t):
        G, r = t // Pn, jnp.mod(t, Pn)
        mb_new = (G // V) * Pn + r
        inject = (jnp.mod(G, V) == 0) & (mb_new < M)
        inp = jax.lax.dynamic_index_in_dim(
            x_mbs, jnp.clip(mb_new, 0, M - 1), 0, keepdims=False)
        slot0 = jnp.where(inject, inp, buf[0])   # else: chunk wraparound
        buf = jax.lax.dynamic_update_index_in_dim(buf, slot0, 0, 0)
        buf = maybe_constrain(buf, _buf_spec(buf.ndim))
        y = vstage(params_at(t), buf)
        y = maybe_constrain(y, _buf_spec(y.ndim))
        return jnp.roll(y, 1, axis=0), y[Pn - 1]

    _, ys = jax.lax.scan(tick, buf, jnp.arange(T))
    # mb m's final (chunk V-1) output exits device P-1 at
    # t = ((m // P)·V + V - 1)·P + (m % P) + (P - 1)
    exit_t = jnp.asarray(
        [((m // Pn) * V + V - 1) * Pn + (m % Pn) + (Pn - 1)
         for m in range(M)])
    out = jnp.take(ys, exit_t, axis=0)
    entries = [None, tuple(BATCH_AXES)] + [None] * (out.ndim - 2)
    return maybe_constrain(out, P(*entries))


def stack_stage_params(body_params: Any, num_stages: int) -> Any:
    """Reshape stacked per-layer params ``[L, ...]`` into per-stage chunks
    ``[P, L/P, ...]`` (contiguous layer ranges per stage, like the
    reference's ``PipelineModule`` uniform partitioning)."""
    def reshape(leaf):
        L = leaf.shape[0]
        assert L % num_stages == 0, \
            f"n_layers {L} not divisible by num_stages {num_stages}"
        return leaf.reshape((num_stages, L // num_stages) + leaf.shape[1:])
    return jax.tree_util.tree_map(reshape, body_params)


def unstack_stage_params(stage_params: Any) -> Any:
    """Inverse of :func:`stack_stage_params`: ``[P, L/P, ...]`` → ``[L, ...]``."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((-1,) + leaf.shape[2:]), stage_params)
