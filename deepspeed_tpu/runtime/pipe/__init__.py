"""Pipeline parallelism (parity: reference ``deepspeed/runtime/pipe/``).

Exports mirror ``deepspeed.pipe``: ``PipelineModule``, ``LayerSpec``,
``TiedLayerSpec`` — plus the TPU-native executor/engine pieces.
"""

from deepspeed_tpu.runtime.pipe.module import (EmbeddingPipe, LayerSpec,
                                               LMHeadPipe, PipelineModule,
                                               TiedLayerSpec,
                                               TransformerBlockPipe,
                                               lm_loss_fn, partition_balanced,
                                               partition_uniform,
                                               transformer_pipeline)
from deepspeed_tpu.runtime.pipe.pipeline import (pipeline_spmd,
                                                 stack_stage_params,
                                                 unstack_stage_params)
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
from deepspeed_tpu.runtime.pipe import schedule

__all__ = [
    "PipelineModule", "LayerSpec", "TiedLayerSpec", "PipelineEngine",
    "EmbeddingPipe", "TransformerBlockPipe", "LMHeadPipe", "lm_loss_fn",
    "partition_balanced", "partition_uniform", "pipeline_spmd",
    "stack_stage_params", "unstack_stage_params", "transformer_pipeline",
    "schedule",
]
