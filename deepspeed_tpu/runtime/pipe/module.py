"""PipelineModule — layer-spec model assembly for pipeline parallelism.

Parity: reference ``runtime/pipe/module.py`` (``LayerSpec``, ``TiedLayerSpec``,
``PipelineModule:88`` with ``partition_method`` uniform/parameters, tied
layers) and the partitioning helpers in ``runtime/utils.py``
(``partition_uniform``/``partition_balanced``).

TPU-first redesign: the reference assigns each stage's layers to a different
*process* and moves activations with p2p NCCL.  Here all stages live in one
SPMD program — stage assignment is a **sharding**: the homogeneous run of
layers (the transformer body) is stacked to ``[L, ...]`` leaves and the
leading dim is sharded over the ``pp`` mesh axis, ``L/P`` layers per stage.
Layers before/after the homogeneous body (embedding, final norm + head) run
unpipelined (their compute is replicated over ``pp``, sharded over the data
axes — they are a tiny fraction of FLOPs).

Tied layers (reference ``TiedLayerSpec``, e.g. embedding/LM-head weight
tying): tied params live once in ``params["tied"][key]`` and every consumer
reads them; gradient summation across uses is automatic under autodiff —
replacing the reference's ``ReduceTiedGrads`` all-reduce.
"""

import math
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.transformer import CausalTransformerLM, TransformerConfig
from deepspeed_tpu.parallel.topology import PP_AXIS, TP_AXIS
from deepspeed_tpu.runtime.pipe.pipeline import (pipeline_interleaved,
                                                 pipeline_spmd,
                                                 pipeline_train_1f1b,
                                                 stack_interleaved_params,
                                                 stack_stage_params)
from deepspeed_tpu.utils.logging import logger


# ----------------------------------------------------------------------
# Partitioning helpers (parity: reference runtime/utils.py)
# ----------------------------------------------------------------------
def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries of ``num_parts`` near-equal chunks of ``num_items``."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    rem = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < rem else 0)
    return parts


def partition_balanced(weights: List[float], num_parts: int) -> List[int]:
    """Boundaries minimising the heaviest part (reference
    ``ds_utils.partition_balanced`` — binary search over the bottleneck)."""
    n = len(weights)
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + w)

    def parts_for(bottleneck: float) -> Optional[List[int]]:
        parts = [0]
        for _ in range(num_parts):
            start = parts[-1]
            # furthest end with sum <= bottleneck
            lo, hi = start, n
            while lo < hi:
                mid = (lo + hi + 1) // 2
                if prefix[mid] - prefix[start] <= bottleneck:
                    lo = mid
                else:
                    hi = mid - 1
            if lo == start and start < n:
                return None  # single item exceeds bottleneck
            parts.append(lo)
            if lo == n:
                break
        if parts[-1] != n:
            return None
        while len(parts) < num_parts + 1:
            parts.append(n)
        return parts

    lo = max(weights) if weights else 0.0
    hi = sum(weights)
    best = parts_for(hi)
    for _ in range(64):
        mid = (lo + hi) / 2
        cand = parts_for(mid)
        if cand is not None:
            best, hi = cand, mid
        else:
            lo = mid
    return best


# ----------------------------------------------------------------------
# Layer specs (parity: reference pipe/module.py LayerSpec/TiedLayerSpec)
# ----------------------------------------------------------------------
class LayerSpec:
    """Lazy layer constructor so a module list can be declared without
    building params (reference builds only the local stage's layers; we
    build all — they are shardings, not copies)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, object):
            raise RuntimeError("LayerSpec expects a class")

    def build(self, log=False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({self.typename.__name__})"


class TiedLayerSpec(LayerSpec):
    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="tok_embed", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


# ----------------------------------------------------------------------
# Pipeline layer classes for the transformer family
# ----------------------------------------------------------------------
class EmbeddingPipe:
    """Token (+ learned position) embedding.  Input: microbatch dict with
    ``input_ids`` (or a raw ids array); output: hidden states."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    def init(self, rng, dtype=jnp.float32):
        c = self.config
        params = {}
        if not c.tie_embeddings:
            # untied: the embedding matrix is a local param; tied models get
            # it from tied_init via the "embed" tied group instead
            params.update(self.tied_init(rng, dtype))
        if not c.use_rope:
            params["pos_embed"] = (
                jax.random.normal(jax.random.fold_in(rng, 1),
                                  (c.max_seq_len, c.hidden_size), jnp.float32)
                / math.sqrt(c.hidden_size)).astype(dtype)
        return params

    def tied_init(self, rng, dtype=jnp.float32):
        c = self.config
        return {"tok_embed": (
            jax.random.normal(rng, (c.vocab_size, c.hidden_size), jnp.float32)
            / math.sqrt(c.hidden_size)).astype(dtype)}

    def __call__(self, params, batch, tied=None):
        ids = batch["input_ids"] if isinstance(batch, dict) else batch
        tok = tied["tok_embed"] if tied is not None else params["tok_embed"]
        x = tok[ids]
        if self.config.embed_scale is not None:   # Gemma: input side only
            x = x * jnp.asarray(self.config.embed_scale, x.dtype)
        if not self.config.use_rope:
            S = ids.shape[-1]
            x = x + params["pos_embed"][:S][None].astype(x.dtype)
        return x


class TransformerBlockPipe:
    """One transformer block — the homogeneous pipelined body unit.
    Reuses the flagship model's block math (attention + MLP).

    MoE bodies (pp × ep composition) need ``moe_layer_freq == 1`` so the
    body stays homogeneous (every block carries an expert bank); the
    block then reports ``has_aux`` and returns ``(x, gate_aux)``."""

    def __init__(self, config: TransformerConfig):
        if config.is_moe and config.moe_layer_freq != 1:
            raise ValueError(
                "pipelined MoE needs moe_layer_freq=1 (a homogeneous "
                "body); mixed dense/MoE stacks cannot stack into one scan")
        self.config = config
        self.has_aux = config.is_moe
        self._model = CausalTransformerLM(config)

    def init(self, rng, dtype=jnp.float32):
        c = self.config
        d, f = c.hidden_size, c.ffn_dim
        dh, H, Hkv = c.head_dim, c.n_heads, c.kv_heads
        ks = jax.random.split(rng, 8)

        def dense(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32) /
                    math.sqrt(fan_in)).astype(dtype)

        layer = {
            "attn_norm": jnp.ones((d,), dtype),
            "wq": dense(ks[0], (d, H * dh), d),
            "wk": dense(ks[1], (d, Hkv * dh), d),
            "wv": dense(ks[2], (d, Hkv * dh), d),
            "wo": dense(ks[3], (H * dh, d), H * dh),
            "mlp_norm": jnp.ones((d,), dtype),
        }
        if c.is_moe:
            E = c.moe_num_experts
            layer["moe"] = {
                "wg": dense(ks[4], (d, E), d).astype(jnp.float32),
                "w_up": dense(ks[5], (E, d, f), d),
                "w_down": dense(ks[6], (E, f, d), f),
            }
            return layer
        layer["w_up"] = dense(ks[4], (d, f), d)
        layer["w_down"] = dense(ks[5], (f, d), f)
        if c.gated:
            layer["w_gate"] = dense(ks[6], (d, f), d)
        return layer

    def __call__(self, params, x, tied=None):
        B, S, _ = x.shape
        positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
        x, aux = self._model._layer(x, params, positions, train=True)
        return (x, aux) if self.has_aux else x

    def tp_rules(self):
        """Single-layer Megatron split (PipelineModule prepends the pp dim)."""
        if self.config.is_moe:
            from deepspeed_tpu.parallel.topology import EP_AXIS
            return [
                (r"moe.*w_up", P(EP_AXIS, None, TP_AXIS)),
                (r"moe.*w_down", P(EP_AXIS, TP_AXIS, None)),
                (r"moe.*wg", P()),
                (r"wq|wk|wv", P(None, TP_AXIS)),
                (r"wo", P(TP_AXIS, None)),
            ]
        return [
            (r"wq|wk|wv|w_up|w_gate", P(None, TP_AXIS)),
            (r"wo|w_down", P(TP_AXIS, None)),
        ]


class LMHeadPipe:
    """Final norm + LM head; emits fp32 logits.  Tied variant reads the
    embedding matrix from the tied group."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    def init(self, rng, dtype=jnp.float32):
        c = self.config
        params = {"final_norm": jnp.ones((c.hidden_size,), dtype)}
        if not c.tie_embeddings:
            params["lm_head"] = (
                jax.random.normal(rng, (c.hidden_size, c.vocab_size),
                                  jnp.float32)
                / math.sqrt(c.hidden_size)).astype(dtype)
        return params

    def __call__(self, params, x, tied=None):
        from deepspeed_tpu.models.transformer import _norm
        c = self.config
        x = _norm(x, params["final_norm"], c.norm_eps, c.use_rmsnorm)
        head = (tied["tok_embed"].T if c.tie_embeddings
                else params["lm_head"])
        return (x @ head.astype(x.dtype)).astype(jnp.float32)


def lm_loss_fn(logits, batch):
    """Default next-token cross-entropy — the same function the dense model
    uses (``models/transformer.py next_token_xent``), so pipeline-vs-dense
    trajectories cannot diverge."""
    from deepspeed_tpu.models.transformer import next_token_xent
    return next_token_xent(logits, batch)


# ----------------------------------------------------------------------
# PipelineModule
# ----------------------------------------------------------------------
class PipelineModule:
    """Assembles a layer list into (pre | pipelined body | post).

    Parity: reference ``pipe/module.py:88`` — same spec-list construction,
    ``partition_method`` and tied-layer surface.  ``num_stages`` defaults to
    the ``pp`` degree of the active mesh.

    The params pytree::

        {"pre":  [per-layer params ...],
         "body": stacked [L, ...] leaves (leading dim sharded over pp),
         "post": [per-layer params ...],
         "tied": {key: params}}

    ``loss(params, microbatched_batch, rng)`` runs the full pipelined
    forward + loss; the microbatch dim is the pipeline clock.
    """

    def __init__(self, layers, num_stages: Optional[int] = None,
                 topology=None, loss_fn: Optional[Callable] = None,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 seed_layers: bool = False,
                 schedule: str = "1f1b",
                 num_virtual_stages: int = 1):
        if topology is not None and num_stages is None:
            num_stages = topology.get_dim("pipe") or topology.get_dim("pp")
        # num_stages=None resolves lazily from the active mesh's pp axis.
        # Resolving eagerly here would install a default (pp=1) mesh when the
        # module is built before deepspeed_tpu.initialize — silently
        # disabling pipelining.
        self._num_stages = int(num_stages) if num_stages is not None else None
        self.loss_fn = loss_fn or lm_loss_fn
        if partition_method not in ("uniform", "parameters"):
            raise ValueError(
                f"unsupported partition_method '{partition_method}' "
                "(uniform|parameters)")
        # uniform == parameters here: the pipelined body is homogeneous, so
        # equal layer counts ARE equal parameter counts (partition_balanced
        # is exported for grid-planning parity)
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        # "1f1b" = TRUE interleaved fwd/bwd (reference TrainSchedule): O(P)
        # in-flight residuals, no recompute.  "1f1b-remat" = GPipe order
        # with chunked remat (O(P) residuals bought with one fwd replay).
        # "gpipe" stores all M.  "interleaved" = Megatron virtual stages
        # (num_virtual_stages chunks per device, ~V x smaller bubble;
        # autodiff backward).
        self.schedule = schedule
        self.num_virtual_stages = int(num_virtual_stages)
        if schedule == "interleaved" and self.num_virtual_stages < 2:
            raise ValueError(
                "schedule='interleaved' needs num_virtual_stages >= 2")
        if schedule != "interleaved" and self.num_virtual_stages > 1:
            raise ValueError(
                "num_virtual_stages > 1 needs schedule='interleaved'")

        self._specs = list(layers)
        self._layers = [s.build() if isinstance(s, LayerSpec) else s
                        for s in self._specs]
        self._tied_keys = [s.key if isinstance(s, TiedLayerSpec) else None
                           for s in self._specs]
        self._split = None      # (body_start, body_end) — set in init()

    @property
    def num_stages(self) -> int:
        if self._num_stages is None:
            from deepspeed_tpu.parallel import groups
            if not groups.mesh_is_initialized():
                raise ValueError(
                    "PipelineModule: num_stages was not given and no device "
                    "mesh is initialized yet — pass num_stages=/topology=, or "
                    "call deepspeed_tpu.initialize (or "
                    "groups.initialize_mesh) before using the module")
            self._num_stages = max(groups.get_pipe_parallel_world_size(), 1)
        return self._num_stages

    # -- structure ------------------------------------------------------
    def _layer_signature(self, i, rng):
        shapes = jax.eval_shape(self._layers[i].init, rng)
        return jax.tree_util.tree_map(
            lambda x: (x.shape, str(x.dtype)), shapes)

    def _find_body(self, rng):
        sigs = [str(self._layer_signature(i, rng))
                for i in range(len(self._layers))]
        classes = [type(l) for l in self._layers]
        best = (0, 0)
        i = 0
        while i < len(sigs):
            j = i
            while (j < len(sigs) and sigs[j] == sigs[i]
                   and classes[j] is classes[i]
                   and self._tied_keys[j] is None):
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = max(j, i + 1)
        start, end = best
        n = end - start
        assert n >= 1, "no homogeneous run of layers to pipeline"
        assert n % self.num_stages == 0, (
            f"pipelined body has {n} layers, not divisible by "
            f"num_stages={self.num_stages}")
        return start, end

    # -- params ---------------------------------------------------------
    def init(self, rng, dtype=jnp.float32) -> Dict[str, Any]:
        self._split = self._find_body(rng)
        start, end = self._split
        if self.schedule == "interleaved":
            n = end - start
            pv = self.num_stages * self.num_virtual_stages
            if n % pv:
                raise ValueError(
                    f"interleaved schedule: {n} body layers not divisible "
                    f"by num_stages*num_virtual_stages = {pv}")
        keys = jax.random.split(rng, len(self._layers) + 1)
        tied: Dict[str, Any] = {}
        pre, post = [], []
        body_layers = []
        for i, layer in enumerate(self._layers):
            p = layer.init(keys[i], dtype)
            key = self._tied_keys[i]
            if key is not None and key not in tied and \
                    hasattr(layer, "tied_init"):
                tied[key] = layer.tied_init(keys[i], dtype)
            if i < start:
                pre.append(p)
            elif i < end:
                body_layers.append(p)
            else:
                post.append(p)
        body = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *body_layers)
        return {"pre": pre, "body": body, "post": post, "tied": tied}

    @property
    def body_range(self):
        return self._split

    # -- sharding rules -------------------------------------------------
    def tp_rules(self):
        """Sharding rules for the pipeline params: body leaves lead with the
        pp axis; per-layer TP rules (from the body layer class) get the pp
        dim prepended.  Pre/post/tied params follow the data-parallel plan
        (fsdp added by the ZeRO plan)."""
        start, _ = self._split if self._split else (0, 0)
        body_layer = self._layers[start] if self._layers else None
        rules = []
        if body_layer is not None and hasattr(body_layer, "tp_rules"):
            for pat, spec in body_layer.tp_rules():
                rules.append((r"body.*(" + pat + r")",
                              P(*([PP_AXIS] + list(spec)))))
        rules.append((r"body", P(PP_AXIS)))
        return rules

    # -- execution ------------------------------------------------------
    def _call_layer(self, i, params, x, tied):
        key = self._tied_keys[i]
        t = tied.get(key) if key is not None else None
        return self._layers[i](params, x, tied=t)

    @property
    def _body_has_aux(self) -> bool:
        start = self._split[0] if self._split else 0
        return bool(getattr(self._layers[start], "has_aux", False)) \
            if self._layers else False

    def _stage_fn(self):
        start, end = self._split
        layer = self._layers[start]
        remat = self.activation_checkpoint_interval > 0
        has_aux = self._body_has_aux

        if has_aux:
            def apply_one(carry, lp):
                x, aux = carry
                y, a = layer(lp, x)
                return (y, aux + a), None
        else:
            def apply_one(x, lp):
                return layer(lp, x), None
        if remat:
            apply_one = jax.checkpoint(apply_one)

        def stage_fn(chunk_params, x):
            if has_aux:
                (y, aux), _ = jax.lax.scan(apply_one, (x, jnp.float32(0.0)),
                                           chunk_params)
                return y, aux
            x, _ = jax.lax.scan(apply_one, x, chunk_params)
            return x
        return stage_fn

    def forward_mbs(self, params, batch_mbs):
        """Pipelined forward over microbatched input (leading dim M).
        Returns the post-layer outputs ``[M, ...]``."""
        assert self._split is not None, "call init() first"
        start, end = self._split
        tied = params["tied"]

        def pre_fn(x):
            for j in range(start):
                x = self._call_layer(j, params["pre"][j], x, tied)
            return x

        x = jax.vmap(pre_fn)(batch_mbs)
        has_aux = self._body_has_aux
        if self.schedule == "interleaved" and not has_aux:
            x = pipeline_interleaved(
                self._stage_fn(),
                stack_interleaved_params(params["body"], self.num_stages,
                                         self.num_virtual_stages),
                x, self.num_stages, self.num_virtual_stages)
        else:
            stage_params = stack_stage_params(params["body"],
                                              self.num_stages)
            sched = ("1f1b-remat" if self.schedule == "interleaved"
                     else self.schedule)
            x = pipeline_spmd(self._stage_fn(), stage_params, x,
                              self.num_stages, schedule=sched,
                              with_aux=has_aux)
            if has_aux:
                x, _ = x          # aux is a training-only term

        def post_fn(h):
            for j in range(end, len(self._layers)):
                h = self._call_layer(j, params["post"][j - end], h, tied)
            return h
        # lax.map bounds logits memory to one microbatch at a time
        return jax.lax.map(post_fn, x)

    def loss(self, params, batch, rng=None, loss_scale=None):
        """Pipelined loss.  ``batch`` MUST carry a leading microbatch dim
        (the engine stacks GAS microbatches; M is the pipeline clock).

        ``loss_scale``: when given, the returned loss is PRE-scaled and the
        1f1b schedule seeds its interleaved backward with the scale, so
        fp16 cotangents ride the pipe amplified (reference semantics:
        scale before backward, not after)."""
        assert self._split is not None, "call init() first"
        start, end = self._split
        tied = params["tied"]

        inputs = batch

        # run pre layers (the first consumes the microbatch itself)
        def pre_fn(mb):
            x = mb
            for j in range(start):
                x = self._call_layer(j, params["pre"][j], x, tied)
            return x
        x = jax.vmap(pre_fn)(inputs)

        # _stage_fn already checkpoints per layer when activation
        # checkpointing is on — no second stage-level remat wrap
        has_aux = self._body_has_aux
        schedule = self.schedule
        if has_aux and schedule == "interleaved":
            # MoE bodies emit the gate aux loss per (stage, microbatch);
            # the interleaved clock does not plumb it yet
            raise ValueError(
                "MoE pipeline bodies need schedule='1f1b-remat', '1f1b' "
                "or 'gpipe' (the gate aux loss is not threaded through "
                "'interleaved' yet)")
        if has_aux and schedule == "1f1b":
            # the hand-threaded 1F1B VJP doesn't carry the aux either;
            # the chunked-remat schedule keeps the O(P) residual cap and
            # lets autodiff own the aux gradients
            schedule = "1f1b-remat"
        if schedule == "interleaved":
            x = pipeline_interleaved(
                self._stage_fn(),
                stack_interleaved_params(params["body"], self.num_stages,
                                         self.num_virtual_stages),
                x, self.num_stages, self.num_virtual_stages)
            return self._post_loss_tail(params, x, inputs, tied, end,
                                        loss_scale)

        stage_params = stack_stage_params(params["body"], self.num_stages)

        if schedule == "1f1b" and self.num_stages > 1:
            # TRUE 1F1B: the loss head runs inside the interleaved scan so
            # each microbatch's backward starts the tick its forward exits
            # (reference TrainSchedule, runtime/pipe/schedule.py:184) —
            # O(P) live residuals, no recompute
            post_params, n_layers, end_ = params["post"], len(self._layers), end

            def head_fn(head_params, h, mb):
                post, tied_hp = head_params
                for j in range(end_, n_layers):
                    h = self._call_layer(j, post[j - end_], h, tied_hp)
                return self.loss_fn(h, mb)

            return pipeline_train_1f1b(
                self._stage_fn(), head_fn, self.num_stages,
                stage_params, (post_params, tied), x, inputs,
                loss_ct=loss_scale)

        out = pipeline_spmd(self._stage_fn(), stage_params, x,
                            self.num_stages, schedule=schedule,
                            with_aux=has_aux)
        if has_aux:
            x, aux_sum = out
            coef = getattr(self._layers[start].config, "moe_aux_loss_coef",
                           0.0)
            # microbatched semantics (same as the dense GAS scan): mean over
            # microbatches of (ce_m + coef * aux_m)
            extra = coef * aux_sum / x.shape[0]
            return self._post_loss_tail(params, x, inputs, tied, end,
                                        loss_scale, extra=extra)
        return self._post_loss_tail(params, out, inputs, tied, end,
                                    loss_scale)

    def _post_loss_tail(self, params, x, inputs, tied, end, loss_scale,
                        extra=None):
        """Shared post-layers + loss over pipelined outputs (one
        definition for every autodiff schedule).  ``extra``: additive loss
        terms computed inside the pipeline (MoE gate aux)."""
        def mb_loss(args):
            h, mb = args
            for j in range(end, len(self._layers)):
                h = self._call_layer(j, params["post"][j - end], h, tied)
            return self.loss_fn(h, mb)
        mean = jnp.mean(jax.lax.map(mb_loss, (x, inputs)))
        if extra is not None:
            mean = mean + extra
        return mean if loss_scale is None else mean * loss_scale

    def partition_layers(self):
        """Report layer→stage assignment (reference logs the same at
        construction).  Pre/post layers are 'replicated'."""
        start, end = self._split if self._split else self._find_body(
            jax.random.key(0))
        out = []
        if self.schedule == "interleaved":
            # round-robin chunks: global chunk c lives on stage c mod P
            k = (end - start) // (self.num_stages * self.num_virtual_stages)
            for i in range(len(self._layers)):
                if i < start or i >= end:
                    out.append((i, type(self._layers[i]).__name__,
                                "replicated"))
                else:
                    chunk = (i - start) // k
                    out.append((i, type(self._layers[i]).__name__,
                                f"stage{chunk % self.num_stages}"
                                f"v{chunk // self.num_stages}"))
            return out
        per = (end - start) // self.num_stages
        for i in range(len(self._layers)):
            if i < start or i >= end:
                out.append((i, type(self._layers[i]).__name__, "replicated"))
            else:
                out.append((i, type(self._layers[i]).__name__,
                            f"stage{(i - start) // per}"))
        return out


def transformer_pipeline(config: TransformerConfig,
                         num_stages: Optional[int] = None,
                         loss_fn: Optional[Callable] = None,
                         activation_checkpoint_interval: int = 0,
                         schedule: str = "1f1b",
                         num_virtual_stages: int = 1) -> PipelineModule:
    """GPT2ModelPipe-style convenience: embedding → N blocks → norm+head
    (parity: Megatron-DeepSpeed ``GPT2ModelPipe`` construction)."""
    specs: List[LayerSpec] = []
    if config.tie_embeddings:
        specs.append(TiedLayerSpec("embed", EmbeddingPipe, config))
    else:
        specs.append(LayerSpec(EmbeddingPipe, config))
    specs += [LayerSpec(TransformerBlockPipe, config)
              for _ in range(config.n_layers)]
    if config.tie_embeddings:
        specs.append(TiedLayerSpec("embed", LMHeadPipe, config))
    else:
        specs.append(LayerSpec(LMHeadPipe, config))
    return PipelineModule(
        specs, num_stages=num_stages, loss_fn=loss_fn,
        activation_checkpoint_interval=activation_checkpoint_interval,
        schedule=schedule, num_virtual_stages=num_virtual_stages)
