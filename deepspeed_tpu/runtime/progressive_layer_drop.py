"""Progressive layer drop (PLD).

Parity: reference ``runtime/progressive_layer_drop.py`` (``ProgressiveLayerDrop``:
theta schedule theta(t) = (1 - theta_min) * gamma-decay + theta_min; engine
``_configure_progressive_layer_drop:1646`` updates theta each step and models
scale layer keep-probability by depth: p_l = 1 - l/L * (1 - theta)).
"""

import math

from deepspeed_tpu.utils.logging import log_dist


class ProgressiveLayerDrop:

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})",
                 ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        def _prob(x, gamma, p):
            return (1.0 - p) * math.exp(-gamma * x) + p
        self.current_theta = _prob(global_step, self.gamma, self.theta)
        return self.current_theta

    def layer_keep_prob(self, layer_idx: int, n_layers: int) -> float:
        """Depth-scaled keep probability (deeper layers drop more)."""
        return 1.0 - (layer_idx + 1) / n_layers * (1.0 - self.current_theta)
