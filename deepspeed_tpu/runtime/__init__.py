"""Runtime package (reference ``deepspeed/runtime/__init__.py`` defines the
optimizer marker base classes used for isinstance checks).  The host
offload optimizer subclasses ZeROOptimizer, so reference-style
``isinstance(opt, ZeROOptimizer)`` gates work for the one optimizer
OBJECT this engine has; the optax transforms of the dense path are
functions, not classes, so the markers are inert there by design."""


class DeepSpeedOptimizer:
    pass


class ZeROOptimizer(DeepSpeedOptimizer):
    pass
