"""Runtime package (reference ``deepspeed/runtime/__init__.py`` defines the
optimizer marker base classes used for isinstance checks)."""


class DeepSpeedOptimizer:
    pass


class ZeROOptimizer(DeepSpeedOptimizer):
    pass
