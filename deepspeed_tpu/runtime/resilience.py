"""Fault-tolerance layer: durable checkpoints, retries, preemption handling,
divergence detection, and a deterministic fault-injection harness.

Parity rationale: the reference DeepSpeed survives real fleets because its
checkpoint/commit path (Nebula async commit, per-rank shard validation) and
overflow machinery tolerate partial failures.  At the scale ZeRO/ZeRO-Infinity
target (Rajbhandari et al., 1910.02054, 2104.07857) preemptions and I/O faults
are the common case, not the exception — this module gives the TPU port the
same survival properties on top of the orbax engine:

* **Durable checkpoints** — :class:`CheckpointTransaction` implements
  write-to-tmp → fsync → commit-marker → atomic-rename.  A tag directory is
  *committed* iff its ``.ds_commit`` marker matches the digest of its
  ``ds_manifest.json`` (tree structure, shapes/dtypes, file list + sizes,
  optional per-leaf checksums).  Everything else — torn writes, truncated
  dirs, crashed-mid-save tmp dirs — is detectably invalid and skipped by
  the load-time scan.
* **Retry with exponential backoff + jitter** — :func:`retry_io` wraps
  checkpoint and host-filesystem I/O; every retry emits a structured
  ``fault/retry`` telemetry event.
* **Preemption handling** — :class:`PreemptionHandler` converts SIGTERM /
  SIGINT into a flag the engine polls at step boundaries, so an eviction
  notice becomes an emergency checkpoint plus a clean thread drain instead
  of a corrupt half-written state dir.
* **Divergence sentinel** — :class:`DivergenceSentinel` watches the fp32
  loss for non-finite values and the fp16 automaton for K consecutive
  overflow-skips, without adding a per-step device sync (device scalars are
  batched through one ``device_get`` per ``interval`` steps).
* **Deterministic fault injection** — :class:`FaultInjector` fails/delays
  checkpoint writes, raises in the dataloader worker, and poisons gradients
  at a chosen step, driven by config or tests, so every recovery path above
  is exercised in tier-1 CPU tests (no flaky sleeps, no real signals
  required).

All telemetry from this module rides the frozen ``fault`` event kind
(``scripts/check_telemetry_schema.py``).
"""

import hashlib
import json
import os
import random
import shutil
import signal
import threading
import time
import zlib

import numpy as np

from deepspeed_tpu.utils.logging import logger

# on-disk protocol names (docs/resilience.md documents the layout)
COMMIT_MARKER = ".ds_commit"
MANIFEST_NAME = "ds_manifest.json"
TMP_SUFFIX = ".tmp"
MANIFEST_VERSION = 1

# tag-dir validation statuses
COMMITTED = "committed"      # marker + manifest present and consistent
NO_MARKER = "no_marker"      # manifest but no (or torn) commit marker
BAD_MANIFEST = "bad_manifest"  # unparseable / digest-mismatched manifest
PARTIAL = "partial"          # manifest-listed payload missing or truncated
LEGACY = "legacy"            # pre-resilience checkpoint (no protocol files)
MISSING = "missing"          # no such tag directory


class CheckpointCorruptError(RuntimeError):
    """A checkpoint tag failed validation (marker / manifest / payload)."""


class TrainingPreempted(RuntimeError):
    """Raised at a step boundary after a preemption signal was handled
    (emergency checkpoint written, worker threads drained)."""


class DivergenceError(RuntimeError):
    """Raised when the divergence sentinel trips and the configured action
    is ``halt`` (or auto-restore is impossible)."""


# ----------------------------------------------------------------------
# retry with exponential backoff + jitter
# ----------------------------------------------------------------------
class RetryPolicy:
    """Exponential backoff with deterministic jitter.

    The jitter stream is seeded so a faulted test run produces the same
    delays every time — determinism is a feature of the whole harness, not
    just the injector.  ``sleep_fn`` is injectable for tests.
    """

    def __init__(self, max_retries=3, backoff_secs=0.5, backoff_max_secs=30.0,
                 jitter=0.25, sleep_fn=time.sleep, seed=0xD5):
        self.max_retries = max(0, int(max_retries))
        self.backoff_secs = float(backoff_secs)
        self.backoff_max_secs = float(backoff_max_secs)
        self.jitter = float(jitter)
        self.sleep_fn = sleep_fn
        self._rng = random.Random(seed)

    @classmethod
    def from_config(cls, rc, sleep_fn=time.sleep):
        return cls(max_retries=rc.max_retries,
                   backoff_secs=rc.retry_backoff_secs,
                   backoff_max_secs=rc.retry_backoff_max_secs,
                   jitter=rc.retry_jitter, sleep_fn=sleep_fn)

    def delay(self, attempt):
        """Backoff for retry ``attempt`` (1-based): ``base * 2^(a-1)``
        capped at ``backoff_max_secs``, stretched by up to ``jitter``."""
        base = min(self.backoff_max_secs,
                   self.backoff_secs * (2.0 ** (attempt - 1)))
        return base * (1.0 + self.jitter * self._rng.random())


def retry_io(fn, policy, telemetry=None, op="io", injector=None, site=None,
             cleanup=None):
    """Run ``fn`` with bounded retries under ``policy``.

    ``injector``/``site`` hook the deterministic fault injector in *front*
    of every attempt (so configured failures are consumed by retries, like
    a flaky filesystem would be).  ``cleanup`` runs between attempts and
    before the final re-raise — checkpoint transactions use it to clear
    their tmp dir.  Every retry emits a ``fault/retry`` event.
    """
    attempt = 0
    while True:
        try:
            if injector is not None and site is not None:
                injector.check(site)
            return fn()
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:
            attempt += 1
            if cleanup is not None:
                try:
                    cleanup()
                except Exception as ce:
                    logger.warning(f"{op}: cleanup after failure raised {ce}")
            if attempt > policy.max_retries:
                logger.error(f"{op}: failed after {policy.max_retries} "
                             f"retries: {exc!r}")
                raise
            delay = policy.delay(attempt)
            logger.warning(f"{op}: attempt {attempt}/{policy.max_retries} "
                           f"failed ({exc!r}); retrying in {delay:.2f}s")
            if telemetry is not None:
                telemetry.fault(
                    "fault/retry",
                    attrs={"op": op, "attempt": attempt,
                           "max_retries": policy.max_retries,
                           "error": repr(exc)[:200],
                           "delay_s": round(delay, 3)})
            if delay > 0:
                policy.sleep_fn(delay)


# ----------------------------------------------------------------------
# deterministic fault injection
# ----------------------------------------------------------------------
_EXC_TABLE = {
    "OSError": OSError,
    "IOError": OSError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "TimeoutError": TimeoutError,
    "CheckpointCorruptError": CheckpointCorruptError,
}

# the sites the runtime consults; check() on anything else is a no-op, so
# configs stay forward-compatible with new sites.  serve_step / serve_sample
# / page_alloc are the serving-side sites (inference/robustness.py): the
# whole-batch decode dispatch, the per-request host sampler, and the KV
# page allocator.  replica_kill / route_dispatch are the fleet-level sites
# (inference/fleet.py): abrupt replica death during a supervision sweep,
# and the routing-table dispatch — consulted BEFORE any routing state
# mutates, so a faulted dispatch never half-registers a request (the
# page_alloc atomicity idiom).  page_migrate / migrate_commit are the
# KV-page migration transaction's two sites (disaggregated fleets): the
# cross-replica page transfer and the all-or-nothing commit — both
# consulted BEFORE any routing-table or allocator mutation becomes
# durable, so a faulted migration retries from a consistent state.
# wire_send / wire_recv / wire_delay / rpc_timeout are the cross-process
# transport's frame-level sites (inference/transport.py): outbound and
# inbound frame faults (drop/duplicate/reorder/tear), injected frame
# latency, and a forced RPC-deadline expiry — consulted by the seeded
# WireFaultInjector, which shares this frozen vocabulary (a tier-1 test
# diffs the two) but keeps frame-action semantics of its own.
FAULT_SITES = ("ckpt_save", "ckpt_load", "fs", "dataloader_next",
               "serve_step", "serve_sample", "page_alloc",
               "replica_kill", "route_dispatch",
               "page_migrate", "migrate_commit",
               "wire_send", "wire_recv", "wire_delay", "rpc_timeout")


class FaultInjector:
    """Deterministic, config- and test-driven fault injection.

    Spec (the ``resilience.fault_injection`` block)::

        {"ckpt_save":       {"fail_times": 2, "exc": "OSError"},
         "dataloader_next": {"fail_at": [3], "msg": "transient read"},
         "fs":              {"delay_secs": 0.01},
         "poison_grads_at": [5]}

    Per-site semantics — each site keeps a 0-based invocation counter:

    * ``fail_times: N`` — the first N calls raise.
    * ``fail_at: [i, ...]`` — calls with those indices raise.
    * ``delay_secs: s`` — every call sleeps first (I/O latency injection).
    * ``exc`` / ``msg`` — exception class name and message to raise.

    ``poison_grads_at`` lists engine steps whose gradients are poisoned
    (NaN-filled float inputs, falling back to params when the batch has no
    float leaves) — the deterministic trigger for the divergence sentinel.
    Counters are lock-protected: the dataloader site is hit from the
    prefetch worker thread.
    """

    def __init__(self, spec=None):
        spec = dict(spec or {})
        self.poison_steps = set(int(s) for s in
                                spec.pop("poison_grads_at", []) or [])
        self._spec = {site: dict(cfg) for site, cfg in spec.items()}
        self._lock = threading.Lock()
        self._counts = {}
        self._poisoned = set()

    @classmethod
    def from_config(cls, fault_injection):
        if not fault_injection:
            return None
        return cls(fault_injection)

    def calls(self, site):
        with self._lock:
            return self._counts.get(site, 0)

    def check(self, site):
        """Consume one invocation of ``site``; sleeps and/or raises per the
        spec.  Unknown sites count but never fire."""
        cfg = self._spec.get(site)
        with self._lock:
            idx = self._counts.get(site, 0)
            self._counts[site] = idx + 1
        if not cfg:
            return
        delay = float(cfg.get("delay_secs", 0) or 0)
        if delay > 0:
            time.sleep(delay)
        fail = False
        if idx < int(cfg.get("fail_times", 0) or 0):
            fail = True
        if idx in set(cfg.get("fail_at", []) or []):
            fail = True
        if fail:
            exc_cls = _EXC_TABLE.get(str(cfg.get("exc", "OSError")), OSError)
            raise exc_cls(cfg.get("msg",
                                  f"injected fault at {site}[{idx}]"))

    def poison_grads(self, step):
        """True exactly once for each step listed in ``poison_grads_at``."""
        step = int(step)
        with self._lock:
            if step in self.poison_steps and step not in self._poisoned:
                self._poisoned.add(step)
                return True
        return False

    def reset(self):
        with self._lock:
            self._counts = {}
            self._poisoned = set()


def poison_tree(tree):
    """NaN-fill every floating leaf of ``tree`` (numpy or jax arrays; jax
    leaves keep their sharding — ``x * nan`` is elementwise).  Returns
    ``(poisoned_tree, n_leaves_poisoned)``."""
    import jax
    import jax.numpy as jnp
    count = [0]

    def f(x):
        dt = getattr(x, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.floating):
            count[0] += 1
            if isinstance(x, np.ndarray):
                return np.full_like(x, np.nan)
            return x * float("nan")
        return x
    out = jax.tree_util.tree_map(f, tree)
    return out, count[0]


# ----------------------------------------------------------------------
# durable checkpoint protocol
# ----------------------------------------------------------------------
def _fsync_path(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_tree(root):
    """fsync every file under ``root`` then every directory bottom-up, so
    the subsequent rename publishes fully-persisted bytes."""
    for dirpath, _, filenames in os.walk(root, topdown=False):
        for fn in filenames:
            try:
                _fsync_path(os.path.join(dirpath, fn))
            except OSError:
                pass
        try:
            _fsync_path(dirpath)
        except OSError:
            pass


def atomic_write_text(path, text, fsync=True):
    """Write ``text`` to ``path`` via tmp-file + atomic rename (the
    ``latest`` pointer must never be observable half-written)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        try:
            _fsync_path(os.path.dirname(os.path.abspath(path)))
        except OSError:
            pass


def _manifest_digest(body):
    """sha256 over the canonical JSON of the manifest body (digest field
    excluded)."""
    data = {k: v for k, v in body.items() if k != "digest"}
    blob = json.dumps(data, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def _leaf_entries(state, checksum=False):
    """Flatten ``state`` into manifest leaf records: keypath, shape, dtype,
    and (on request) crc32 of the host bytes.  Checksums force a device_get
    per leaf — a deliberate cost, gated by ``resilience.checksum``."""
    import jax
    leaves, _ = jax.tree_util.tree_flatten_with_path(state)
    entries = []
    for path, leaf in leaves:
        rec = {"path": jax.tree_util.keystr(path),
               "shape": list(np.shape(leaf)),
               "dtype": str(getattr(leaf, "dtype", type(leaf).__name__))}
        if checksum:
            rec["crc32"] = leaf_crc32(leaf)
        entries.append(rec)
    return entries


def leaf_crc32(leaf):
    import jax
    if isinstance(leaf, jax.Array) and jax.dtypes.issubdtype(
            leaf.dtype, jax.dtypes.prng_key):
        leaf = jax.random.key_data(leaf)  # typed keys have no numpy view
    host = np.asarray(jax.device_get(leaf))
    return zlib.crc32(np.ascontiguousarray(host).tobytes()) & 0xFFFFFFFF


def build_manifest(state, tag, global_step, checksum=False, extra=None):
    """The manifest body (files are appended at commit time when the full
    payload is on disk)."""
    body = {"version": MANIFEST_VERSION,
            "tag": str(tag),
            "global_step": int(global_step),
            "created": round(time.time(), 6),
            "leaves": _leaf_entries(state, checksum=checksum),
            "checksum": bool(checksum)}
    if extra:
        body.update(extra)
    return body


def _payload_files(tag_dir):
    """Relative paths + sizes of everything in the tag dir except the
    protocol files themselves."""
    skip = {COMMIT_MARKER, MANIFEST_NAME}
    files = []
    for dirpath, _, filenames in os.walk(tag_dir):
        for fn in filenames:
            rel = os.path.relpath(os.path.join(dirpath, fn), tag_dir)
            if rel in skip:
                continue
            files.append({"path": rel,
                          "bytes": os.path.getsize(
                              os.path.join(dirpath, fn))})
    files.sort(key=lambda f: f["path"])
    return files


class CheckpointTransaction:
    """Write-to-tmp → fsync → marker → atomic-rename for one tag.

    All writers (orbax engine, ZeRO-Offload host shards, param-stream host
    store) target ``tmp_tag`` — a dot-prefixed sibling directory invisible
    to tag scans.  ``commit()`` then:

    1. records the payload file list + sizes into the manifest,
    2. writes ``ds_manifest.json`` (self-digested) and the ``.ds_commit``
       marker carrying that digest,
    3. fsyncs the whole tree,
    4. atomically renames ``.{tag}.tmp`` → ``{tag}``.

    A crash at any point leaves either the previous committed tag intact or
    an ignorable tmp dir — never a half-visible checkpoint.  On multi-host,
    every process writes its shards into the shared tmp dir; only the
    coordinator performs steps 1–4, bracketed by ``barrier_fn``.
    """

    def __init__(self, save_dir, tag, is_coordinator=True, barrier_fn=None,
                 fsync=True):
        self.save_dir = os.path.abspath(save_dir)
        self.tag = str(tag)
        self.tmp_tag = f".{self.tag}{TMP_SUFFIX}"
        self.is_coordinator = is_coordinator
        self.barrier_fn = barrier_fn
        self.fsync = fsync

    @property
    def tmp_path(self):
        return os.path.join(self.save_dir, self.tmp_tag)

    @property
    def final_path(self):
        return os.path.join(self.save_dir, self.tag)

    def begin(self):
        """Clear any stale tmp dir from a previous crashed/failed attempt
        and create a fresh one."""
        if self.is_coordinator:
            if os.path.isdir(self.tmp_path):
                shutil.rmtree(self.tmp_path, ignore_errors=True)
            os.makedirs(self.tmp_path, exist_ok=True)
        if self.barrier_fn is not None:
            self.barrier_fn()
        return self

    def commit(self, manifest):
        """Publish the tmp dir as ``tag``.  ``manifest`` is the body from
        :func:`build_manifest`; the payload file list is appended here."""
        if self.barrier_fn is not None:
            self.barrier_fn()  # every process finished writing its shards
        if self.is_coordinator:
            manifest = dict(manifest)
            manifest["files"] = _payload_files(self.tmp_path)
            manifest["digest"] = _manifest_digest(manifest)
            with open(os.path.join(self.tmp_path, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            with open(os.path.join(self.tmp_path, COMMIT_MARKER), "w") as f:
                f.write(manifest["digest"])
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            if self.fsync:
                fsync_tree(self.tmp_path)
            # replacing an existing tag: move it aside first (os.replace
            # cannot atomically swap non-empty directories)
            if os.path.isdir(self.final_path):
                old = f"{self.final_path}.replaced.{os.getpid()}"
                os.replace(self.final_path, old)
                shutil.rmtree(old, ignore_errors=True)
            os.replace(self.tmp_path, self.final_path)
            if self.fsync:
                try:
                    _fsync_path(self.save_dir)
                except OSError:
                    pass
        if self.barrier_fn is not None:
            self.barrier_fn()  # commit visible everywhere before returning
        return self.final_path

    def abort(self):
        """Remove the tmp dir (between retries / on final failure)."""
        if self.is_coordinator and os.path.isdir(self.tmp_path):
            shutil.rmtree(self.tmp_path, ignore_errors=True)


def validate_tag(tag_dir):
    """Classify one tag directory.  Returns ``(status, manifest_or_None)``
    — :data:`COMMITTED` means marker and manifest agree and every
    manifest-listed payload file exists at its recorded size."""
    if not os.path.isdir(tag_dir):
        return MISSING, None
    marker_path = os.path.join(tag_dir, COMMIT_MARKER)
    manifest_path = os.path.join(tag_dir, MANIFEST_NAME)
    has_marker = os.path.exists(marker_path)
    has_manifest = os.path.exists(manifest_path)
    if not has_marker and not has_manifest:
        return LEGACY, None
    if not has_manifest:
        return BAD_MANIFEST, None
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        digest = _manifest_digest(manifest)
        if manifest.get("digest") != digest:
            return BAD_MANIFEST, None
    except (ValueError, OSError):
        return BAD_MANIFEST, None
    if not has_marker:
        return NO_MARKER, manifest
    try:
        with open(marker_path) as f:
            marker_digest = f.read().strip()
    except OSError:
        return NO_MARKER, manifest
    if marker_digest != manifest.get("digest"):
        return NO_MARKER, manifest
    for rec in manifest.get("files", []):
        p = os.path.join(tag_dir, rec["path"])
        if not os.path.exists(p) or os.path.getsize(p) != rec["bytes"]:
            return PARTIAL, manifest
    return COMMITTED, manifest


def scan_tags(root):
    """All non-tmp tag dirs under ``root`` with their validation status:
    ``[(tag, status, manifest)]`` sorted newest-first (manifest
    ``global_step`` desc, then mtime desc)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        path = os.path.join(root, name)
        if name.startswith(".") or not os.path.isdir(path):
            continue
        status, manifest = validate_tag(path)
        out.append((name, status, manifest))

    def key(item):
        _, _, manifest = item
        step = (manifest or {}).get("global_step", -1)
        try:
            mtime = os.path.getmtime(os.path.join(root, item[0]))
        except OSError:
            mtime = 0.0
        return (step, mtime)
    out.sort(key=key, reverse=True)
    return out


def verify_restored(state, manifest):
    """Per-leaf checksum verification of a *restored* state against the
    manifest (only when the manifest carries checksums).  Raises
    :class:`CheckpointCorruptError` on the first mismatch."""
    if not manifest or not manifest.get("checksum"):
        return True
    import jax
    leaves, _ = jax.tree_util.tree_flatten_with_path(state)
    by_path = {r["path"]: r for r in manifest.get("leaves", [])}
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path)
        rec = by_path.get(key)
        if rec is None or "crc32" not in rec:
            continue
        got = leaf_crc32(leaf)
        if got != rec["crc32"]:
            raise CheckpointCorruptError(
                f"leaf {key}: checksum mismatch (manifest "
                f"{rec['crc32']:#010x}, restored {got:#010x})")
    return True


def gc_tags(root, keep_last, protect=(), telemetry=None):
    """Retention: keep the newest ``keep_last`` COMMITTED tags, delete the
    rest (plus stale tmp dirs).  Non-committed tags are never deleted —
    they are evidence, and ``ds_ckpt_fsck`` reports them.  Tags in
    ``protect`` are always kept."""
    if keep_last <= 0:
        return []
    removed = []
    committed = [t for t, s, _ in scan_tags(root) if s == COMMITTED]
    for tag in committed[keep_last:]:
        if tag in protect:
            continue
        shutil.rmtree(os.path.join(root, tag), ignore_errors=True)
        removed.append(tag)
        logger.info(f"checkpoint GC: removed {tag} (keep_last={keep_last})")
    for name in os.listdir(root):
        if name.startswith(".") and name.endswith(TMP_SUFFIX):
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)
    if removed and telemetry is not None:
        telemetry.emit("meta", "ckpt/gc",
                       attrs={"removed": removed, "keep_last": keep_last})
    return removed


# ----------------------------------------------------------------------
# preemption handling
# ----------------------------------------------------------------------
class PreemptionHandler:
    """SIGTERM/SIGINT → a flag the engine polls at step boundaries.

    The signal handler itself does the minimum legal work (set a flag, log,
    emit ``fault/preempt_requested``); the engine then writes an emergency
    checkpoint at the next boundary and drains its worker threads.  A
    second signal restores the original handlers and re-raises — an
    operator double-Ctrl-C still kills the process immediately.
    """

    def __init__(self, telemetry=None, signals=(signal.SIGTERM,
                                                signal.SIGINT)):
        self.telemetry = telemetry
        self.signals = tuple(signals)
        self._requested = threading.Event()
        self._prev = {}
        self._installed = False

    def install(self):
        try:
            for sig in self.signals:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            self._installed = True
        except ValueError:
            # signal.signal only works in the main thread — degrade to
            # manual request() (tests, embedded runtimes)
            logger.warning("preemption handler: not in main thread; "
                           "signals not hooked (manual request() only)")
        return self

    def uninstall(self):
        if self._installed:
            for sig, prev in self._prev.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, TypeError):
                    pass
            self._prev = {}
            self._installed = False

    def _on_signal(self, signum, frame):
        if self._requested.is_set():
            # second signal: get out of the way and re-deliver
            self.uninstall()
            signal.raise_signal(signum)
            return
        self.request(signum=signum)

    def request(self, signum=None):
        """Flag a preemption (signal handler or tests)."""
        self._requested.set()
        logger.warning(
            f"preemption requested (signal={signum}); emergency checkpoint "
            "at the next step boundary")
        if self.telemetry is not None:
            self.telemetry.fault(
                "fault/preempt_requested",
                attrs={"signal": int(signum) if signum is not None else None})

    @property
    def requested(self):
        return self._requested.is_set()

    def clear(self):
        self._requested.clear()


# ----------------------------------------------------------------------
# divergence sentinel
# ----------------------------------------------------------------------
class DivergenceSentinel:
    """Non-finite fp32 loss or K consecutive fp16 overflow-skips → trip.

    The engine ``push()``es each step's loss / overflow as *device* scalars
    (no sync); every ``interval`` pushes the sentinel fetches the pending
    batch with one ``device_get`` and evaluates.  ``poll()`` returns the
    configured action (``"halt"`` / ``"restore"``) once per trip; the
    engine acts on its own thread at the step boundary.
    """

    def __init__(self, max_consecutive_skips=0, check_nonfinite=True,
                 interval=1, action="halt", telemetry=None):
        self.max_consecutive_skips = int(max_consecutive_skips)
        self.check_nonfinite = bool(check_nonfinite)
        self.interval = max(1, int(interval))
        self.action = action
        self.telemetry = telemetry
        self._pending = []      # [(step, loss_or_None, overflow_or_None)]
        self._skip_streak = 0
        self.tripped = False
        self.reason = None
        self.trip_step = None
        self._delivered = False

    def push(self, step, loss=None, overflow=None):
        if self.tripped:
            return
        self._pending.append((int(step), loss, overflow))

    def _evaluate(self, step, loss_f, overflow_b):
        if overflow_b is not None and self.max_consecutive_skips > 0:
            self._skip_streak = self._skip_streak + 1 if overflow_b else 0
            if self._skip_streak >= self.max_consecutive_skips:
                self._trip(step, "overflow_streak",
                           {"consecutive_skips": self._skip_streak})
                return
        if self.check_nonfinite and loss_f is not None and \
                not np.isfinite(loss_f):
            self._trip(step, "nonfinite_loss", {"loss": repr(loss_f)})

    def _trip(self, step, reason, attrs):
        self.tripped = True
        self.reason = reason
        self.trip_step = int(step)
        logger.error(f"divergence sentinel tripped at step {step}: {reason} "
                     f"{attrs} (action={self.action})")
        if self.telemetry is not None:
            self.telemetry.fault(
                "fault/divergence", step=int(step),
                attrs=dict(attrs, reason=reason, action=self.action))

    def poll(self, force=False):
        """Fetch + evaluate pending observations when due.  Returns the
        action string exactly once after a trip, else None."""
        if not self.tripped and self._pending and \
                (force or len(self._pending) >= self.interval):
            batch, self._pending = self._pending, []
            import jax
            refs = [v for _, loss, ovf in batch for v in (loss, ovf)
                    if v is not None]
            host = iter(jax.device_get(refs)) if refs else iter(())
            for step, loss, ovf in batch:
                loss_f = float(next(host)) if loss is not None else None
                ovf_b = bool(next(host)) if ovf is not None else None
                self._evaluate(step, loss_f, ovf_b)
                if self.tripped:
                    break
        if self.tripped and not self._delivered:
            self._delivered = True
            return self.action
        return None

    def reset(self):
        """Re-arm after a successful auto-restore."""
        self._pending = []
        self._skip_streak = 0
        self.tripped = False
        self.reason = None
        self.trip_step = None
        self._delivered = False
