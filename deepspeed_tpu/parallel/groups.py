"""Global parallel-group state.

Parity: reference ``deepspeed/utils/groups.py`` (``_create_expert_and_data_parallel``
:109, ``_get_data_parallel_group`` etc.).  Where the reference stores NCCL
``ProcessGroup`` handles, we store the active ``jax.sharding.Mesh`` and answer
the same questions (world size / rank along each parallel dimension) from mesh
axis sizes and ``jax.process_index``.
"""

import threading
from typing import Optional

from deepspeed_tpu.parallel.topology import (
    BATCH_AXES, DP_AXIS, EP_AXIS, FSDP_AXIS, MESH_AXES, PP_AXIS, SP_AXIS,
    TP_AXIS, TopologyConfig, build_mesh,
)

_lock = threading.Lock()
_mesh = None
_topology_config: Optional[TopologyConfig] = None


def initialize_mesh(topo: Optional[TopologyConfig] = None, devices=None, mesh=None):
    """Install the process-wide mesh.  Called from ``initialize()``; tests may
    install their own mesh directly."""
    global _mesh, _topology_config
    with _lock:
        if mesh is not None:
            _mesh = mesh
        else:
            _mesh = build_mesh(topo, devices=devices)
        _topology_config = topo or TopologyConfig()
    return _mesh


def get_mesh():
    global _mesh
    if _mesh is None:
        initialize_mesh()
    return _mesh


def mesh_is_initialized():
    return _mesh is not None


def reset_mesh():
    global _mesh, _topology_config
    with _lock:
        _mesh = None
        _topology_config = None


def _axis_size(axis) -> int:
    mesh = get_mesh()
    if isinstance(axis, (tuple, list)):
        n = 1
        for a in axis:
            n *= _axis_size(a)
        return n
    return mesh.shape.get(axis, 1)


# ------------------------------------------------------------------
# Parity accessors (reference groups.py names, minus torch groups)
# ------------------------------------------------------------------
def get_data_parallel_world_size() -> int:
    """Effective DP degree = product of every axis a batch is sharded over."""
    return _axis_size(list(BATCH_AXES))


def get_partition_world_size() -> int:
    """ZeRO partition degree (the fsdp axis)."""
    return _axis_size(FSDP_AXIS)


def get_model_parallel_world_size() -> int:
    return _axis_size(TP_AXIS)


def get_pipe_parallel_world_size() -> int:
    return _axis_size(PP_AXIS)


def get_sequence_parallel_world_size() -> int:
    return _axis_size(SP_AXIS)


def get_expert_parallel_world_size() -> int:
    return _axis_size(EP_AXIS)


def get_expert_data_parallel_world_size() -> int:
    """DP degree *within* an expert group (reference
    ``_create_expert_and_data_parallel``: expert-data-parallel =
    dp_world / ep_size)."""
    return _axis_size([DP_AXIS, FSDP_AXIS])


def get_world_size() -> int:
    mesh = get_mesh()
    return mesh.devices.size


def get_data_parallel_rank() -> int:
    import jax
    return jax.process_index()


def get_model_parallel_rank() -> int:
    """This process's coordinate on the tp axis (0 when tp fits inside one
    process, which is always true single-host — SPMD programs see tp ranks
    as mesh coordinates, not processes)."""
    import jax
    mesh = get_mesh()
    tp = mesh.shape.get(TP_AXIS, 1)
    if tp <= 1 or jax.process_count() == 1:
        return 0
    # multi-host: processes are laid out in mesh order; derive the tp
    # coordinate of this process's first local device
    dev = jax.local_devices()[0]
    idx = int(list(mesh.devices.flat).index(dev))
    axes = list(mesh.shape.keys())
    sizes = [mesh.shape[a] for a in axes]
    coord = {}
    for a, s in zip(reversed(axes), reversed(sizes)):
        coord[a] = idx % s
        idx //= s
    return coord.get(TP_AXIS, 0)
