from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import (
    DP_AXIS, FSDP_AXIS, MESH_AXES, PP_AXIS, SP_AXIS, TP_AXIS,
    ProcessTopology, PipeDataParallelTopology, TopologyConfig, build_mesh)
