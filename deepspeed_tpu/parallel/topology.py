"""Process/device topology — named mesh axes over ICI/DCN.

TPU-native re-design of the reference's ``runtime/pipe/topology.py:9``
(``ProcessTopology``/``PipeDataParallelTopology``) and the process-group
bookkeeping in ``deepspeed/utils/groups.py``.  Where the reference builds
NCCL process groups per parallel dimension, we build ONE
``jax.sharding.Mesh`` whose named axes are the parallel dimensions; XLA
lowers collectives over an axis to ICI (intra-slice) or DCN (inter-slice)
automatically when the mesh is constructed from
``mesh_utils.create_device_mesh`` / ``create_hybrid_device_mesh``.

Canonical axis order (outermost → innermost, slowest → fastest wire):

    pp   pipeline stages        (point-to-point ppermute traffic)
    dp   pure data parallel     (gradient all-reduce; rides DCN across slices)
    fsdp ZeRO partition axis    (all-gather / reduce-scatter; wants ICI)
    ep   expert parallel        (MoE all-to-all dispatch/combine)
    sp   sequence/context       (all-to-all / ring ppermute)
    tp   tensor parallel        (all-reduce per layer; innermost = fastest ICI)

EP overlays DP exactly like the reference (``groups.py:109``: expert-parallel
ranks are data-parallel ranks): the ``ep`` axis carries batch shards too, so
``dp_world = dp × fsdp × ep`` and experts are sharded over ``ep``.
"""

import collections
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

PP_AXIS = "pp"
DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
EP_AXIS = "ep"
SP_AXIS = "sp"
TP_AXIS = "tp"

# The order matters: innermost axes get the fastest ICI links when the mesh
# comes from mesh_utils.create_device_mesh.
MESH_AXES = (PP_AXIS, DP_AXIS, FSDP_AXIS, EP_AXIS, SP_AXIS, TP_AXIS)

# Axes over which a data batch is sharded (each contributes to the
# effective data-parallel world size).
BATCH_AXES = (DP_AXIS, FSDP_AXIS, EP_AXIS)


@dataclass
class TopologyConfig:
    """Degrees of each parallel dimension.  -1 for fsdp means "absorb all
    remaining devices" (the common ZeRO default: DP world == partition world).
    """
    pp: int = 1
    dp: int = 1
    fsdp: int = -1
    ep: int = 1   # expert parallel degree (own mesh axis; overlays DP)
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> "TopologyConfig":
        known = self.pp * self.dp * self.ep * self.sp * self.tp
        fsdp = self.fsdp
        if fsdp == -1:
            assert n_devices % known == 0, \
                f"device count {n_devices} not divisible by pp*dp*ep*sp*tp={known}"
            fsdp = n_devices // known
        total = known * fsdp
        assert total == n_devices, \
            f"topology {self} needs {total} devices, have {n_devices}"
        return TopologyConfig(pp=self.pp, dp=self.dp, fsdp=fsdp, ep=self.ep,
                              sp=self.sp, tp=self.tp)


class ProcessTopology:
    """Cartesian coordinate math over named axes.

    API parity with reference ``topology.py:9`` (``get_rank``, ``get_coord``,
    ``get_axis_comm_lists``, ``filter_match``) so grid-walking code ports
    directly; the difference is that ranks index *devices in the mesh*, not
    OS processes.
    """

    def __init__(self, axes: List[str], dims: List[int]):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = collections.namedtuple("ProcessCoord", axes)
        self.mapping = {}
        ranges = [range(d) for d in dims]
        import itertools
        for global_rank, coord in enumerate(itertools.product(*ranges)):
            key = dict(zip(axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs) -> int:
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"coord {coord_kwargs} not in topology"
        return self.mapping[key]

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("dp", "pp"), inner_sep="_", outer_sep="-"):
        omit_axes = list(omit_axes)
        axes = [a for a in self.get_axis_names() if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank: int):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that communicate along ``axis`` (all other coords
        equal).  Parity: reference ``topology.py`` same-named method."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        import itertools
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for other in itertools.product(*ranges):
            fixed = dict(zip(other_axes, other))
            ranks = [self.get_rank(**{axis: i, **fixed})
                     for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        def _match(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())
        return sorted(idx for coord, idx in self.mapping.items() if _match(coord))

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return self.filter_match(**{axis: idx})

    def world_size(self) -> int:
        return int(np.prod(self.dims))

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """Parity shim for reference ``topology.py`` 3D grid (pipe × data × model)."""

    def __init__(self, num_pp, num_dp, num_mp=1):
        if num_mp > 1:
            super().__init__(axes=["pipe", "data", "model"],
                             dims=[num_pp, num_dp, num_mp])
        else:
            super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


def build_mesh(topo: Optional[TopologyConfig] = None, devices=None):
    """Create a ``jax.sharding.Mesh`` with the canonical named axes.

    Uses ``mesh_utils.create_device_mesh`` so axis order maps onto physical
    ICI topology (innermost axis ↔ nearest neighbours); falls back to a plain
    reshape for virtual/CPU device sets where topology discovery fails.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    topo = (topo or TopologyConfig()).resolve(len(devices))
    shape = (topo.pp, topo.dp, topo.fsdp, topo.ep, topo.sp, topo.tp)
    try:
        from jax.experimental import mesh_utils
        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXES)


def single_device_mesh(device=None):
    import jax
    from jax.sharding import Mesh
    if device is None:
        device = jax.devices()[0]
    return Mesh(np.asarray([device]).reshape((1,) * len(MESH_AXES)), MESH_AXES)
