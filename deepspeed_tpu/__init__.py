"""deepspeed_tpu — a TPU-native training/inference framework with the
capabilities of DeepSpeed (reference: FreyaRao/DeepSpeed 0.8.3), built on
JAX/XLA/Pallas.

Top-level API parity: reference ``deepspeed/__init__.py`` (``initialize:52``,
``init_inference:233``, ``init_distributed``, ``add_config_arguments``).
"""

__version__ = "0.1.0"
__git_hash__ = None
__git_branch__ = None

from deepspeed_tpu.accelerator import get_accelerator, set_accelerator  # noqa: F401
from deepspeed_tpu import comm  # noqa: F401
from deepspeed_tpu.comm.comm import init_distributed  # noqa: F401
from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError  # noqa: F401
from deepspeed_tpu.runtime import zero  # noqa: F401
from deepspeed_tpu.utils.init_on_device import OnDevice  # noqa: F401
from deepspeed_tpu.utils.logging import logger, log_dist  # noqa: F401
from deepspeed_tpu import module_inject, ops  # noqa: F401
from deepspeed_tpu.runtime import DeepSpeedOptimizer, ZeROOptimizer  # noqa: F401
from deepspeed_tpu.runtime.engine import DeepSpeedEngine  # noqa: F401
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine  # noqa: F401
from deepspeed_tpu.inference.engine import InferenceEngine  # noqa: F401
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig  # noqa: F401
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments  # noqa: F401
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing  # noqa: F401
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerLayer,  # noqa: F401
                                           DeepSpeedTransformerConfig)
from deepspeed_tpu.module_inject import (replace_transformer_layer,  # noqa: F401
                                         revert_transformer_layer)


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mesh=None,
               tp_rules=None,
               collate_fn=None,
               config=None,
               config_params=None):
    """Initialise the training engine.

    Parity: reference ``deepspeed/__init__.py:52``.  Differences forced by the
    functional paradigm:

    * ``model`` is a callable ``loss_fn(params, batch, rng) -> loss`` (or an
      object with ``.loss``), not an ``nn.Module``;
    * ``model_parameters`` is the params *pytree* (it is required);
    * ``optimizer`` (optional) is an optax ``GradientTransformation``;
    * ``mesh``/``tp_rules`` configure the device mesh and tensor-parallel
      sharding rules (the reference takes an ``mpu`` object for this).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    """
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    assert model is not None, "deepspeed_tpu.initialize: model is required"
    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config") \
            and args.deepspeed_config is not None:
        config = args.deepspeed_config
    assert config is not None, \
        "DeepSpeed requires --deepspeed_config or the config= argument"

    if not isinstance(config, DeepSpeedConfig):
        config = DeepSpeedConfig(config)

    # PipelineModule models get the pipeline engine — parity:
    # reference deepspeed/__init__.py:124-148
    engine_cls = (PipelineEngine if isinstance(model, PipelineModule)
                  else DeepSpeedEngine)
    engine = engine_cls(
        model=model,
        config=config,
        params=model_parameters,
        optimizer=optimizer,
        lr_scheduler=lr_scheduler,
        mesh=mesh,
        tp_rules=tp_rules,
        collate_fn=collate_fn,
        training_data=training_data)

    return engine, engine.tx, engine.training_dataloader, engine.lr_scheduler


def create_serving_engine(model, params, config=None, overlay_path=None,
                          **kwargs):
    """Build a paged-KV :class:`~deepspeed_tpu.inference.serving
    .ServingEngine` from a ds-style config dict, applying a persisted
    autotuner overlay (``autotuning.overlay_path`` or the explicit
    ``overlay_path``) first — the serving twin of :func:`initialize`'s
    overlay hook."""
    from deepspeed_tpu.inference.serving import create_serving_engine as _f
    return _f(model, params, config=config, overlay_path=overlay_path,
              **kwargs)


def init_inference(model=None, config=None, params=None, mesh=None, **kwargs):
    """Parity: reference ``deepspeed/__init__.py:233``.  Config kwargs
    (``mp_size=2`` etc.) merge into ``config`` like the reference; ``params``
    is the weights pytree (functional-paradigm addition)."""
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine

    cfg_dict = dict(config or {})
    cfg_dict.update(kwargs)
    cfg = DeepSpeedInferenceConfig(cfg_dict)

    # HF torch model → policy-driven conversion (reference
    # replace_transformer_layer kernel injection path)
    from deepspeed_tpu.module_inject import is_hf_model, replace_transformer_layer
    if model is not None and is_hf_model(model):
        model, params = replace_transformer_layer(model)
    return InferenceEngine(model, cfg, params=params, mesh=mesh)


def add_config_arguments(parser):
    """Parity: reference ``deepspeed/__init__.py add_config_arguments``."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag, parity)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed json configuration")
    group.add_argument("--deepscale", default=False, action="store_true")
    group.add_argument("--deepscale_config", default=None, type=str)
    group.add_argument("--deepspeed_mpi", default=False, action="store_true")
    return parser
