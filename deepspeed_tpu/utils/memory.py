"""Memory introspection (reference ``runtime/utils.py``
``see_memory_usage:764`` / ``memory_status`` — the debug API sprinkled
through DeepSpeed training scripts).

TPU flavor: device numbers come from the backend's ``memory_stats()``
(bytes_in_use / peak / limit); host numbers from ``/proc/self/status``
(VmRSS) so there is no psutil dependency.
"""

import os
from typing import Dict

import jax

from deepspeed_tpu.utils.logging import logger


def _host_rss_gb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / (1024 ** 2)  # kB → GB
    except OSError:
        pass
    return 0.0


def memory_status(device=None) -> Dict[str, float]:
    """Device + host memory snapshot in GB (zeros where the backend does
    not report stats, e.g. CPU)."""
    if device is None:
        device = jax.devices()[0]
    stats = {}
    try:
        stats = device.memory_stats() or {}
    except Exception:
        pass
    gb = 1024 ** 3
    return {
        "device_in_use_gb": stats.get("bytes_in_use", 0) / gb,
        "device_peak_gb": stats.get("peak_bytes_in_use", 0) / gb,
        "device_limit_gb": stats.get("bytes_limit", 0) / gb,
        "host_rss_gb": _host_rss_gb(),
    }


def see_memory_usage(message: str, force: bool = False, ranks=(0,)):
    """Log a memory snapshot (reference signature).  ``force=False`` is a
    no-op, matching the reference's opt-in behaviour."""
    if not force:
        return
    if jax.process_index() not in ranks:
        return
    m = memory_status()
    logger.info(
        f"{message} | device {m['device_in_use_gb']:.2f} GB "
        f"(peak {m['device_peak_gb']:.2f}, limit {m['device_limit_gb']:.2f}) "
        f"| host RSS {m['host_rss_gb']:.2f} GB")
    return m
