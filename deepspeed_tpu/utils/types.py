"""Parity: reference ``deepspeed/utils/types.py``."""

from enum import IntEnum


class ActivationFuncType(IntEnum):
    UNKNOWN = 0
    GELU = 1
    ReLU = 2
