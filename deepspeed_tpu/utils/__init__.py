from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.memory import memory_status, see_memory_usage
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from deepspeed_tpu.utils.tensor_fragment import (safe_get_full_fp32_param,
                                                 safe_get_full_grad,
                                                 safe_get_full_optimizer_state)
from deepspeed_tpu.utils.init_on_device import OnDevice
