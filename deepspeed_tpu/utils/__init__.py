from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.memory import memory_status, see_memory_usage
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer
